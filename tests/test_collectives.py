"""Collective correctness over (ranks x payload sizes x dtypes), with
rank-and-index-determined fixtures and closed-form expected values
(reference analog: gloo/test/allreduce_test.cc etc., base_test.h fixtures)."""

import os

import numpy as np
import pytest

from tests.harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SIZES = [1, 2, 3, 4, 8]
COUNTS = [1, 7, 100, 10_000]


def fixture(rank, count, dtype):
    """Deterministic per-rank pattern with exact closed-form reductions."""
    idx = np.arange(count, dtype=np.float64)
    vals = (rank + 1) + (idx % 5)
    return vals.astype(dtype)


@pytest.mark.parametrize("algorithm", ["ring", "halving_doubling", "bcube"])
@pytest.mark.parametrize("size", SIZES)
@pytest.mark.parametrize("count", COUNTS)
def test_allreduce_sum(size, count, algorithm):
    def fn(ctx, rank):
        x = fixture(rank, count, np.float32)
        ctx.allreduce(x, algorithm=algorithm)
        return x

    results = spawn(size, fn)
    expected = sum(fixture(r, count, np.float64) for r in range(size))
    for got in results:
        np.testing.assert_allclose(got, expected, rtol=1e-6)


@pytest.mark.parametrize("algorithm", ["halving_doubling", "bcube"])
@pytest.mark.parametrize("size", [2, 3, 5, 6, 7, 8])
def test_allreduce_hd_nonpow2(size, algorithm):
    """Non-power-of-2 groups: HD binary-blocks path and mixed-radix bcube."""
    count = 4097  # also exercises uneven block windows

    def fn(ctx, rank):
        x = fixture(rank, count, np.float64)
        ctx.allreduce(x, algorithm=algorithm)
        return x

    results = spawn(size, fn)
    expected = sum(fixture(r, count, np.float64) for r in range(size))
    for got in results:
        np.testing.assert_allclose(got, expected, rtol=1e-12)


@pytest.mark.parametrize("variant", ["blocks", "fold"])
@pytest.mark.parametrize("size,count", [
    (3, 1), (5, 3), (6, 4097), (7, 911), (12, 4097), (12, 5),
])
def test_allreduce_hd_np2_variants(size, count, variant, monkeypatch):
    """Both non-power-of-2 HD strategies, incl. tiny counts where some
    block windows are empty (zero-byte messages must still match up)."""
    monkeypatch.setenv("TPUCOLL_HD_NP2", variant)

    def fn(ctx, rank):
        x = fixture(rank, count, np.float64)
        ctx.allreduce(x, algorithm="halving_doubling")
        return x

    results = spawn(size, fn)
    expected = sum(fixture(r, count, np.float64) for r in range(size))
    for got in results:
        np.testing.assert_allclose(got, expected, rtol=1e-12)


@pytest.mark.parametrize("dtype,rtol", [
    (np.int32, 0), (np.int64, 0), (np.uint8, 0),
    (np.float64, 1e-12), (np.float16, 1e-2),
])
def test_allreduce_dtypes(dtype, rtol):
    size, count = 4, 523

    def fn(ctx, rank):
        x = fixture(rank, count, dtype)
        ctx.allreduce(x)
        return x

    results = spawn(size, fn)
    expected = sum(fixture(r, count, np.float64) for r in range(size))
    for got in results:
        if rtol == 0:
            np.testing.assert_array_equal(got.astype(np.float64), expected)
        else:
            np.testing.assert_allclose(got.astype(np.float64), expected,
                                       rtol=rtol)


def test_allreduce_bfloat16():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    size, count = 2, 256

    def fn(ctx, rank):
        x = np.full(count, rank + 1, dtype=ml_dtypes.bfloat16)
        ctx.allreduce(x)
        return x.astype(np.float32)

    results = spawn(size, fn)
    for got in results:
        np.testing.assert_array_equal(got, np.full(count, 3.0, np.float32))


@pytest.mark.parametrize("op,reducer", [
    ("min", np.minimum), ("max", np.maximum), ("product", np.multiply),
])
def test_allreduce_ops(op, reducer):
    size, count = 3, 97

    def fn(ctx, rank):
        x = fixture(rank, count, np.float32)
        ctx.allreduce(x, op=op)
        return x

    results = spawn(size, fn)
    expected = fixture(0, count, np.float32)
    for r in range(1, size):
        expected = reducer(expected, fixture(r, count, np.float32))
    for got in results:
        np.testing.assert_allclose(got, expected, rtol=1e-6)


@pytest.mark.parametrize("size", SIZES)
def test_broadcast(size):
    count = 1000

    def fn(ctx, rank):
        root = size // 2
        if rank == root:
            x = fixture(root, count, np.float32)
        else:
            x = np.zeros(count, dtype=np.float32)
        ctx.broadcast(x, root=root)
        return x

    results = spawn(size, fn)
    expected = fixture(size // 2, count, np.float32)
    for got in results:
        np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("size", SIZES)
def test_reduce(size):
    count = 1234

    def fn(ctx, rank):
        x = fixture(rank, count, np.float64)
        out = ctx.reduce(x, root=0)
        return out

    results = spawn(size, fn)
    expected = sum(fixture(r, count, np.float64) for r in range(size))
    np.testing.assert_allclose(results[0], expected, rtol=1e-12)
    assert all(r is None for r in results[1:])


@pytest.mark.parametrize("algorithm", ["binomial", "ring"])
@pytest.mark.parametrize("size", SIZES)
def test_reduce_algorithms(size, algorithm):
    """Both reduce schedules, non-zero root, counts exercising the ring's
    uneven trailing block (count % size != 0) and the sub-size payload
    (count < size, some ranks own empty blocks)."""
    for count, root in ((1234, min(1, size - 1)), (size - 1, 0),
                        (8192 + 3, size - 1)):

        def fn(ctx, rank, count=count, root=root):
            x = fixture(rank, count, np.float32)
            return ctx.reduce(x, root=root, algorithm=algorithm)

        results = spawn(size, fn)
        expected = sum(fixture(r, count, np.float64)
                       for r in range(size)).astype(np.float32)
        np.testing.assert_allclose(results[root], expected, rtol=1e-5)
        assert all(r is None for i, r in enumerate(results) if i != root)


@pytest.mark.parametrize("size", SIZES)
def test_gather(size):
    def fn(ctx, rank):
        x = fixture(rank, 17, np.float32)
        return ctx.gather(x, root=0)

    results = spawn(size, fn)
    for r in range(size):
        np.testing.assert_array_equal(results[0][r],
                                      fixture(r, 17, np.float32))


def test_gatherv():
    size = 4
    counts = [3, 0, 5, 2]

    def fn(ctx, rank):
        x = np.full(counts[rank], float(rank), dtype=np.float32)
        return ctx.gatherv(x, counts, root=1)

    results = spawn(size, fn)
    expected = np.concatenate(
        [np.full(counts[r], float(r), np.float32) for r in range(size)])
    np.testing.assert_array_equal(results[1], expected)


@pytest.mark.parametrize("size", SIZES)
def test_scatter(size):
    def fn(ctx, rank):
        root = 0
        if rank == root:
            data = np.stack([fixture(r, 21, np.float32)
                             for r in range(size)])
            return ctx.scatter(data, root=root)
        return ctx.scatter(None, root=root,
                           output=np.zeros(21, dtype=np.float32))

    results = spawn(size, fn)
    for r in range(size):
        np.testing.assert_array_equal(results[r],
                                      fixture(r, 21, np.float32))


@pytest.mark.parametrize("size", SIZES)
@pytest.mark.parametrize("count", [1, 64, 5000])
def test_allgather(size, count):
    def fn(ctx, rank):
        return ctx.allgather(fixture(rank, count, np.float32))

    results = spawn(size, fn)
    expected = np.stack([fixture(r, count, np.float32)
                         for r in range(size)])
    for got in results:
        np.testing.assert_array_equal(got, expected)


def test_allgatherv():
    size = 4
    counts = [2, 5, 0, 3]

    def fn(ctx, rank):
        x = np.full(counts[rank], float(rank + 1), dtype=np.float64)
        return ctx.allgatherv(x, counts)

    results = spawn(size, fn)
    expected = np.concatenate(
        [np.full(counts[r], float(r + 1), np.float64) for r in range(size)])
    for got in results:
        np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("size", SIZES)
def test_alltoall(size):
    count = 13

    def fn(ctx, rank):
        # Row j carries "rank -> j" markers.
        x = np.stack([np.full(count, rank * 100 + j, dtype=np.int32)
                      for j in range(size)])
        return ctx.alltoall(x)

    results = spawn(size, fn)
    for r, got in enumerate(results):
        for j in range(size):
            np.testing.assert_array_equal(
                got[j], np.full(count, j * 100 + r, dtype=np.int32))


def test_alltoallv():
    size = 3
    # in_counts[i][j]: rank i sends that many elements to rank j.
    in_counts = [[1, 2, 3], [4, 0, 1], [2, 2, 2]]

    def fn(ctx, rank):
        my_in = in_counts[rank]
        out_counts = [in_counts[j][rank] for j in range(size)]
        x = np.concatenate(
            [np.full(my_in[j], rank * 10 + j, dtype=np.int64)
             for j in range(size)])
        return ctx.alltoallv(x, my_in, out_counts)

    results = spawn(size, fn)
    for r, got in enumerate(results):
        expected = np.concatenate(
            [np.full(in_counts[j][r], j * 10 + r, dtype=np.int64)
             for j in range(size)])
        np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("size", SIZES)
def test_reduce_scatter(size):
    count_per_rank = 9

    def fn(ctx, rank):
        x = fixture(rank, count_per_rank * size, np.float32)
        return ctx.reduce_scatter(x)

    results = spawn(size, fn)
    full = sum(fixture(r, count_per_rank * size, np.float64)
               for r in range(size))
    for r in range(size):
        np.testing.assert_allclose(
            results[r].astype(np.float64),
            full[r * count_per_rank:(r + 1) * count_per_rank], rtol=1e-6)


@pytest.mark.parametrize("size", [2, 3, 4, 6, 8])
@pytest.mark.parametrize("algorithm", ["ring", "hd", "direct"])
def test_reduce_scatter_algorithms(size, algorithm):
    """Both RS schedules: even counts, uneven counts (incl. empty
    blocks), and a count smaller than the group."""
    cases = [[7] * size,
             [(3 * i) % 5 for i in range(size)],
             [1 if i == size - 1 else 0 for i in range(size)]]
    for recv_counts in cases:
        total = sum(recv_counts)

        def fn(ctx, rank, recv_counts=recv_counts, total=total):
            x = fixture(rank, total, np.float32)
            return ctx.reduce_scatter(x, recv_counts=recv_counts,
                                      algorithm=algorithm)

        results = spawn(size, fn)
        full = sum(fixture(r, total, np.float64) for r in range(size))
        off = 0
        for r in range(size):
            np.testing.assert_allclose(
                results[r].astype(np.float64),
                full[off:off + recv_counts[r]], rtol=1e-6)
            off += recv_counts[r]


def test_reduce_scatter_uneven():
    size = 3
    recv_counts = [4, 0, 7]
    total = sum(recv_counts)

    def fn(ctx, rank):
        x = fixture(rank, total, np.float32)
        return ctx.reduce_scatter(x, recv_counts=recv_counts)

    results = spawn(size, fn)
    full = sum(fixture(r, total, np.float64) for r in range(size))
    offset = 0
    for r in range(size):
        np.testing.assert_allclose(
            results[r].astype(np.float64),
            full[offset:offset + recv_counts[r]], rtol=1e-6)
        offset += recv_counts[r]


def test_allreduce_segment_boundary_mismatch():
    """Blocks straddling the 4 MiB segment boundary give adjacent ring
    blocks different segment counts; the send-drain accounting must follow
    the send block's segmentation (regression test)."""
    size, count = 2, 2 * 1024 * 1024 + 1  # blocks: 4MiB+4B vs 4MiB

    def fn(ctx, rank):
        x = np.full(count, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        return float(x[0]), float(x[-1])

    results = spawn(size, fn, timeout=60)
    for a, b in results:
        assert (a, b) == (3.0, 3.0)


@pytest.mark.parametrize("size", SIZES)
def test_barrier(size):
    import time

    def fn(ctx, rank):
        # Stagger arrival; everyone must leave after the last arrival.
        time.sleep(0.02 * rank)
        t_before = time.monotonic()
        ctx.barrier()
        return t_before, time.monotonic()

    results = spawn(size, fn)
    last_arrival = max(t0 for t0, _ in results)
    for _, t_exit in results:
        assert t_exit >= last_arrival - 0.005


def test_concurrent_collectives_distinct_tags():
    """Two allreduces interleaved on one context must not cross-match."""
    size = 4

    def fn(ctx, rank):
        import threading
        a = np.full(1000, float(rank), dtype=np.float32)
        b = np.full(1000, float(rank * 2), dtype=np.float32)
        t = threading.Thread(target=lambda: ctx.allreduce(b, tag=2))
        t.start()
        ctx.allreduce(a, tag=1)
        t.join()
        return float(a[0]), float(b[0])

    results = spawn(size, fn)
    sa = sum(range(size))
    sb = sum(2 * r for r in range(size))
    for a0, b0 in results:
        assert (a0, b0) == (sa, sb)


def test_multiple_contexts_same_device():
    """Independent groups over one shared store namespace must isolate."""
    import gloo_tpu

    base = gloo_tpu.HashStore()
    import threading
    size = 3
    results = [None] * (2 * size)
    errors = []

    def worker(group, rank):
        try:
            dev = gloo_tpu.Device()
            store = gloo_tpu.PrefixStore(base, f"group{group}")
            ctx = gloo_tpu.Context(rank, size, timeout=15)
            ctx.connect_full_mesh(store, dev)
            x = np.full(10, float(rank + group * 10), dtype=np.float32)
            ctx.allreduce(x)
            results[group * size + rank] = float(x[0])
            ctx.close()
        except BaseException as exc:  # noqa: BLE001
            errors.append((group, rank, exc))

    threads = [threading.Thread(target=worker, args=(g, r))
               for g in range(2) for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors
    assert results[:size] == [sum(range(size))] * size
    expected_g1 = sum(r + 10 for r in range(size))
    assert results[size:] == [expected_g1] * size


def test_context_fork():
    """ContextFactory parity: re-bootstrap over an existing context with no
    store traffic; parent and child communicators are independent."""
    size = 4

    def fn(ctx, rank):
        child = ctx.fork()
        a = np.full(64, float(rank + 1), dtype=np.float32)
        b = np.full(64, float(rank + 1) * 2, dtype=np.float32)
        # Interleave collectives on both contexts.
        ctx.allreduce(a)
        child.allreduce(b)
        child.barrier()
        child.close()
        return float(a[0]), float(b[0])

    results = spawn(size, fn)
    sa = size * (size + 1) / 2
    for a0, b0 in results:
        assert (a0, b0) == (sa, 2 * sa)


def test_allreduce_bf16_wire():
    """bf16 wire compression: fp32 accumulate, half the wire bytes, all
    ranks bit-identical, error within bf16 rounding of the true sum."""
    size, count = 4, 10_000

    def fn(ctx, rank):
        x = fixture(rank, count, np.float32)
        ctx.allreduce(x, algorithm="ring_bf16_wire")
        return x

    results = spawn(size, fn)
    expected = sum(fixture(r, count, np.float64) for r in range(size))
    for got in results:
        # Per-hop requantization: allow a few bf16 ulps (~0.8% rel).
        np.testing.assert_allclose(got, expected, rtol=3e-2)
    for got in results[1:]:
        np.testing.assert_array_equal(got, results[0])  # consensus


def test_allreduce_multi_input():
    """Multi-buffer allreduce: N local buffers reduced together, result in
    every buffer (the reference's one-process-N-accelerators form)."""
    size = 3

    def fn(ctx, rank):
        a = np.full(100, float(rank + 1), dtype=np.float32)
        b = np.full(100, float(10 * (rank + 1)), dtype=np.float32)
        ctx.allreduce_multi([a, b])
        return float(a[0]), float(b[0])

    results = spawn(size, fn)
    expected = sum((r + 1) + 10 * (r + 1) for r in range(size))
    for a0, b0 in results:
        assert a0 == expected and b0 == expected


def test_runaway_sender_bounded_by_backpressure():
    """Back-to-back same-tag collectives let a leaf rank run unboundedly
    ahead of a slow parent; stash backpressure must bound receiver memory
    (regression: the stash once grew to gigabytes) while preserving
    completion."""
    import os

    os.environ["TPUCOLL_MAX_STASH_BYTES"] = str(2 << 20)
    try:
        def fn(ctx, rank):
            x = np.ones(50_000, dtype=np.float32)
            for _ in range(500):
                ctx.reduce(x, root=0)
            return True

        assert all(spawn(4, fn, timeout=120))
    finally:
        del os.environ["TPUCOLL_MAX_STASH_BYTES"]


def test_concurrent_tags_under_backpressure():
    """Two collectives on distinct tags per rank, one racing ahead, with a
    tight stash cap: the paused-source policy must not starve the other
    tag's receives (regression for the pause/starvation interaction)."""
    import os
    import threading as th

    os.environ["TPUCOLL_MAX_STASH_BYTES"] = str(2 << 20)
    try:
        size = 4

        def fn(ctx, rank):
            a_ok = [False]
            b_ok = [False]

            def stream_a():
                x = np.ones(100_000, dtype=np.float32)
                for _ in range(100):
                    ctx.reduce(x, root=0, tag=1)
                a_ok[0] = True

            def stream_b():
                y = np.full(1000, float(rank + 1), dtype=np.float32)
                for _ in range(100):
                    ctx.allreduce(y, tag=2)
                    y[:] = float(rank + 1)
                b_ok[0] = True

            ta, tb = th.Thread(target=stream_a), th.Thread(target=stream_b)
            ta.start(); tb.start()
            ta.join(90); tb.join(90)
            return a_ok[0] and b_ok[0]

        assert all(spawn(size, fn, timeout=120))
    finally:
        del os.environ["TPUCOLL_MAX_STASH_BYTES"]


def test_sixteen_ranks():
    """Scaling smoke: 16 thread-ranks, every allreduce algorithm."""
    size = 16

    def fn(ctx, rank):
        results = []
        for i, algo in enumerate(["ring", "halving_doubling", "bcube",
                                  "rd"]):
            x = np.full(2000, float(rank + 1), dtype=np.float64)
            ctx.allreduce(x, algorithm=algo, tag=i)
            results.append(float(x[0]))
        return results

    expected = size * (size + 1) / 2
    for res in spawn(size, fn, timeout=120, context_timeout=60):
        assert res == [expected] * 4, res


@pytest.mark.parametrize("algorithm", ["ring", "halving_doubling", "bcube"])
def test_allreduce_custom_fn(algorithm):
    """Arbitrary Python reduction callable, every exchange schedule."""
    n, count = 4, 1000

    def custom(acc, inp):
        # max-by-absolute-value: commutative + associative, not one of
        # the builtin ops.
        np.copyto(acc, np.where(np.abs(inp) > np.abs(acc), inp, acc))

    def fn(ctx, rank):
        rng = np.random.RandomState(rank)
        x = rng.randn(count).astype(np.float32)
        ctx.allreduce(x, op=custom, algorithm=algorithm)
        return x

    results = spawn(n, fn)
    alls = np.stack([np.random.RandomState(r).randn(count).astype(np.float32)
                     for r in range(n)])
    expected = np.take_along_axis(
        alls, np.abs(alls).argmax(axis=0)[None], axis=0)[0]
    for got in results:
        np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_reduce_and_reduce_scatter_custom_fn():
    n, count = 3, 90

    def custom(acc, inp):
        np.minimum(acc, inp, out=acc)

    def fn(ctx, rank):
        x = (np.arange(count, dtype=np.float32) + rank * 7) % 13
        r = ctx.reduce(x.copy(), root=1, op=custom)
        rs = ctx.reduce_scatter(x.copy(), op=custom)
        return r, rs

    results = spawn(n, fn)
    alls = np.stack([(np.arange(count, dtype=np.float32) + r * 7) % 13
                     for r in range(n)])
    expected = alls.min(axis=0)
    np.testing.assert_allclose(results[1][0], expected)
    assert results[0][0] is None
    per = count // n
    for r in range(n):
        np.testing.assert_allclose(results[r][1],
                                   expected[r * per:(r + 1) * per])


def test_allreduce_custom_fn_rejects_bf16_wire():
    import gloo_tpu

    def fn(ctx, rank):
        x = np.ones(8, np.float32)
        try:
            ctx.allreduce(x, op=lambda a, b: None,
                          algorithm="ring_bf16_wire")
            return "no error"
        except gloo_tpu.Error as e:
            return str(e)

    for msg in spawn(2, fn):
        assert "incompatible" in msg


def test_allreduce_multi_custom_fn():
    n = 2

    def custom(acc, inp):
        np.maximum(acc, inp, out=acc)

    def fn(ctx, rank):
        a = np.full(16, rank * 2.0, np.float32)
        b = np.full(16, rank * 2.0 + 1, np.float32)
        ctx.allreduce_multi([a, b], op=custom)
        return a, b

    for a, b in spawn(n, fn):
        np.testing.assert_array_equal(a, np.full(16, 3.0, np.float32))
        np.testing.assert_array_equal(b, np.full(16, 3.0, np.float32))


def test_allreduce_custom_fn_raising_callable_surfaces():
    import gloo_tpu

    def bad(acc, inp):
        raise RuntimeError("boom in user fn")

    def fn(ctx, rank):
        x = np.ones(64, np.float32)
        try:
            ctx.allreduce(x, op=bad)
            return "no error"
        except gloo_tpu.Error as e:
            return f"{e} / cause: {e.__cause__}"

    for msg in spawn(2, fn):
        assert "invalid on all ranks" in msg and "boom in user fn" in msg


def test_recv_reduce_disabled_fallback():
    """TPUCOLL_RECV_REDUCE=0 restores the recv-into-scratch schedule; the
    results must be identical to the fused default. The flag is read once
    per process, so the disabled run happens in a child interpreter."""
    import os
    import subprocess
    import sys
    import textwrap

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = textwrap.dedent("""
        import sys
        sys.path.insert(0, {repo!r})
        import numpy as np
        from tests.harness import spawn

        def fn(ctx, rank):
            x = np.arange(70_000, dtype=np.float32) + rank
            ctx.allreduce(x, algorithm="ring")
            y = np.full(4096, float(rank + 1), np.float64)
            out = ctx.reduce_scatter(y, [1024] * 4)
            z = np.full(33, float(rank), np.int32)
            r = ctx.reduce(z, root=1)
            return x, out, r

        results = spawn(4, fn)
        base = sum(np.arange(70_000, dtype=np.float64) + r for r in range(4))
        for x, out, r in results:
            np.testing.assert_allclose(x, base, rtol=1e-6)
            np.testing.assert_array_equal(out, np.full(1024, 10.0))
        np.testing.assert_array_equal(
            results[1][2], np.full(33, 0 + 1 + 2 + 3, np.int32))
        print("FALLBACK-OK")
    """).format(repo=repo)
    env = dict(os.environ, TPUCOLL_RECV_REDUCE="0")
    proc = subprocess.run([sys.executable, "-c", prog], env=env,
                          capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr
    assert "FALLBACK-OK" in proc.stdout


def test_allreduce_bf16_wire_fused_matches_staged():
    """The fused typed receive (decode/accumulate straight from the shm
    ring, with re-compressed forwarding) must be BITWISE identical to the
    staged schedule — the allgather forward relies on bf16->f32->bf16
    being an exact roundtrip, and all ranks must still agree."""
    import os
    import subprocess
    import sys
    import textwrap

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = textwrap.dedent("""
        import sys
        sys.path.insert(0, {repo!r})
        import numpy as np
        from tests.harness import spawn

        def fn(ctx, rank):
            x = ((np.arange(30_001, dtype=np.float32) % 97) * 0.37
                 + rank * 1.13).astype(np.float32)
            ctx.allreduce(x, algorithm="ring_bf16_wire")
            return x

        results = spawn(4, fn)
        for got in results[1:]:
            np.testing.assert_array_equal(got, results[0])  # consensus
        np.save(sys.argv[1], results[0])
    """).format(repo=repo)
    outs = {}
    # The cmake-less fallback build never creates build/; the artifact
    # path must not depend on which build flavor ran.
    os.makedirs(os.path.join(repo, "build"), exist_ok=True)
    for mode in ("auto", "0"):
        out = os.path.join(repo, "build", f"bf16wire_{mode}.npy")
        env = dict(os.environ, TPUCOLL_RECV_REDUCE=mode)
        proc = subprocess.run([sys.executable, "-c", prog, out], env=env,
                              capture_output=True, text=True, timeout=180)
        assert proc.returncode == 0, proc.stderr
        outs[mode] = np.load(out)
        os.unlink(out)
    np.testing.assert_array_equal(outs["auto"], outs["0"])


@pytest.mark.parametrize("force", ["1073741824", "0"])
@pytest.mark.parametrize("size", [2, 3, 4, 5, 8])
def test_alltoall_bruck_and_pairwise_tiers(force, size):
    """Both alltoall tiers against the oracle: the huge crossover
    forces Bruck's log-round schedule at P=3,4,5,8 (non-power-of-2
    included; the tier guard keeps P=2 on pairwise, so that cell is
    extra pairwise coverage), =0 forces the pairwise exchange
    everywhere. Subprocesses: the crossover knob is latched per
    process."""
    import subprocess
    import sys
    import textwrap

    body = textwrap.dedent("""
        import sys
        sys.path.insert(0, {repo!r})
        sys.path.insert(0, {repo!r} + "/tests")
        import numpy as np
        from tests.harness import spawn

        size = {size}

        def fn(ctx, rank):
            counts = [1, 7, 33]
            outs = []
            for c in counts:
                x = np.arange(size * c, dtype=np.int64) + 1000 * rank
                outs.append(ctx.alltoall(x.reshape(size, c)))
            return outs

        results = spawn(size, fn)
        for c_i, c in enumerate([1, 7, 33]):
            for r in range(size):
                got = np.asarray(results[r][c_i]).reshape(size, c)
                for src in range(size):
                    expect = (np.arange(size * c, dtype=np.int64)
                              + 1000 * src).reshape(size, c)[r]
                    assert (got[src] == expect).all(), (r, src, c)
        print("OK")
    """).format(repo=_REPO, size=size)
    env = dict(os.environ, TPUCOLL_ALLTOALL_BRUCK_MAX=force)
    proc = subprocess.run([sys.executable, "-c", body], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0 and "OK" in proc.stdout, (proc.stdout,
                                                          proc.stderr)


@pytest.mark.parametrize("force", ["1073741824", "0"])
@pytest.mark.parametrize("size", [2, 3, 4, 5, 6, 8, 12])
def test_allreduce_recursive_doubling_tier(force, size):
    """Recursive doubling against the oracle (forced via a huge
    TPUCOLL_ALLREDUCE_RD_MAX) and the same workload with the tier
    disabled. Non-power-of-2 sizes exercise the Rabenseifner pre/post
    fold (P=3: one pair + one direct survivor; P=5,6,12: mixed; the
    bitwise-identity assertion covers extras receiving the survivors'
    exact bits). Subprocesses: the knob latches per process."""
    import subprocess
    import sys
    import textwrap

    body = textwrap.dedent("""
        import sys
        sys.path.insert(0, {repo!r})
        sys.path.insert(0, {repo!r} + "/tests")
        import numpy as np
        from tests.harness import spawn

        size = {size}

        def fn(ctx, rank):
            outs = []
            for c in (1, 17, 300):
                x = (np.arange(c, dtype=np.float64) + 1) * (rank + 1)
                ctx.allreduce(x)
                outs.append(x)
            # mixed ops ride the same tier
            m = np.full(5, float(rank), np.float32)
            ctx.allreduce(m, op="max")
            outs.append(m)
            return outs

        results = spawn(size, fn)
        tot = size * (size + 1) / 2
        for r in range(size):
            for c_i, c in enumerate((1, 17, 300)):
                expect = (np.arange(c, dtype=np.float64) + 1) * tot
                np.testing.assert_allclose(results[r][c_i], expect,
                                           rtol=1e-12)
            assert (results[r][3] == size - 1).all()
        # bitwise-identical across ranks (commutative pairwise folds)
        for r in range(1, size):
            assert (results[r][2] == results[0][2]).all()
        print("OK")
    """).format(repo=_REPO, size=size)
    env = dict(os.environ, TPUCOLL_ALLREDUCE_RD_MAX=force)
    proc = subprocess.run([sys.executable, "-c", body], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0 and "OK" in proc.stdout, (proc.stdout,
                                                          proc.stderr)


def test_allreduce_rd_explicit_non_power_of_two():
    """Explicit algorithm="rd" at P=3 runs the pre/post-fold path
    (historically this was rejected; the fold made it exact)."""

    def fn(ctx, rank):
        x = (np.arange(64, dtype=np.float64) + 1) * (rank + 1)
        ctx.allreduce(x, algorithm="rd")
        return x

    results = spawn(3, fn)
    expect = (np.arange(64, dtype=np.float64) + 1) * 6.0
    for r in range(3):
        np.testing.assert_allclose(results[r], expect, rtol=1e-12)
        assert (results[r] == results[0]).all()
