"""Pallas flash attention: correctness vs materialized attention (CPU
interpret mode; real-chip validation rides the graft/TPU checks)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402

from gloo_tpu.ops import flash_attention  # noqa: E402
from gloo_tpu.ops.attention import _reference_attention  # noqa: E402


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    rng = np.random.RandomState(0)
    b, h, t, d = 2, 2, 128, 128  # asymmetric blocks below cover t != block
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    out = np.asarray(flash_attention(q, k, v, causal=causal, block_q=64,
                                     block_k=32, interpret=True))
    s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), np.asarray(k))
    s /= np.sqrt(d)
    if causal:
        s = np.where(np.tril(np.ones((t, t), bool)), s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, np.asarray(v))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_transformer_with_flash_attention():
    """Transformer forward with the flash path matches the default path
    (same weights) within matmul-precision tolerance."""
    from gloo_tpu.models import Transformer, TransformerConfig

    base = TransformerConfig(vocab_size=64, d_model=64, n_heads=2,
                             n_layers=1, d_ff=128, max_seq_len=64,
                             dtype=jnp.float32)
    flash = TransformerConfig(vocab_size=64, d_model=64, n_heads=2,
                              n_layers=1, d_ff=128, max_seq_len=64,
                              dtype=jnp.float32, use_flash_attention=True)
    m0, m1 = Transformer(base), Transformer(flash)
    params = m0.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 64)), jnp.int32)
    # Flash path in interpret mode isn't reachable through the model flag;
    # on CPU, pallas needs interpret — monkeypatch for the comparison.

    orig_platform = jax.devices()[0].platform
    if orig_platform != "tpu":
        import sys

        # The package re-export shadows the submodule attribute; fetch the
        # real module from sys.modules.
        fmod = sys.modules["gloo_tpu.ops.attention"]
        real = fmod.flash_attention

        def interp(*a, **kw):
            kw["interpret"] = True
            return real(*a, **kw)

        fmod.flash_attention = interp
        try:
            y0 = np.asarray(m0.apply(params, tokens))
            y1 = np.asarray(m1.apply(params, tokens))
        finally:
            fmod.flash_attention = real
    else:
        y0 = np.asarray(m0.apply(params, tokens))
        y1 = np.asarray(m1.apply(params, tokens))
    np.testing.assert_allclose(y0, y1, rtol=2e-3, atol=2e-3)


def test_flash_rejects_indivisible_seq():
    import jax.numpy as jnp
    import pytest as _pytest

    q = jnp.zeros((1, 1, 192, 128), jnp.float32)
    with _pytest.raises(ValueError):
        flash_attention(q, q, q, block_q=128, block_k=128, interpret=True)


def test_largest_block_helper():
    from gloo_tpu.ops import largest_block

    assert largest_block(192) == 96
    assert largest_block(128) == 128
    assert largest_block(256) == 128
    assert largest_block(40) == 40


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_q,block_k", [(32, 32), (64, 32), (32, 16)])
def test_flash_attention_trainable(causal, block_q, block_k):
    """Gradients through the dedicated backward kernels match the
    materialized path across causal modes and asymmetric blocks."""
    import sys

    import jax.numpy as jnp

    fmod = sys.modules["gloo_tpu.ops.attention"]
    rng = np.random.RandomState(0)
    b, h, t, d = 1, 2, 64, 128
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    def loss_flash(q, k, v):
        return (fmod.flash_attention(q, k, v, causal=causal,
                                     block_q=block_q, block_k=block_k,
                                     interpret=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (fmod._reference_attention(q, k, v, causal) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("h,h_kv", [(8, 2), (4, 1), (6, 3)])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_gqa(h, h_kv, causal):
    """Grouped-query/multi-query: kv heads shared via index map; grads
    group-summed. Oracle: full attention on repeated kv heads."""
    b, t, d = 2, 32, 32
    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h_kv, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h_kv, t, d), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=causal, block_q=8, block_k=8, interpret=True)))

    def loss_ref(q, k, v):
        kx = jnp.repeat(k, h // h_kv, axis=1)
        vx = jnp.repeat(v, h // h_kv, axis=1)
        return jnp.sum(jnp.sin(_reference_attention(q, kx, vx, causal)))

    out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                          interpret=True)
    ref = _reference_attention(q, jnp.repeat(k, h // h_kv, axis=1),
                               jnp.repeat(v, h // h_kv, axis=1), causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)
    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


def test_flash_attention_gqa_bad_heads():
    q = jnp.zeros((1, 5, 32, 16), jnp.float32)
    k = jnp.zeros((1, 2, 32, 16), jnp.float32)
    with pytest.raises(ValueError, match="multiple of kv heads"):
        flash_attention(q, k, k, block_q=8, block_k=8, interpret=True)


def test_transformer_gqa_config():
    """GQA transformer (einsum path on CPU): trains, and the kv projection
    really shrinks."""
    from gloo_tpu.models import Transformer, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq_len=32,
                            n_kv_heads=2, dtype=jnp.float32)
    m = Transformer(cfg)
    params = m.init(jax.random.PRNGKey(0))
    # wqkv: d_model query + 2 * (d_model/4 * 2) shared kv columns
    assert params["layers"][0]["wqkv"].shape == (64, 64 + 2 * 32)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 32)))
    loss, grads = jax.value_and_grad(m.loss)(params, (toks, toks))
    assert np.isfinite(float(loss))
    for g in jax.tree.leaves(grads):
        assert bool(jnp.all(jnp.isfinite(g)))
    # 3 SGD steps reduce the loss
    p = params
    for _ in range(3):
        _, g = jax.value_and_grad(m.loss)(p, (toks, toks))
        p = jax.tree.map(lambda a, b: a - 0.5 * b, p, g)
    assert float(m.loss(p, (toks, toks))) < float(loss)


def test_transformer_gqa_flash_matches_einsum():
    """Same weights through the GQA flash path and the repeat-based
    einsum fallback: the two head-grouping conventions must agree."""
    import sys

    from gloo_tpu.models import Transformer, TransformerConfig

    kw = dict(vocab_size=64, d_model=64, n_heads=4, n_layers=1, d_ff=128,
              max_seq_len=64, n_kv_heads=2, dtype=jnp.float32)
    m0 = Transformer(TransformerConfig(**kw))
    m1 = Transformer(TransformerConfig(**kw, use_flash_attention=True))
    params = m0.init(jax.random.PRNGKey(2))
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(0, 64, (2, 64)), jnp.int32)

    fmod = sys.modules["gloo_tpu.ops.attention"]
    real = fmod.flash_attention

    def interp(*a, **kwargs):
        kwargs["interpret"] = True
        return real(*a, **kwargs)

    if jax.devices()[0].platform != "tpu":
        fmod.flash_attention = interp
    try:
        y0 = np.asarray(m0.apply(params, tokens))
        y1 = np.asarray(m1.apply(params, tokens))
    finally:
        fmod.flash_attention = real
    np.testing.assert_allclose(y0, y1, rtol=2e-3, atol=2e-3)


def test_transformer_gqa_bad_config():
    from gloo_tpu.models import Transformer, TransformerConfig

    for bad in (0, 3):
        cfg = TransformerConfig(n_heads=4, n_kv_heads=bad)
        with pytest.raises(ValueError, match="positive multiple"):
            Transformer(cfg).init(jax.random.PRNGKey(0))


@pytest.mark.parametrize("t,bq", [(512, 512), (1024, 512)])
def test_flash_large_square_tiles_match(t, bq):
    """Causal parity at the production tile shapes (square 512+ tiles,
    including t == bq: the whole sequence in one diagonal tile — the
    short-sequence serving configuration). Guards the diagonal-tile
    masked path at realistic tile sizes; r4 note: a strip-mined
    diagonal-tile variant was measured 2.1x SLOWER on v5e (thin strip
    matmuls + serialized online-softmax chains) and reverted — see
    BASELINE.md "flash short-sequence floor"."""
    rng = np.random.RandomState(5)
    b, h, d = 1, 2, 128
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    out = np.asarray(flash_attention(q, k, v, causal=True, block_q=bq,
                                     block_k=bq, interpret=True))
    ref = np.asarray(_reference_attention(q, k, v, causal=True))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
