"""Parallelism strategies (DDP / TP / ring attention) on the CPU mesh,
plus host-plane DDP gradient sync through the C++ transport."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
optax = pytest.importorskip("optax")

import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from gloo_tpu.models import MLP, Transformer, TransformerConfig  # noqa: E402
from gloo_tpu.parallel import (HostGradSync, make_ddp_train_step,  # noqa: E402
                               ring_attention, tp_mlp_block)
from gloo_tpu.tpu import make_mesh  # noqa: E402
from tests.harness import spawn  # noqa: E402


def test_ddp_mlp_converges():
    mesh = make_mesh({"data": -1})
    model = MLP([8, 32, 1])
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    step = make_ddp_train_step(model.loss, optimizer, mesh)

    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) * 0.5).astype(np.float32)

    losses = []
    for _ in range(60):
        params, opt_state, loss = step(params, opt_state, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, losses[::20]


def test_ddp_matches_single_device():
    """DDP gradients over the mesh must equal full-batch gradients."""
    mesh = make_mesh({"data": -1})
    model = MLP([4, 8, 2])
    params = model.init(jax.random.PRNGKey(1))
    optimizer = optax.sgd(0.1)
    opt_state = optimizer.init(params)
    step = make_ddp_train_step(model.loss, optimizer, mesh)

    rng = np.random.RandomState(1)
    x = rng.randn(16, 4).astype(np.float32)
    y = rng.randn(16, 2).astype(np.float32)

    p_ddp, _, loss_ddp = step(params, opt_state, (x, y))

    loss_ref, grads_ref = jax.value_and_grad(model.loss)(params, (x, y))
    updates, _ = optimizer.update(grads_ref, optimizer.init(params), params)
    p_ref = optax.apply_updates(params, updates)

    assert abs(float(loss_ddp) - float(loss_ref)) < 1e-5
    for a, b in zip(jax.tree.leaves(p_ddp), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_tp_mlp_block_matches_dense():
    mesh = make_mesh({"model": -1})
    p = mesh.shape["model"]
    d, ff = 16, 32 * p
    rng = np.random.RandomState(2)
    x = rng.randn(4, d).astype(np.float32)
    w_up = rng.randn(d, ff).astype(np.float32) * 0.1
    w_down = rng.randn(ff, d).astype(np.float32) * 0.1

    def shard_fn(x, w_up_s, w_down_s):
        return tp_mlp_block(x, w_up_s, w_down_s, "model")

    f = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(None, "model"), P("model", None)),
        out_specs=P()))
    got = np.asarray(f(x, w_up, w_down))
    expected = np.asarray(jax.nn.gelu(x @ w_up) @ w_down)
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    b, h, t, d = 2, 2, 8 * p, 4
    rng = np.random.RandomState(3)
    q = rng.randn(b, h, t, d).astype(np.float32)
    k = rng.randn(b, h, t, d).astype(np.float32)
    v = rng.randn(b, h, t, d).astype(np.float32)

    f = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "seq"), P(None, None, "seq"),
                  P(None, None, "seq")),
        out_specs=P(None, None, "seq")))
    got = np.asarray(f(q, k, v))

    scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = np.tril(np.ones((t, t), bool))
        scores = np.where(mask, scores, -np.inf)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expected = np.einsum("bhqk,bhkd->bhqd", probs, v)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_transformer_trains():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=16)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    mesh = make_mesh({"data": -1})
    step = make_ddp_train_step(model.loss, optimizer, mesh)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 64, (8, 16)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, (tokens, targets))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_host_grad_sync_matches_mean():
    """DDP over the host plane: per-process grads averaged via C++ allreduce."""
    size = 4

    def fn(ctx, rank):
        grads = {
            "w": np.full((5, 3), float(rank), dtype=np.float32),
            "b": np.arange(3, dtype=np.float32) * (rank + 1),
        }
        sync = HostGradSync(ctx)
        avg = sync.average(grads)
        return {k: np.asarray(v) for k, v in avg.items()}

    results = spawn(size, fn)
    w_expect = np.full((5, 3), np.mean(range(size)), np.float32)
    b_expect = np.arange(3, dtype=np.float32) * np.mean(
        [r + 1 for r in range(size)])
    for res in results:
        np.testing.assert_allclose(res["w"], w_expect, rtol=1e-6)
        np.testing.assert_allclose(res["b"], b_expect, rtol=1e-6)


def test_pipeline_parallel_matches_sequential():
    """GPipe schedule over the mesh == applying all stages sequentially."""
    from gloo_tpu.parallel import pipeline_apply

    mesh = make_mesh({"pipe": -1})
    stages = mesh.shape["pipe"]
    d, m = 8, 5  # feature width, microbatches
    rng = np.random.RandomState(7)
    ws = rng.randn(stages, d, d).astype(np.float32) * 0.3
    x = rng.randn(m, 4, d).astype(np.float32)

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def shard_fn(w_stage, xs):
        return pipeline_apply(stage_fn, w_stage[0], xs, "pipe")

    f = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("pipe"), P()), out_specs=P("pipe")))
    # Output lives on the last stage: take its block.
    out = np.asarray(f(ws, x))
    got = out.reshape(stages, m, 4, d)[stages - 1]

    expected = x
    for s in range(stages):
        expected = np.tanh(expected @ ws[s])
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_expert_parallel_dispatch_combine():
    """MoE routing: every kept token processed by its assigned expert."""
    from gloo_tpu.parallel import dispatch_combine

    mesh = make_mesh({"expert": -1})
    n_exp = mesh.shape["expert"]
    t_local, d, capacity = 16, 8, 16  # capacity ample: nothing dropped
    rng = np.random.RandomState(9)
    tokens = rng.randn(n_exp * t_local, d).astype(np.float32)
    assignment = rng.randint(0, n_exp, n_exp * t_local).astype(np.int32)
    # Per-expert scale so expert identity is observable.
    scales = (1.0 + np.arange(n_exp)).astype(np.float32)

    def shard_fn(tok, idx, scale):
        def expert(x):
            return x * scale[0]
        return dispatch_combine(expert, tok, idx, capacity, "expert")

    f = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("expert"), P("expert"), P("expert")),
        out_specs=P("expert")))
    out = np.asarray(f(tokens, assignment, scales))
    expected = tokens * scales[assignment][:, None]
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_expert_parallel_out_of_range_assignment_dropped():
    """A router bug producing expert_idx >= n_experts must yield zeros,
    not another expert's output (regression test)."""
    from gloo_tpu.parallel import dispatch_combine

    mesh = make_mesh({"expert": -1})
    n_exp = mesh.shape["expert"]
    tokens = np.ones((n_exp * 4, 8), np.float32)
    assignment = np.full(n_exp * 4, n_exp + 3, np.int32)  # all invalid

    f = jax.jit(jax.shard_map(
        lambda t, i: dispatch_combine(lambda x: x * 2.0, t, i, 8, "expert"),
        mesh=mesh, in_specs=(P("expert"), P("expert")),
        out_specs=P("expert")))
    out = np.asarray(f(tokens, assignment))
    np.testing.assert_array_equal(out, np.zeros_like(out))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_attention_matches_full(causal):
    """Ring rotation x flash inner kernel == full attention."""
    from gloo_tpu.parallel import ring_flash_attention

    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    b, h, t, d = 1, 2, 16 * p, 128
    rng = np.random.RandomState(3)
    q = rng.randn(b, h, t, d).astype(np.float32)
    k = rng.randn(b, h, t, d).astype(np.float32)
    v = rng.randn(b, h, t, d).astype(np.float32)

    f = jax.jit(jax.shard_map(
        lambda q, k, v: ring_flash_attention(q, k, v, "seq", causal=causal,
                                             block_q=8, block_k=8,
                                             interpret=True),
        mesh=mesh,
        in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq"), check_vma=False))
    got = np.asarray(f(q, k, v))

    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        s = np.where(np.tril(np.ones((t, t), bool)), s, -np.inf)
    pr = np.exp(s - s.max(-1, keepdims=True))
    pr /= pr.sum(-1, keepdims=True)
    expected = np.einsum("bhqk,bhkd->bhqd", pr, v)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_attention_grads(causal):
    """VJP of the ring-flash path == grads of full attention."""
    from gloo_tpu.parallel import ring_flash_attention

    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    b, h, t, d = 1, 1, 16 * p, 32
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    def loss_ring(q, k, v):
        f = jax.shard_map(
            lambda q, k, v: ring_flash_attention(q, k, v, "seq",
                                                 causal=causal, block_q=8,
                                                 block_k=8, interpret=True),
            mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
            out_specs=P(None, None, "seq"), check_vma=False)
        return jnp.sum(jnp.sin(f(q, k, v)))

    def loss_full(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        if causal:
            s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -jnp.inf)
        pr = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.sin(jnp.einsum("bhqk,bhkd->bhqd", pr, v)))

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_full(causal):
    """all-to-all head/seq exchange == full attention (needs h % n == 0)."""
    from gloo_tpu.parallel import ulysses_attention

    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    b, h, t, d = 1, p, 8 * p, 16
    rng = np.random.RandomState(7)
    q = rng.randn(b, h, t, d).astype(np.float32)
    k = rng.randn(b, h, t, d).astype(np.float32)
    v = rng.randn(b, h, t, d).astype(np.float32)

    # Default attn path = the Pallas flash kernel (interpreted on the CPU
    # mesh, which requires check_vma=False on the enclosing shard_map).
    f = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq", causal=causal),
        mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq"), check_vma=False))
    got = np.asarray(f(q, k, v))

    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        s = np.where(np.tril(np.ones((t, t), bool)), s, -np.inf)
    pr = np.exp(s - s.max(-1, keepdims=True))
    pr /= pr.sum(-1, keepdims=True)
    expected = np.einsum("bhqk,bhkd->bhqd", pr, v)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_ulysses_attention_vma_checked():
    """The all_to_all vma bookkeeping must hold under default
    check_vma=True (the flash default needs the interpreter on CPU and
    so can't run checked here; the reference oracle path can)."""
    from gloo_tpu.ops.attention import _reference_attention
    from gloo_tpu.parallel import ulysses_attention

    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    b, h, t, d = 1, p, 8 * p, 16
    rng = np.random.RandomState(11)
    q = rng.randn(b, h, t, d).astype(np.float32)

    f = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq",
                                          attn_fn=_reference_attention),
        mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq")))
    got = np.asarray(f(q, q, q))

    s = np.einsum("bhqd,bhkd->bhqk", q, q) / np.sqrt(d)
    s = np.where(np.tril(np.ones((t, t), bool)), s, -np.inf)
    pr = np.exp(s - s.max(-1, keepdims=True))
    pr /= pr.sum(-1, keepdims=True)
    expected = np.einsum("bhqk,bhkd->bhqd", pr, q)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_ulysses_attention_grads():
    """Ulysses is pure XLA ops — differentiable by construction."""
    from gloo_tpu.parallel import ulysses_attention

    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    b, h, t, d = 1, p, 8 * p, 16
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    f = jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq"),
        mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq"), check_vma=False)

    def loss_full(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -jnp.inf)
        pr = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.sin(jnp.einsum("bhqk,bhkd->bhqd", pr, v)))

    got = jax.grad(lambda *a: jnp.sum(jnp.sin(f(*a))), argnums=(0, 1, 2))(
        q, k, v)
    want = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-5)


def test_ulysses_attention_bad_heads():
    from gloo_tpu.parallel import ulysses_attention

    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    if p == 1:
        pytest.skip("needs >1 device")
    q = np.zeros((1, p + 1, 8 * p, 16), np.float32)
    with pytest.raises(ValueError, match="not divisible"):
        jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, "seq"),
            mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
            out_specs=P(None, None, "seq"))(q, q, q)


def test_fsdp_matches_single_device_sgd():
    """Sharded params + autodiff-recovered reduce-scatter == plain SGD."""
    from gloo_tpu.parallel import (make_fsdp_train_step, shard_params,
                                   unshard_params)
    from gloo_tpu.models.mlp import MLP

    mesh = make_mesh({"data": -1})
    n = mesh.shape["data"]
    model = MLP([8, 17, 4])  # odd hidden width exercises the pad path
    params = model.init(jax.random.PRNGKey(0))

    rng = np.random.RandomState(2)
    xs = jnp.asarray(rng.randn(4 * n, 8), jnp.float32)
    ys = jnp.asarray(rng.randn(4 * n, 4), jnp.float32)

    def loss_fn(p, batch):
        x, y = batch
        return jnp.mean((model.apply(p, x) - y) ** 2)

    lr = 0.1
    step = make_fsdp_train_step(loss_fn, params, "data", lr=lr)

    def run(params, xs, ys):
        sharded = shard_params(params, "data")
        losses = []
        for _ in range(3):
            sharded, loss = step(sharded, (xs, ys))
            losses.append(loss)
        return unshard_params(sharded, params, "data"), jnp.stack(losses)

    # unshard_params output is replicated in value but vma-varying (there
    # is no varying->invariant cast), so disable the replication check.
    final, losses = jax.jit(jax.shard_map(
        run, mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))(params, xs, ys)

    # Oracle: plain full-batch SGD on one device.
    ref = params
    ref_losses = []
    for _ in range(3):
        l, g = jax.value_and_grad(loss_fn)(ref, (xs, ys))
        ref_losses.append(l)
        ref = jax.tree.map(lambda p, gr: p - lr * gr, ref, g)

    np.testing.assert_allclose(np.asarray(losses),
                               np.asarray(jnp.stack(ref_losses)),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(final), jax.tree.leaves(ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)
    assert float(losses[2]) < float(losses[0])


def test_ring_flash_attention_gqa():
    """GQA through the ring: smaller kv blocks rotate; grads group-sum."""
    from gloo_tpu.parallel import ring_flash_attention

    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    b, h, h_kv, t, d = 1, 2 * p, p, 16 * p, 32
    rng = np.random.RandomState(13)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h_kv, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h_kv, t, d), jnp.float32)

    def loss_ring(q, k, v):
        f = jax.shard_map(
            lambda q, k, v: ring_flash_attention(q, k, v, "seq", block_q=8,
                                                 block_k=8, interpret=True),
            mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
            out_specs=P(None, None, "seq"), check_vma=False)
        return jnp.sum(jnp.sin(f(q, k, v)))

    def loss_full(q, k, v):
        kx = jnp.repeat(k, h // h_kv, axis=1)
        vx = jnp.repeat(v, h // h_kv, axis=1)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kx) / np.sqrt(d)
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -jnp.inf)
        return jnp.sum(jnp.sin(jnp.einsum("bhqk,bhkd->bhqd",
                                          jax.nn.softmax(s, -1), vx)))

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("m", [5, 8, 3])
def test_pipeline_1f1b_matches_sequential_grads(m):
    """1F1B training schedule == jax.grad of the sequentially composed
    model, per stage, summed over microbatches (the GPipe/direct
    oracle). Covers M > S, M == S, and the M < S corner."""
    from gloo_tpu.parallel import pipeline_train_1f1b

    mesh = make_mesh({"pipe": -1})
    stages = mesh.shape["pipe"]
    d = 6
    rng = np.random.RandomState(11)
    ws = rng.randn(stages, d, d).astype(np.float32) * 0.4
    x = rng.randn(m, 4, d).astype(np.float32)
    y = rng.randn(m, 4, d).astype(np.float32)

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    def loss_fn(out, target):
        return jnp.mean((out - target) ** 2)

    def shard_fn(w_stage, xs, ys):
        grads, loss = pipeline_train_1f1b(
            stage_fn, loss_fn, w_stage[0], xs, ys, "pipe")
        return grads[None], loss[None]

    f = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("pipe"), P(), P()),
        out_specs=(P("pipe"), P("pipe"))))
    grads, losses = f(ws, x, y)
    grads = np.asarray(grads)          # (stages, d, d)
    loss_sum = float(np.asarray(losses)[-1])  # last stage accumulates

    # Oracle: compose all stages, sum the per-microbatch loss, jax.grad.
    def full_loss(all_ws):
        total = 0.0
        for i in range(m):
            h = x[i]
            for s in range(stages):
                h = stage_fn(all_ws[s], h)
            total = total + loss_fn(h, y[i])
        return total

    ref_loss = float(full_loss(ws))
    ref_grads = np.asarray(jax.grad(full_loss)(ws))
    np.testing.assert_allclose(loss_sum, ref_loss, rtol=1e-5)
    np.testing.assert_allclose(grads, ref_grads, rtol=2e-4, atol=1e-6)


def test_1f1b_tables_shape_and_memory_bound():
    """The timetable is the classic 2(M+S-1) ticks for M >= S, every
    microbatch is forwarded and backwarded exactly once per stage, and
    the in-flight window (forwarded, not yet backwarded) never exceeds
    the stage's 1F1B bound — the invariant that lets every runtime
    buffer be sized S instead of M."""
    from gloo_tpu.parallel.pp import _build_1f1b_tables

    for stages, m in [(2, 3), (4, 8), (4, 4), (8, 8), (3, 12)]:
        fwd, bwd = _build_1f1b_tables(stages, m)
        if m >= stages:
            assert fwd.shape[0] == 2 * (m + stages - 1), (stages, m)
        for s in range(stages):
            fs = [i for i in fwd[:, s] if i >= 0]
            bs = [i for i in bwd[:, s] if i >= 0]
            assert fs == list(range(m)) and bs == list(range(m))
            inflight = 0
            peak = 0
            for t in range(fwd.shape[0]):
                inflight += fwd[t, s] >= 0
                inflight -= bwd[t, s] >= 0
                peak = max(peak, inflight)
            assert peak <= min(stages - s, m), (stages, m, s, peak)
