"""Encrypted data plane: ChaCha20-Poly1305 framing keyed from the PSK
handshake (reference capability: gloo/transport/tcp/tls — confidentiality
and integrity of the wire, not just join authentication).

The wire-level tamper test (malicious peer with the key flips a
ciphertext byte -> authentication IoException) lives in
csrc/tests/integration_main.cc where raw sockets are available; here we
cover the Python surface: the collective/p2p suites over encrypted
devices, failure injection, and tier-mismatch rejection.
"""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

import gloo_tpu
from tests.harness import spawn

ENC = {"auth_key": "wire-secret", "encrypt": True}


def test_encrypt_requires_auth_key():
    with pytest.raises(ValueError, match="auth_key"):
        gloo_tpu.Device(encrypt=True)


@pytest.mark.parametrize("size", [2, 3, 4])
def test_allreduce_encrypted(size):
    def fn(ctx, rank):
        x = np.arange(4097, dtype=np.float32) + rank
        ctx.allreduce(x)
        return x

    results = spawn(size, fn, device_kwargs=ENC)
    expected = sum(np.arange(4097, dtype=np.float64) + r
                   for r in range(size))
    for got in results:
        np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_collective_suite_encrypted():
    """One pass of every collective over encrypted pairs."""
    size = 4

    def fn(ctx, rank):
        out = {}
        x = np.full(1000, float(rank + 1), np.float32)
        ctx.allreduce(x)
        out["allreduce"] = x[0]
        b = np.full(64, 42.0 if rank == 1 else 0.0)
        ctx.broadcast(b, root=1)
        out["broadcast"] = b[0]
        g = ctx.allgather(np.full(10, float(rank), np.float64))
        out["allgather"] = [row[0] for row in g]
        s = np.arange(size * 3, dtype=np.float32) + rank
        out["reduce_scatter"] = ctx.reduce_scatter(s).copy()
        a = (np.arange(size * 2, dtype=np.float64) + 10 * rank).reshape(
            size, 2)
        out["alltoall"] = ctx.alltoall(a).copy()
        ctx.barrier()
        return out

    results = spawn(size, fn, device_kwargs=ENC)
    rs_total = sum(np.arange(size * 3, dtype=np.float64) + r
                   for r in range(size))
    for rank, out in enumerate(results):
        assert out["allreduce"] == size * (size + 1) / 2
        assert out["broadcast"] == 42.0
        assert out["allgather"] == [float(r) for r in range(size)]
        np.testing.assert_allclose(out["reduce_scatter"],
                                   rs_total[rank * 3:(rank + 1) * 3])
        expected_a2a = np.stack(
            [np.arange(size * 2, dtype=np.float64).reshape(size, 2)[rank] +
             10 * src for src in range(size)])
        np.testing.assert_array_equal(out["alltoall"], expected_a2a)


def test_sendrecv_encrypted():
    def fn(ctx, rank):
        if rank == 0:
            ctx.send(np.arange(100000, dtype=np.float64), dst=1, slot=9)
            return None
        got = np.zeros(100000, dtype=np.float64)
        ctx.recv(got, src=0, slot=9)
        return got

    results = spawn(2, fn, device_kwargs=ENC)
    np.testing.assert_array_equal(results[1],
                                  np.arange(100000, dtype=np.float64))


def test_tier_mismatch_rejected():
    """Authenticated-but-plaintext and encrypted peers must not form a
    mesh: the hello negotiation rejects the mismatch in either direction
    and ranks fail at the handshake instead of silently downgrading."""
    import threading

    store = gloo_tpu.HashStore()
    errors = [None, None]

    def worker(rank):
        try:
            ctx = gloo_tpu.Context(rank, 2, timeout=3.0)
            dev = gloo_tpu.Device(auth_key="wire-secret",
                                  encrypt=(rank == 0))
            ctx.connect_full_mesh(store, dev)
            x = np.ones(8, np.float32)
            ctx.allreduce(x)
        except BaseException as exc:  # noqa: BLE001
            errors[rank] = exc

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert any(isinstance(e, (gloo_tpu.IoError, TimeoutError))
               for e in errors), errors


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_crypto_tier_agreement_on_the_wire():
    """A rank sealing with the AVX-512 fused ChaCha+Poly kernels and a
    rank restricted to the scalar/AVX2 fallback (TPUCOLL_NO_AVX512=1)
    must interoperate byte-for-byte: same ciphertext framing, same tags.
    Payload spans several 256 KiB frames plus a partial one so both the
    fused bulk path and the tail path are exercised in each direction."""
    if gloo_tpu.crypto_isa_tier() < 2:
        pytest.skip("AVX-512 AEAD tier not active here: both ranks would "
                    "run the same fallback and the test would be vacuous")
    store = tempfile.mkdtemp()

    def worker(rank, env_extra):
        prog = textwrap.dedent("""
            import sys
            sys.path.insert(0, {repo!r})
            import numpy as np
            import gloo_tpu

            rank = {rank}; size = 2
            store = gloo_tpu.FileStore({store!r})
            ctx = gloo_tpu.Context(rank, size, timeout=15.0)
            ctx.connect_full_mesh(
                store, gloo_tpu.Device(auth_key="k", encrypt=True))
            n = (640 * 1024 + 123) // 4
            x = np.full(n, float(rank + 1), dtype=np.float32)
            ctx.allreduce(x)
            assert np.all(x == 3.0), x[:4]
            ctx.barrier()
            ctx.close()
            sys.exit(10)
        """).format(repo=_REPO, rank=rank, store=store)
        env = dict(os.environ, TPUCOLL_SHM="0", **env_extra)
        return subprocess.Popen([sys.executable, "-c", prog], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    procs = [worker(0, {}), worker(1, {"TPUCOLL_NO_AVX512": "1"})]
    outs = [p.communicate(timeout=60) for p in procs]
    assert [p.returncode for p in procs] == [10, 10], outs


def test_peer_killed_mid_collective_encrypted():
    """Fast failure detection must survive the encrypted framing: SIGKILL
    one rank, survivors get IoError well inside the context timeout."""
    store = tempfile.mkdtemp()

    def worker(rank):
        prog = textwrap.dedent("""
            import os, signal, sys, time
            sys.path.insert(0, {repo!r})
            import numpy as np
            import gloo_tpu

            rank = {rank}; size = 3
            store = gloo_tpu.FileStore({store!r})
            ctx = gloo_tpu.Context(rank, size, timeout=10.0)
            ctx.connect_full_mesh(
                store, gloo_tpu.Device(auth_key="k", encrypt=True))
            if rank == 1:
                os.kill(os.getpid(), signal.SIGKILL)
            x = np.ones(1 << 20, dtype=np.float32)
            t0 = time.monotonic()
            try:
                ctx.allreduce(x)
                sys.exit(3)
            except gloo_tpu.IoError:
                print(f"IOERROR {{time.monotonic() - t0:.3f}}")
                sys.exit(10)
        """).format(repo=_REPO, rank=rank, store=store)
        return subprocess.Popen([sys.executable, "-c", prog],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    procs = [worker(r) for r in range(3)]
    outs = [p.communicate(timeout=60) for p in procs]
    codes = [p.returncode for p in procs]
    assert codes[1] == -signal.SIGKILL
    for r in (0, 2):
        assert codes[r] == 10, (codes, outs)
        elapsed = float(outs[r][0].split()[1])
        assert elapsed < 5.0, f"failure detection took {elapsed}s"


@pytest.mark.parametrize("algorithm", ["ring", "hd", "ring_bf16_wire",
                                       "ring_q8_wire"])
def test_allreduce_encrypted_multiframe_fold_on_open(algorithm):
    """Multi-frame encrypted recvReduce over real TCP payloads
    (TPUCOLL_SHM=0 — same-host shm would carry the bytes plaintext and
    bypass the AEAD rx path entirely), with TPUCOLL_RECV_REDUCE=1: the
    auto policy only fuses recvReduce onto shm peers, so the force knob
    is what routes recvReduce over the encrypted TCP pairs and lights
    up the r5 fold-on-open path (pair.cc rxFoldInline_: every verified
    256 KiB frame folds straight into the accumulator). Each message
    spans several frames. Ring covers the fused segment pipeline, hd
    the window-walk recvReduce, and ring_bf16_wire the TYPED fold
    (wire elsize 2, accumulator elsize 4 — per-frame accumulator
    offsets must scale by the acc elsize, not wire bytes; values stay
    small integers so bf16 wire rounding is exact). ring_q8_wire covers
    the typed fold with a wire elsize (260-byte scale+codes units) that
    does NOT divide the AEAD frame, forcing the completion-time fold
    instead of rxFoldInline_ — verified by tolerance plus a cross-rank
    consensus allgather (q8's block quantization is inexact even on
    small ints). Size 3 adds the non-trivial vrank/fold topology."""
    store = tempfile.mkdtemp()
    size = 3
    n = (3 * 1024 * 1024 + 4096) // 4  # ~3 MiB: several frames/segment
    # bf16 wire: keep every partial sum an integer <= 256 (exact in
    # bf16's 8-bit mantissa) so the expectation is still closed-form.
    mod = 64 if algorithm in ("ring_bf16_wire", "ring_q8_wire") else 512

    def worker(rank):
        prog = textwrap.dedent("""
            import sys
            sys.path.insert(0, {repo!r})
            import numpy as np
            import gloo_tpu

            rank = {rank}; size = {size}; n = {n}
            store = gloo_tpu.FileStore({store!r})
            ctx = gloo_tpu.Context(rank, size, timeout=30.0)
            ctx.connect_full_mesh(
                store, gloo_tpu.Device(auth_key="k", encrypt=True))
            x = (np.arange(n, dtype=np.float32) % {mod}) + rank + 1
            ctx.allreduce(x, algorithm={algorithm!r})
            expect = ((np.arange(n, dtype=np.float64) % {mod}) * size
                      + size * (size + 1) / 2)
            if {algorithm!r} == "ring_q8_wire":
                # Within the per-hop quantization bound, and
                # bit-identical on every rank (consensus survives the
                # encrypted typed fold).
                assert np.abs(x - expect).max() <= expect.max() * 0.02
                allx = ctx.allgather(x)
                for r in range(size):
                    assert np.array_equal(allx[r], x), r
            else:
                assert np.array_equal(x, expect.astype(np.float32)), \\
                    np.flatnonzero(x != expect.astype(np.float32))[:8]
            ctx.barrier()
            ctx.close()
            sys.exit(10)
        """).format(repo=_REPO, rank=rank, size=size, n=n, store=store,
                    algorithm=algorithm, mod=mod)
        env = dict(os.environ, TPUCOLL_SHM="0", TPUCOLL_RECV_REDUCE="1")
        return subprocess.Popen([sys.executable, "-c", prog], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    procs = [worker(r) for r in range(size)]
    outs = [p.communicate(timeout=120) for p in procs]
    assert [p.returncode for p in procs] == [10] * size, outs
