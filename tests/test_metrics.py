"""Metrics registry: counters, histograms, drain, text exposition, and
the straggler watchdog (tentpole of the observability subsystem —
docs/observability.md)."""

import json
import time

import numpy as np

from gloo_tpu.utils.metrics import (histogram_quantile, merge_snapshots,
                                    summarize_ops, to_prometheus)
from tests.harness import spawn


def test_collective_counters_and_bytes():
    def fn(ctx, rank):
        x = np.ones(1000, dtype=np.float32)
        ctx.allreduce(x)
        ctx.allreduce(x)
        ctx.allreduce(x)
        ctx.broadcast(x, root=0)
        ctx.barrier()
        return ctx.metrics()

    for rank, snap in enumerate(spawn(2, fn)):
        assert snap["rank"] == rank
        assert snap["size"] == 2
        assert snap["enabled"] is True
        ops = snap["ops"]
        # Exact call and byte accounting (1000 float32 = 4000 bytes/call).
        assert ops["allreduce"]["calls"] == 3
        assert ops["allreduce"]["bytes"] == 12000
        assert ops["allreduce"]["errors"] == 0
        assert ops["broadcast"]["calls"] == 1
        assert ops["broadcast"]["bytes"] == 4000
        assert ops["barrier"]["calls"] == 1
        # The bootstrap is accounted as its own op.
        assert ops["connect"]["calls"] == 1
        # Nonzero latency histogram with consistent totals.
        hist = ops["allreduce"]["latency_us"]
        assert hist["count"] == 3
        assert sum(n for _, n in hist["buckets"]) == 3
        assert hist["sum_us"] >= 0
        assert hist["max_us"] <= 2 * hist["sum_us"] + 1
        # Transport counters: the peer moved bytes both ways, and its
        # last-progress timestamp is recent.
        peer = 1 - rank
        t = snap["transport"][peer]
        assert t["sent_bytes"] > 0
        assert t["recv_bytes"] > 0
        assert t["sent_msgs"] > 0
        assert 0 <= t["last_progress_age_us"] < 60_000_000
        assert t["recv_wait_us"]["count"] > 0


def test_delegating_ops_keep_their_own_names():
    """gather/allgather/alltoall share schedules with their *v forms but
    must be attributed under their own names (dashboards watch
    op="allgather"; it must not read zero forever)."""

    def fn(ctx, rank):
        x = np.ones(8, dtype=np.float32)
        ctx.gather(x, root=0)
        ctx.allgather(x)
        # 3 ranks, block above the Bruck crossover: the pairwise
        # (delegated) path must still count as alltoall.
        big = np.ones((3, 1024), dtype=np.float32)
        ctx.alltoall(big)
        return ctx.metrics()

    snap = spawn(3, fn)[0]
    ops = snap["ops"]
    assert ops["gather"]["calls"] == 1 and ops["gather"]["bytes"] == 32
    assert ops["allgather"]["calls"] == 1
    assert ops["allgather"]["bytes"] == 32
    assert ops["alltoall"]["calls"] == 1
    assert ops["alltoall"]["bytes"] == 3 * 4096
    for delegated in ("gatherv", "allgatherv", "alltoallv"):
        assert delegated not in ops, delegated


def test_p2p_send_recv_counters():
    def fn(ctx, rank):
        x = np.arange(64, dtype=np.float32)
        if rank == 0:
            ctx.send(x, 1, slot=3)
        else:
            ctx.recv(x, 0, slot=3)
        return ctx.metrics()

    snaps = spawn(2, fn)
    assert snaps[0]["ops"]["send"]["calls"] == 1
    assert snaps[0]["ops"]["send"]["bytes"] == 256
    assert snaps[0]["ops"]["send"]["latency_us"]["count"] == 1
    assert snaps[1]["ops"]["recv"]["calls"] == 1
    assert snaps[1]["ops"]["recv"]["bytes"] == 256
    assert snaps[1]["ops"]["recv"]["latency_us"]["count"] == 1


def test_drain_semantics():
    def fn(ctx, rank):
        x = np.ones(16, dtype=np.float32)
        ctx.allreduce(x)
        first = ctx.metrics(drain=True)
        second = ctx.metrics()
        ctx.allreduce(x)
        third = ctx.metrics()
        return first, second, third

    first, second, third = spawn(2, fn)[0]
    assert first["ops"]["allreduce"]["calls"] == 1
    # Drained: counters reset (the op disappears from the snapshot)...
    assert "allreduce" not in second["ops"]
    assert second["watchdog"]["stalls"] == 0
    # ...but counting continues from zero afterwards.
    assert third["ops"]["allreduce"]["calls"] == 1
    assert third["ops"]["allreduce"]["bytes"] == 64


def test_disable_stops_counting():
    def fn(ctx, rank):
        x = np.ones(16, dtype=np.float32)
        ctx.metrics_enable(False)
        assert not ctx.metrics_enabled()
        ctx.allreduce(x)
        snap = ctx.metrics()
        ctx.metrics_enable(True)
        ctx.allreduce(x)
        return snap, ctx.metrics()

    disabled, enabled = spawn(2, fn)[0]
    assert disabled["enabled"] is False
    assert "allreduce" not in disabled["ops"]
    assert enabled["ops"]["allreduce"]["calls"] == 1


def test_prometheus_exposition():
    def fn(ctx, rank):
        x = np.ones(100, dtype=np.float32)
        ctx.allreduce(x)
        return ctx.metrics()

    snap = spawn(2, fn)[0]
    text = to_prometheus(snap, extra_labels={"job": "t1"})
    lines = text.splitlines()
    assert ('gloo_tpu_collective_calls_total'
            '{job="t1",op="allreduce",rank="0"} 1') in lines
    assert ('gloo_tpu_collective_bytes_total'
            '{job="t1",op="allreduce",rank="0"} 400') in lines
    assert "# TYPE gloo_tpu_collective_latency_us histogram" in lines
    # Histogram buckets are cumulative and end with +Inf == count.
    hist = snap["ops"]["allreduce"]["latency_us"]
    inf_line = [ln for ln in lines
                if ln.startswith("gloo_tpu_collective_latency_us_bucket")
                and 'op="allreduce"' in ln and 'le="+Inf"' in ln]
    assert inf_line and inf_line[0].endswith(f" {hist['count']}")
    bucket_vals = [int(ln.rsplit(" ", 1)[1]) for ln in lines
                   if ln.startswith(
                       "gloo_tpu_collective_latency_us_bucket")
                   and 'op="allreduce"' in ln]
    assert bucket_vals == sorted(bucket_vals)  # cumulative
    assert "gloo_tpu_watchdog_stalls_total" in text
    assert "gloo_tpu_transport_sent_bytes_total" in text


def test_watchdog_identifies_stalled_peer():
    def fn(ctx, rank):
        ctx.set_watchdog(0.05)
        x = np.zeros(8, dtype=np.float32)
        if rank == 0:
            # Blocked on rank 1, which sits on its hands well past the
            # watchdog threshold before sending.
            ctx.recv(x, 1, slot=9, timeout=10)
            return ctx.metrics()
        time.sleep(0.35)
        ctx.send(x, 0, slot=9)
        return None

    snap = spawn(2, fn)[0]
    wd = snap["watchdog"]
    assert wd["stalls"] >= 1
    last = wd["last"]
    assert last["op"] == "recv"
    assert last["peer"] == 1  # the culprit is named
    assert last["slot"] == 9
    assert last["waited_us"] >= 50_000
    # The wait eventually completed: no error was recorded.
    assert snap["ops"]["recv"]["errors"] == 0


def test_watchdog_disarmed_by_default():
    def fn(ctx, rank):
        x = np.zeros(4, dtype=np.float32)
        if rank == 0:
            ctx.recv(x, 1, slot=2, timeout=10)
            return ctx.metrics()
        time.sleep(0.15)
        ctx.send(x, 0, slot=2)
        return None

    snap = spawn(2, fn)[0]
    assert snap["watchdog"]["stalls"] == 0
    assert snap["watchdog"]["last"] is None


def test_histogram_quantile_and_summary():
    hist = {"count": 100, "sum_us": 0, "max_us": 4096,
            "buckets": [[64, 50], [128, 40], [4096, 10]]}
    p50 = histogram_quantile(hist, 0.50)
    assert 32 <= p50 <= 64
    p95 = histogram_quantile(hist, 0.95)
    assert 2048 <= p95 <= 4096
    assert histogram_quantile({"count": 0, "buckets": []}, 0.5) == 0.0

    snap = {"ops": {"allreduce": {"calls": 100, "bytes": 5, "errors": 0,
                                  "latency_us": hist}}}
    digest = summarize_ops(snap)["allreduce"]
    assert digest["calls"] == 100
    assert digest["p50_us"] == round(p50, 1)


def test_rebuild_publishes_stall_evidence():
    """resilience.rebuild_after_failure(failed_context=...) publishes the
    watchdog's verdict so recovery can cite WHICH rank stalled."""
    import gloo_tpu
    from gloo_tpu.resilience import rebuild_after_failure, stall_reports

    shared = gloo_tpu.HashStore()

    def fn(ctx, rank):
        ctx.set_watchdog(0.05)
        # One collective first: the published evidence carries the flight
        # recorder's COLLECTIVE fingerprint tail, so there must be one.
        ctx.allreduce(np.ones(4, dtype=np.float32), tag=7)
        x = np.zeros(4, dtype=np.float32)
        if rank == 0:
            ctx.recv(x, 1, slot=11, timeout=10)  # watchdog fires here
        else:
            time.sleep(0.3)
            ctx.send(x, 0, slot=11)
        # Pretend the group then failed: both ranks re-rendezvous,
        # feeding the old context's evidence into the new generation.
        new_ctx, new_rank, new_size = rebuild_after_failure(
            shared, gloo_tpu.Device(), old_rank=rank, old_size=2,
            generation=1, settle=0.3, timeout=30.0,
            failed_context=ctx)
        assert new_size == 2 and new_rank == rank
        y = np.ones(4, dtype=np.float32)
        new_ctx.allreduce(y)
        new_ctx.close()
        return float(y[0])

    assert spawn(2, fn, timeout=60) == [2.0, 2.0]
    reports = stall_reports(shared, generation=1, old_size=2)
    # Rank 0 stalled on rank 1 and said so. Rank 1 never stalled, but it
    # still publishes evidence: since the flight recorder every survivor
    # ships its fingerprint tail (suspect -1 = "nothing to blame") so
    # the cross-rank desync comparison has both sides.
    assert sorted(reports) == [0, 1]
    assert reports[0]["suspect"] == 1
    assert reports[0]["op"] == "recv"
    assert reports[0]["waited_ms"] >= 50
    assert reports[1]["suspect"] == -1
    for r in (0, 1):
        tail = reports[r]["flightrec"]["tail"]
        assert tail and all("fp" in e and "seq" in e for e in tail)


def test_merge_snapshots():
    def fn(ctx, rank):
        x = np.ones(10, dtype=np.float32)
        ctx.allreduce(x)
        return ctx.metrics()

    merged = merge_snapshots(spawn(2, fn))
    assert sorted(merged["ranks"]) == [0, 1]
    assert merged["ops"]["allreduce"]["calls"] == 2
    assert merged["ops"]["allreduce"]["bytes"] == 80
    assert merged["ops"]["allreduce"]["latency_us"]["count"] == 2
    assert "0->1" in merged["transport"] and "1->0" in merged["transport"]


def test_fault_and_backpressure_fields_in_snapshot():
    """The registry's PR-3 fields are always present: faults (zero when
    no schedule is installed), stash_pauses, per-peer rx_pauses, and the
    transport_failure record (null while healthy) — and they drain."""
    def fn(ctx, rank):
        x = np.ones(16, dtype=np.float32)
        ctx.allreduce(x)
        snap = ctx.metrics(drain=True)
        drained = ctx.metrics()
        return snap, drained

    snap, drained = spawn(2, fn)[0]
    assert snap["faults"] == {"total": 0}
    assert snap["stash_pauses"] == 0
    assert snap["transport_failure"] is None
    assert snap["transport"][1]["rx_pauses"] == 0
    assert drained["faults"] == {"total": 0}


def test_transport_failure_names_first_failed_peer():
    """An UNEXPECTED peer death is recorded in
    metrics()["transport_failure"] even with the watchdog disarmed — the
    EOF-fast evidence resilience uses to blame the dead rank — while an
    orderly goodbye departure is not blamed (clean shutdown skew is not
    a death)."""
    import gloo_tpu
    from gloo_tpu import fault
    from gloo_tpu.resilience import _stall_evidence

    fault.install({"faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data"},
         "action": "kill", "count": 1}]})

    def fn(ctx, rank):
        x = np.zeros(8, dtype=np.float32)
        if rank == 0:
            try:
                ctx.recv(x, 1, slot=3, timeout=10)
            except gloo_tpu.IoError:
                pass
            return ctx.metrics(), _stall_evidence(ctx)
        try:
            ctx.send(x, 1 - rank, slot=3)  # the kill fires here
        except gloo_tpu.IoError:
            pass
        return None

    try:
        snap, evidence = spawn(2, fn)[0]
    finally:
        fault.clear()
    failure = snap["transport_failure"]
    assert failure is not None and failure["peer"] == 1, failure
    assert evidence is not None and evidence["suspect"] == 1, evidence

    # Orderly departure: close() announces goodbye; no blame recorded.
    def orderly(ctx, rank):
        x = np.zeros(8, dtype=np.float32)
        if rank == 0:
            try:
                ctx.recv(x, 1, slot=3, timeout=10)
            except gloo_tpu.IoError:
                pass
            return ctx.metrics()
        ctx.close()
        return None

    snap = spawn(2, orderly)[0]
    assert snap["transport_failure"] is None, snap["transport_failure"]


def test_prometheus_label_escaping():
    """Satellite: label values containing backslash, double-quote, or
    newline must be escaped per the exposition format — transport-
    failure messages routinely contain all three."""
    from gloo_tpu.utils.metrics import _fmt_labels

    labels = _fmt_labels({"op": 'say "hi"\nback\\slash', "rank": 0})
    assert labels == '{op="say \\"hi\\"\\nback\\\\slash",rank="0"}'
    assert "\n" not in labels

    # End to end: a snapshot whose stall op name carries the hostile
    # characters still renders one metric per line, every line parseable
    # as  name{labels} value.
    snap = {"rank": 0, "ops": {}, "transport": {}, "retries": 0,
            "stash_pauses": 0, "trace_events_dropped": 0, "faults": {},
            "watchdog": {"stalls": 1,
                         "last": {"op": 'recv "x"\n\\y', "peer": 2,
                                  "waited_us": 5}}}
    text = to_prometheus(snap)
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        assert "\n" not in line
        name_part, value = line.rsplit(" ", 1)
        float(value)  # the sample value must still parse
    assert 'op="recv \\"x\\"\\n\\\\y"' in text


def test_tracer_bounded_with_drop_counter():
    """Satellite: the opt-in tracer no longer grows without limit — with
    TPUCOLL_TRACE_MAX_EVENTS=5 a 12-op traced run retains 5 spans and
    counts 7 drops in the metrics registry (and its Prometheus
    exposition)."""
    import os

    from gloo_tpu.utils.metrics import to_prometheus as to_prom

    os.environ["TPUCOLL_TRACE_MAX_EVENTS"] = "5"
    try:
        def fn(ctx, rank):
            ctx.trace_start()
            for i in range(12):
                ctx.barrier(tag=i)
            return json.loads(ctx.trace_json()), ctx.metrics()

        events, snap = spawn(2, fn)[0]
    finally:
        del os.environ["TPUCOLL_TRACE_MAX_EVENTS"]
    assert len(events) == 5, len(events)
    assert snap["trace_events_dropped"] == 7, snap["trace_events_dropped"]
    assert "gloo_tpu_trace_events_dropped_total" in to_prom(snap)

    # Draining frees the budget: spans record again afterwards.
    def fn2(ctx, rank):
        ctx.trace_start()
        ctx.barrier(tag=1)
        ctx.trace_json()  # drain
        ctx.barrier(tag=2)
        return json.loads(ctx.trace_json())

    assert len(spawn(2, fn2)[0]) == 1
