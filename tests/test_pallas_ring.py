"""Pallas ring allreduce validated with the distributed TPU interpreter on
the CPU mesh (remote DMA + semaphore semantics are simulated faithfully;
real-chip execution is covered by the benchmark and graft entry)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

from gloo_tpu.ops import ring_allreduce  # noqa: E402


def _run_ring(n, per_rows=None, cols=128, dtype=np.float32):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    per_rows = per_rows if per_rows is not None else n * 8
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
    fn = jax.jit(
        jax.shard_map(lambda s: ring_allreduce(s, "x", interpret=True),
                      mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                      check_vma=False))
    x = (1.0 + np.arange(n, dtype=dtype))[:, None, None] * np.ones(
        (n, per_rows, cols), dtype)
    x += np.arange(cols, dtype=dtype)[None, None, :] * 0.01
    out = np.asarray(fn(x.reshape(n * per_rows, cols)))
    return x, out.reshape(n, per_rows, cols)


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_ring_allreduce_sizes(n):
    x, out = _run_ring(n)
    expected = x.sum(axis=0)
    for i in range(n):
        np.testing.assert_allclose(out[i], expected, rtol=1e-5)


def test_ring_allreduce_large_chunks():
    x, out = _run_ring(4, per_rows=32, cols=256)
    expected = x.sum(axis=0)
    for i in range(4):
        np.testing.assert_allclose(out[i], expected, rtol=1e-5)


def test_ring_allreduce_bfloat16():
    """bf16 shards: per-device rows must honor (16, 128) tiling."""
    import ml_dtypes

    n = 4
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
    fn = jax.jit(
        jax.shard_map(lambda s: ring_allreduce(s, "x", interpret=True),
                      mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                      check_vma=False))
    per_rows = n * 16
    x = (1.0 + np.arange(n, dtype=np.float32))[:, None, None] * np.ones(
        (n, per_rows, 128), np.float32)
    xb = x.astype(ml_dtypes.bfloat16)
    out = np.asarray(fn(xb.reshape(n * per_rows, 128))).astype(np.float32)
    expected = x.sum(axis=0)
    out = out.reshape(n, per_rows, 128)
    for i in range(n):
        np.testing.assert_allclose(out[i], expected, rtol=1e-2)


@pytest.mark.parametrize("n,per_rows", [
    (2, 16), (3, 24), (2, 1024), (4, 32),
    # Odd tile counts through the double-buffered stream (chunk = per
    # rows / n): chunk 264 = 3 tiles of 88, chunk 520 = 5 tiles of 104
    # (largest <=256 multiple-of-8 divisors).
    (2, 528), (3, 792), (2, 1040),
])
def test_hbm_ring_allreduce(n, per_rows):
    """HBM-streaming variant: buffers in HBM, tiled VMEM reduction
    (per_rows=1024 exercises the multi-tile stream path)."""
    from gloo_tpu.ops.pallas_ring import ring_allreduce_hbm

    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
    fn = jax.jit(
        jax.shard_map(lambda s: ring_allreduce_hbm(s, "x", interpret=True),
                      mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                      check_vma=False))
    x = (1.0 + np.arange(n, dtype=np.float32))[:, None, None] * np.ones(
        (n, per_rows, 128), np.float32)
    out = np.asarray(fn(x.reshape(n * per_rows, 128)))
    expected = x.sum(axis=0)
    out = out.reshape(n, per_rows, 128)
    for i in range(n):
        np.testing.assert_allclose(out[i], expected, rtol=1e-5)


@pytest.mark.parametrize("n", [2, 4])
def test_q8_ring_allreduce(n):
    """Quantized int8-wire ring: ~1% error bound, cross-rank consensus."""
    from gloo_tpu.ops import ring_allreduce_q8

    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
    fn = jax.jit(
        jax.shard_map(lambda s: ring_allreduce_q8(s, "x", interpret=True),
                      mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                      check_vma=False))
    rng = np.random.RandomState(0)
    per = n * 32
    x = rng.randn(n, per, 128).astype(np.float32)
    out = np.asarray(fn(x.reshape(n * per, 128))).reshape(n, per, 128)
    expected = x.sum(axis=0)
    rel = np.abs(out[0] - expected).max() / np.abs(expected).max()
    assert rel < 0.05, rel
    for i in range(1, n):
        np.testing.assert_array_equal(out[i], out[0])


def test_ring_allreduce_grad():
    """The kernels are differentiable: VJP of the sum-allreduce is the
    allreduce of the cotangent."""
    n = 4
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))

    def loss(x):
        y = jax.shard_map(
            lambda s: ring_allreduce(s, "x", interpret=True),
            mesh=mesh, in_specs=P("x"), out_specs=P("x"),
            check_vma=False)(x)
        return (y ** 2).sum()

    per = n * 8
    x = np.linspace(-1, 1, n * per * 128).astype(np.float32).reshape(
        n * per, 128)
    g = np.asarray(jax.jit(jax.grad(loss))(x))
    # y_shard = sum(shards) on every rank; dL/dy = 2y (same all ranks);
    # dL/dx_shard = allreduce(2y) = n * 2 * sum(shards).
    total = x.reshape(n, per, 128).sum(axis=0)
    expected = np.tile(2.0 * n * total, (n, 1))
    np.testing.assert_allclose(g, expected, rtol=1e-4)


@pytest.mark.parametrize("n", [2, 3, 4])
def test_bidirectional_ring_allreduce(n):
    """Counter-rotating rings over column halves (both ICI directions)."""
    from gloo_tpu.ops import ring_allreduce_bidir

    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
    fn = jax.jit(
        jax.shard_map(lambda s: ring_allreduce_bidir(s, "x", interpret=True),
                      mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                      check_vma=False))
    per = n * 8
    x = (1.0 + np.arange(n, dtype=np.float32))[:, None, None] * np.ones(
        (n, per, 256), np.float32)
    x += np.arange(256, dtype=np.float32)[None, None, :] * 0.01
    out = np.asarray(fn(x.reshape(n * per, 256))).reshape(n, per, 256)
    expected = x.sum(axis=0)
    for i in range(n):
        np.testing.assert_allclose(out[i], expected, rtol=1e-5)


def test_reduce_scatter_and_allgather_kernels():
    """Standalone phase kernels: RS lands chunk r on rank r; AG stacks."""
    from gloo_tpu.ops import ring_allgather, ring_reduce_scatter

    n = 4
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
    f_rs = jax.jit(jax.shard_map(
        lambda s: ring_reduce_scatter(s, "x", interpret=True),
        mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_vma=False))
    x = np.random.RandomState(0).randn(n, 16, 128).astype(np.float32)
    rs = np.asarray(f_rs(x.reshape(n * 16, 128))).reshape(n, 4, 128)
    full = x.sum(axis=0)
    for r in range(n):
        np.testing.assert_allclose(rs[r], full[r * 4:(r + 1) * 4],
                                   rtol=1e-4, atol=1e-5)

    f_ag = jax.jit(jax.shard_map(
        lambda s: ring_allgather(s, "x", interpret=True),
        mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_vma=False))
    y = np.random.RandomState(1).randn(n, 4, 128).astype(np.float32)
    ag = np.asarray(f_ag(y.reshape(n * 4, 128))).reshape(n, n * 4, 128)
    for r in range(n):
        np.testing.assert_array_equal(ag[r], y.reshape(n * 4, 128))


def test_torus_allreduce_2d():
    """Dimension-ordered allreduce over a 2x2 torus: RS x, RS y, AG y,
    AG x — neighbor ids map through the flattened mesh coordinates."""
    from gloo_tpu.ops import ring_allreduce_torus

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:4], dtype=object).reshape(2, 2),
                ("y", "x"))
    f = jax.jit(jax.shard_map(
        lambda s: ring_allreduce_torus(s, ("x", "y"), mesh_axes=("y", "x"),
                                       interpret=True),
        mesh=mesh, in_specs=P(("y", "x")), out_specs=P(("y", "x")),
        check_vma=False))
    z = np.random.RandomState(2).randn(4, 8, 128).astype(np.float32)
    out = np.asarray(f(z.reshape(32, 128))).reshape(4, 8, 128)
    expect = z.sum(axis=0)
    for i in range(4):
        np.testing.assert_allclose(out[i], expect, rtol=1e-4, atol=1e-5)


def test_pallas_alltoall_kernel():
    """Rotated pairwise all-to-all: output block r = rank r's block my."""
    from gloo_tpu.ops import pallas_alltoall

    n = 4
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
    f = jax.jit(jax.shard_map(
        lambda s: pallas_alltoall(s, "x", interpret=True),
        mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_vma=False))
    rows = 2 * n
    x = np.random.RandomState(3).randn(n * rows, 128).astype(np.float32)
    got = np.asarray(f(x))
    chunk = rows // n
    blocks = x.reshape(n, n, chunk, 128)
    expected = blocks.transpose(1, 0, 2, 3).reshape(n * rows, 128)
    np.testing.assert_array_equal(got, expected)


def test_pallas_alltoall_2d_mesh():
    """mesh_axes stride arithmetic: all-to-all along one axis of a 2x2."""
    from gloo_tpu.ops import pallas_alltoall

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:4], dtype=object).reshape(2, 2),
                ("a", "b"))
    for ax in ("a", "b"):
        f = jax.jit(jax.shard_map(
            lambda s: pallas_alltoall(s, ax, interpret=True,
                                      mesh_axes=("a", "b")),
            mesh=mesh, in_specs=P(ax), out_specs=P(ax), check_vma=False))
        x = np.random.RandomState(4).randn(2 * 8, 128).astype(np.float32)
        got = np.asarray(f(x))
        blocks = x.reshape(2, 2, 4, 128)
        expected = blocks.transpose(1, 0, 2, 3).reshape(16, 128)
        np.testing.assert_array_equal(got, expected)


def test_pallas_alltoall_grad():
    """The block swap is an involution: VJP == another all-to-all."""
    from gloo_tpu.ops import pallas_alltoall

    n = 4
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs 4 devices")
    mesh = Mesh(np.asarray(devs[:n], dtype=object), ("x",))
    import jax.numpy as jnp

    x = jnp.asarray(
        np.random.RandomState(5).randn(n * n * 2, 128), jnp.float32)
    w = jnp.asarray(
        np.random.RandomState(6).randn(n * n * 2, 128), jnp.float32)

    def loss(x):
        f = jax.shard_map(
            lambda s, ww: jnp.sum(pallas_alltoall(s, "x", interpret=True)
                                  * ww)[None],
            mesh=mesh, in_specs=(P("x"), P("x")), out_specs=P("x"),
            check_vma=False)
        return jnp.sum(f(x, w))

    got = jax.grad(loss)(x)
    # d/dx sum(A2A(x) * w) = A2A(w) (involution adjoint)
    blocks = np.asarray(w).reshape(n, n, 2, 128)
    expected = blocks.transpose(1, 0, 2, 3).reshape(n * n * 2, 128)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-6)
