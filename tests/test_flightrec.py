"""Flight recorder + desync detector (docs/flightrec.md).

Covers the full chain the observability tentpole promises: chaos
(PR 3's fault plane) -> always-on recorder -> per-rank dumps -> cross-
rank merge -> blame. Plus the merge() edge cases (empty file, missing
rank, unsorted timestamps) and the determinism contract (same seed =>
identical per-rank seq streams).
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import threading

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu.resilience import (_stall_evidence, analyze_stall_reports,
                                 raise_on_desync_reports)
from gloo_tpu.utils import flightrec
from gloo_tpu.utils.flightrec import DesyncError
from tests.harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_flightrec_records_dump_merge_roundtrip():
    """Tier-1 smoke: the recorder is ALWAYS on — no arming call — and a
    clean run dumps, merges, and analyzes to an "ok" verdict with
    identical per-rank seq/fingerprint streams."""
    dump_dir = tempfile.mkdtemp(prefix="flightrec-")

    def fn(ctx, rank):
        x = np.full(2048, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=1)
        ctx.barrier(tag=2)
        ctx.allgather(np.full(8, float(rank), np.float64), tag=3)
        assert ctx.flightrec_seq() == 3
        return flightrec.dump(ctx, dump_dir)

    paths = spawn(3, fn)
    assert all(os.path.exists(p) for p in paths)
    merged = flightrec.merge(dump_dir)
    assert sorted(merged["ranks"]) == [0, 1, 2]
    assert merged["missing"] == []
    # One timeline, 3 ops per rank, all completed, fingerprints agree.
    assert len(merged["timeline"]) == 9
    for doc in merged["ranks"].values():
        assert [e["op"] for e in doc["events"]] == \
            ["allreduce", "barrier", "allgather"]
        assert all(e["state"] == "completed" for e in doc["events"])
        # allreduce resolved its algorithm and the transport stamped the
        # started transition between enqueue and completion.
        ar = doc["events"][0]
        assert ar["algo"] is not None
        assert (ar["ts_enqueued_us"] <= ar["ts_started_us"]
                <= ar["ts_completed_us"])
    fps = [[e["fp"] for e in doc["events"]]
           for _, doc in sorted(merged["ranks"].items())]
    assert fps[0] == fps[1] == fps[2]
    verdict = flightrec.analyze(merged)
    assert verdict["kind"] == "ok", verdict
    assert flightrec.raise_on_desync(merged)["kind"] == "ok"


def test_chaos_stall_dumps_and_blames_inflight_op():
    """Acceptance: a PR 3 fault schedule stalls rank 1 mid-allreduce at
    P=3. Every rank writes a flight-recorder dump (rank 0's arrives via
    the watchdog auto-dump trigger, mid-stall), flightrec.merge()
    produces one timeline, and the analysis names rank 1 and the
    in-flight op."""
    store = tempfile.mkdtemp()
    fr_dir = os.path.join(store, "flightrec")
    schedule = {"seed": 21, "faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 1},
         "action": "stall", "ms": 1200}]}
    sched_path = os.path.join(store, "fault_schedule.json")
    with open(sched_path, "w") as f:
        json.dump(schedule, f)

    body = textwrap.dedent("""
        import json, os, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu
        from gloo_tpu.utils import flightrec

        rank = int(sys.argv[1]); size = 3
        store = gloo_tpu.FileStore({store!r})
        ctx = gloo_tpu.Context(rank, size, timeout=15.0)
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        if rank == 0:
            # Only rank 0 arms the watchdog: its blocked wait fires the
            # automatic mid-stall dump that blames peer 1 (arming rank 2
            # too would add a second, tie-breaking blame vote).
            ctx.set_watchdog(0.15)
        x = np.full(2048, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, tag=1)
        assert x[0] == size * (size + 1) / 2, x[0]
        if rank != 0:
            # Ranks 1/2 dump explicitly; rank 0 keeps its auto dump (the
            # mid-stall evidence) instead of overwriting it post-success.
            flightrec.dump(ctx, {fr_dir!r})
        ctx.close()
        print("OK")
    """).format(repo=_REPO, store=store, fr_dir=fr_dir)

    env = dict(os.environ, TPUCOLL_FAULT_FILE=sched_path,
               TPUCOLL_FLIGHTREC_DIR=fr_dir)
    procs = [subprocess.Popen([sys.executable, "-c", body, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for r in range(3)]
    outs = [p.communicate(timeout=120) for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "OK" in out[0], (r, out)

    merged = flightrec.merge(fr_dir)
    assert sorted(merged["ranks"]) == [0, 1, 2], merged["missing"]
    assert merged["missing"] == []
    # Rank 0's dump is the watchdog's: written mid-stall, blaming peer 1,
    # with the allreduce still in flight.
    r0 = merged["ranks"][0]
    assert r0["reason"] == "stall", r0["reason"]
    assert r0["blamed_peer"] == 1, r0["blamed_peer"]
    assert r0["events"][0]["op"] == "allreduce"
    assert r0["events"][0]["state"] in ("enqueued", "started")
    verdict = flightrec.analyze(merged)
    assert verdict["kind"] == "stall", verdict
    assert verdict["blamed_ranks"] == [1], verdict
    assert "allreduce" in verdict["message"], verdict["message"]


def test_desync_mismatched_schedule_typed_error():
    """PR 3's third driver: a mismatched schedule. Rank 2 issues a
    broadcast at the seq where ranks 0/1 issue an allreduce; the
    collectives time out, the fingerprint exchange runs through the
    resilience evidence path, and the verdict is a typed DesyncError
    whose message names both ops at the diverging seq."""
    gate = threading.Barrier(3, timeout=60)
    docs = [None] * 3
    reports = {}

    def fn(ctx, rank):
        x = np.full(1024, float(rank + 1), dtype=np.float32)
        try:
            if rank == 2:
                ctx.broadcast(x, root=2, tag=1, timeout=2.0)
            else:
                ctx.allreduce(x, tag=1, timeout=2.0)
        except gloo_tpu.Error:
            pass
        gate.wait()
        docs[rank] = ctx.flightrec()
        reports[rank] = _stall_evidence(ctx)
        gate.wait()  # hold every context open until evidence is read

    spawn(3, fn, timeout=90)

    merged = flightrec.merge(docs)
    verdict = flightrec.analyze(merged)
    assert verdict["kind"] == "desync", verdict
    assert verdict["blamed_ranks"] == [2], verdict
    with pytest.raises(DesyncError, match="desync") as exc:
        flightrec.raise_on_desync(merged)
    msg = str(exc.value)
    assert "broadcast" in msg and "allreduce" in msg and "seq" in msg, msg

    # Store-exchange face: the published stall evidence carries the
    # fingerprint tails, and analyze_stall_reports reaches the same
    # verdict through resilience.
    assert all(r is not None and "flightrec" in r for r in reports.values())
    v2 = analyze_stall_reports(reports)
    assert v2["kind"] == "desync" and v2["blamed_ranks"] == [2], v2
    with pytest.raises(DesyncError):
        raise_on_desync_reports(reports)


def test_same_seed_chaos_identical_seq_streams():
    """Acceptance: same-seed chaos runs produce identical per-rank
    (seq, op, fingerprint) streams — the recorder is deterministic even
    with a probabilistic fault schedule firing underneath."""
    from gloo_tpu import fault

    schedule = {"seed": 31, "faults": [
        {"when": {"rank": 1, "opcode": "data"},
         "action": "delay", "ms": 1, "prob": 0.5, "seed": 7}]}

    def workload():
        def fn(ctx, rank):
            data = np.arange(64, dtype=np.float64)
            out = np.zeros(64, dtype=np.float64)
            for i in range(10):
                ctx.allreduce(data.copy(), tag=2 * i)
                if rank == 1:
                    ctx.send(data, dst=0, slot=500 + i)
                else:
                    ctx.recv(out, src=1, slot=500 + i)
            ctx.barrier(tag=999)
            return [(e["seq"], e["op"], e["fp"])
                    for e in ctx.flightrec()["events"]]

        return spawn(2, fn, timeout=60)

    fault.install(schedule)
    try:
        first = workload()
        fault.install(schedule)  # reset firing state for the replay
        second = workload()
    finally:
        fault.clear()
    assert first == second
    assert len(first[0]) == 21  # 10 allreduce + 10 p2p + barrier


def test_merge_edge_cases_degrade_gracefully():
    """Satellite: empty per-rank files, a missing rank's dump, and
    unsorted timestamps must not throw — merge notes the absent rank
    and still produces one ordered timeline."""
    d = tempfile.mkdtemp(prefix="flightrec-")

    def ev(seq, ts, op="allreduce", state="completed", fp="aa"):
        return {"seq": seq, "cseq": seq, "op": op, "algo": None, "slot": 0,
                "peer": -1, "bytes": 64, "dtype": "float32", "fp": fp,
                "state": state, "ts_enqueued_us": ts,
                "ts_started_us": ts + 1,
                "ts_completed_us": ts + 2 if state == "completed" else 0}

    # rank 0: healthy but with UNSORTED timestamps; rank 1: empty file;
    # rank 2: truncated JSON; rank 3: never dumped (size says 4 ranks).
    with open(os.path.join(d, "flightrec-rank0.json"), "w") as f:
        json.dump({"version": 1, "rank": 0, "size": 4, "reason": "explicit",
                   "blamed_peer": -1, "now_us": 100, "next_seq": 3,
                   "capacity": 8, "dropped": 0,
                   "events": [ev(1, 50), ev(0, 10), ev(2, 30)]}, f)
    open(os.path.join(d, "flightrec-rank1.json"), "w").close()
    with open(os.path.join(d, "flightrec-rank2.json"), "w") as f:
        f.write('{"rank": 2, "events": [{"se')

    merged = flightrec.merge(d)
    assert sorted(merged["ranks"]) == [0]
    assert merged["missing"] == [1, 2, 3]
    assert [e["ts_enqueued_us"] for e in merged["timeline"]] == [10, 30, 50]
    verdict = flightrec.analyze(merged)
    assert verdict["kind"] == "stall"
    assert verdict["blamed_ranks"] == [1, 2, 3]

    # The dict/None input form tolerates absent docs the same way.
    merged2 = flightrec.merge([merged["ranks"][0], None])
    assert merged2["missing"] == [1, 2, 3]

    # detect_desync over partial tails: overlapping collective seqs
    # compare, absent ranks are simply not blamed.
    tails = {0: [{"seq": 9, "cseq": 5, "fp": "x", "desc": "allreduce"}],
             1: [{"seq": 7, "cseq": 5, "fp": "y", "desc": "broadcast"}],
             2: []}
    report = flightrec.detect_desync(tails)
    assert report is not None and report["blamed_ranks"] in ([0], [1])


def test_asymmetric_p2p_is_not_a_desync():
    """Regression: user p2p traffic is rank-asymmetric by nature (one
    rank sends, another receives, a third does neither) — it must
    neither shift the collective comparison axis nor be compared
    itself. Only a COLLECTIVE divergence is a desync."""
    def fn(ctx, rank):
        data = np.arange(32, dtype=np.float64)
        out = np.zeros(32, dtype=np.float64)
        ctx.allreduce(data.copy(), tag=1)
        # ranks 0/1 exchange different NUMBERS of p2p ops; rank 2 none.
        if rank == 1:
            for i in range(3):
                ctx.send(data, dst=0, slot=300 + i)
        elif rank == 0:
            for i in range(3):
                ctx.recv(out, src=1, slot=300 + i)
        ctx.barrier(tag=2)
        return ctx.flightrec()

    docs = spawn(3, fn, timeout=60)
    # Ring seqs differ per rank (p2p counts differ), collective seqs
    # align: allreduce at cseq 0, barrier at cseq 1, on every rank.
    for doc in docs:
        colls = [e for e in doc["events"] if e["cseq"] is not None]
        assert [(e["cseq"], e["op"]) for e in colls] == \
            [(0, "allreduce"), (1, "barrier")]
        for e in doc["events"]:
            if e["op"] in ("send", "recv"):
                assert e["cseq"] is None
    merged = flightrec.merge(docs)
    verdict = flightrec.analyze(merged)
    assert verdict["kind"] == "ok", verdict
    flightrec.raise_on_desync(merged)


def test_mismatched_tag_is_a_desync():
    """Regression: a tag divergence hangs exactly like an op divergence
    and must read as a desync — the fingerprint folds in the slot
    (prefix + tag), not just the opcode."""
    def fn(ctx, rank):
        x = np.full(256, float(rank + 1), dtype=np.float32)
        try:
            ctx.allreduce(x, tag=9 if rank == 2 else 1, timeout=1.5)
        except gloo_tpu.Error:
            pass
        return ctx.flightrec()

    docs = spawn(3, fn, timeout=60)
    verdict = flightrec.analyze(flightrec.merge(docs))
    assert verdict["kind"] == "desync", verdict
    assert verdict["blamed_ranks"] == [2], verdict


def test_heterogeneous_counts_same_schedule_not_desync():
    """Regression: allgatherv with per-rank counts is ONE schedule even
    though every rank's own payload differs — the fingerprint must use
    the rank-invariant group total, not this rank's share."""
    def fn(ctx, rank):
        counts = [4, 8, 12]
        x = np.full(counts[rank], float(rank), dtype=np.float32)
        ctx.allgatherv(x, counts, tag=1)
        ctx.gatherv(x, counts, root=0, tag=2)
        return ctx.flightrec()

    docs = spawn(3, fn, timeout=60)
    fps = [[e["fp"] for e in d["events"]] for d in docs]
    assert fps[0] == fps[1] == fps[2], fps
    assert flightrec.detect_desync(
        {i: d["events"] for i, d in enumerate(docs)}) is None


def test_signal_handler_dumps_on_fatal_signal():
    """Opt-in fatal-signal trigger: TPUCOLL_FLIGHTREC_SIGNALS=1 dumps
    the ring to TPUCOLL_FLIGHTREC_DIR on SIGTERM and the process still
    dies with the signal's default disposition."""
    store = tempfile.mkdtemp()
    fr_dir = os.path.join(store, "fr")
    prog = textwrap.dedent(f"""
        import os, signal, sys
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        import gloo_tpu
        ctx = gloo_tpu.Context(0, 1, timeout=5.0)
        ctx.connect_full_mesh(gloo_tpu.FileStore({store!r}),
                              gloo_tpu.Device())
        ctx.allreduce(np.ones(16, dtype=np.float32), tag=1)
        os.kill(os.getpid(), signal.SIGTERM)
    """)
    env = dict(os.environ, TPUCOLL_FLIGHTREC_DIR=fr_dir,
               TPUCOLL_FLIGHTREC_SIGNALS="1")
    p = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, env=env, timeout=60)
    assert p.returncode == -signal.SIGTERM, (p.returncode, p.stderr)
    doc = flightrec.load(os.path.join(fr_dir, "flightrec-rank0.json"))
    assert doc is not None, os.listdir(fr_dir) if os.path.isdir(fr_dir) \
        else "no dump dir"
    assert doc["reason"] == "signal"
    assert [e["op"] for e in doc["events"]] == ["allreduce"]


def test_p2p_ops_recorded_with_resolved_peer():
    """User-facing p2p posts get ring entries too; a recv-from-any
    resolves its peer at completion and waits flip entries to
    completed."""
    def fn(ctx, rank):
        data = np.full(32, float(rank), dtype=np.float64)
        out = np.zeros(32, dtype=np.float64)
        if rank == 0:
            buf = ctx.register(out)
            buf.recv([1, 2], slot=77)       # recv-from-any
            src = buf.wait_recv()
            assert src == 1
        elif rank == 1:
            ctx.send(data, dst=0, slot=77)
        ctx.barrier(tag=5)
        return ctx.flightrec()["events"]

    events = spawn(3, fn, timeout=60)
    r0 = [e for e in events[0] if e["op"] == "recv"]
    assert len(r0) == 1
    assert r0[0]["state"] == "completed"
    assert r0[0]["peer"] == 1  # resolved at wait_recv
    r1 = [e for e in events[1] if e["op"] == "send"]
    assert len(r1) == 1 and r1[0]["state"] == "completed"
    assert r1[0]["peer"] == 0


def test_flightrec_ring_bounded_and_drop_counted():
    """The ring is bounded: with TPUCOLL_FLIGHTREC_EVENTS=8, a 30-op run
    keeps the newest 8 records and reports the overwritten count."""
    os.environ["TPUCOLL_FLIGHTREC_EVENTS"] = "8"
    try:
        def fn(ctx, rank):
            for i in range(30):
                ctx.barrier(tag=i)
            return ctx.flightrec()

        doc = spawn(2, fn, timeout=60)[0]
    finally:
        del os.environ["TPUCOLL_FLIGHTREC_EVENTS"]
    assert doc["capacity"] == 8
    assert doc["next_seq"] == 30
    assert doc["dropped"] == 22
    assert [e["seq"] for e in doc["events"]] == list(range(22, 30))
