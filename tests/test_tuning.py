"""Collective autotuning plane: table JSON round trips, kAuto dispatch
consulting the installed table (and keeping today's threshold behavior
when untuned), the TPUCOLL_TUNING_FILE hook, the tuner smoke, and
rank-consistency of the elected table across a real multiprocess group.

Dispatch decisions are asserted through the tracer: every allreduce /
reduce span records the algorithm that actually ran in its `detail`
arg, so these tests observe the native dispatcher itself, not a Python
re-implementation of it.
"""

import json
import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu import tuning
from tests.harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _table(entries):
    return {"version": 1, "entries": entries}


def _entry(collective, algorithm, bucket, cost_us, world_size=2,
           dtype="float32"):
    return {"collective": collective, "algorithm": algorithm,
            "world_size": world_size, "dtype": dtype, "bucket": bucket,
            "cost_us": cost_us}


def _spans(events, name):
    """Trace-span details (algorithm names) for collective `name`.
    `events` is a parsed trace (trace_json DRAINS — fetch it once)."""
    return [e["args"].get("detail") for e in events if e["name"] == name]


# ---- table JSON round trip (no group needed: install is per-rank) ----


def test_table_json_roundtrip(tmp_path):
    table = _table([
        _entry("allreduce", "ring", 20, 1500.0),
        _entry("allreduce", "ring", 10, 80.5),
        _entry("allreduce", "halving_doubling", 10, 40.25),
        _entry("reduce", "binomial", 14, 200.0),
        _entry("reduce_scatter", "direct", 12, 55.125, world_size=4),
    ])
    path = os.path.join(tmp_path, "table.json")
    tuning.save_table(table, path)
    loaded = tuning.load_table(path)
    assert loaded == table

    ctx = gloo_tpu.Context(0, 2)  # install needs no transport
    assert tuning.installed_table(ctx) is None
    tuning.install_table(ctx, loaded)
    got = tuning.installed_table(ctx)
    # The native table canonicalizes entry order; compare as sets.
    key = lambda e: (e["collective"], e["algorithm"], e["world_size"],
                     e["dtype"], e["bucket"])
    assert sorted(got["entries"], key=key) == sorted(table["entries"],
                                                     key=key)
    for mine, theirs in zip(sorted(got["entries"], key=key),
                            sorted(table["entries"], key=key)):
        assert mine["cost_us"] == pytest.approx(theirs["cost_us"])
    # Native serialization is canonical: a second round trip through the
    # core is byte-stable (the rank-agreement check is a string compare).
    tuning.install_table(ctx, got)
    assert tuning.installed_table(ctx) == got

    tuning.clear_table(ctx)
    assert tuning.installed_table(ctx) is None


def test_malformed_table_raises():
    ctx = gloo_tpu.Context(0, 2)
    with pytest.raises(gloo_tpu.Error):
        tuning.install_table(ctx, "{not json")
    with pytest.raises(gloo_tpu.Error):
        tuning.install_table(ctx, {"version": 99, "entries": []})
    with pytest.raises(gloo_tpu.Error):
        tuning.install_table(ctx, _table([
            _entry("allreduce", "ring", 10, -5.0)]))  # negative cost
    assert tuning.installed_table(ctx) is None


# ---- fallback: untuned contexts keep today's threshold behavior ----


def test_untuned_dispatch_keeps_default_thresholds():
    """With no table installed, kAuto must follow the historical
    constants: allreduce rd <= 16K < hd <= 1M < ring; reduce binomial
    <= 2M < ring."""
    def fn(ctx, rank):
        assert tuning.installed_table(ctx) is None
        ctx.trace_start()
        ctx.allreduce(np.zeros(1024, dtype=np.float32))       # 4K -> rd
        ctx.allreduce(np.zeros(128 * 1024, dtype=np.float32)) # 512K -> hd
        ctx.allreduce(np.zeros(512 * 1024, dtype=np.float32)) # 2M -> ring
        ctx.reduce(np.zeros(1024, dtype=np.float32))          # binomial
        ctx.reduce(np.zeros(1024 * 1024, dtype=np.float32))   # 4M -> ring
        events = json.loads(ctx.trace_json())
        algos = _spans(events, "allreduce")
        reduces = _spans(events, "reduce")
        ctx.trace_stop()
        assert algos == ["recursive_doubling", "halving_doubling", "ring"], \
            algos
        assert reduces == ["binomial", "ring"], reduces

    spawn(2, fn)


def test_installed_table_overrides_thresholds():
    """A table that prices ring cheapest at small sizes must flip kAuto
    to ring where the default thresholds would pick rd/hd — and
    clear_table must restore the default choice."""
    table = _table([
        # ring "measured" cheapest across the whole range...
        _entry("allreduce", "ring", 10, 10.0),
        _entry("allreduce", "ring", 22, 10.0),
        # ...and the competitors expensive.
        _entry("allreduce", "recursive_doubling", 10, 900.0),
        _entry("allreduce", "recursive_doubling", 22, 900.0),
        _entry("allreduce", "halving_doubling", 10, 900.0),
        _entry("allreduce", "halving_doubling", 22, 900.0),
        # reduce: invert the default (ring for tiny payloads).
        _entry("reduce", "ring", 10, 10.0),
        _entry("reduce", "binomial", 10, 900.0),
    ])

    def fn(ctx, rank):
        tuning.install_table(ctx, table)
        ctx.trace_start()
        x = np.zeros(1024, dtype=np.float32)  # 4K: default would pick rd
        ctx.allreduce(x)
        ctx.reduce(np.zeros(1024, dtype=np.float32))  # default: binomial
        tuning.clear_table(ctx)
        ctx.allreduce(x)  # back to the default choice
        events = json.loads(ctx.trace_json())
        algos = _spans(events, "allreduce")
        reduces = _spans(events, "reduce")
        ctx.trace_stop()
        assert algos == ["ring", "recursive_doubling"], algos
        assert reduces == ["ring"], reduces

    spawn(2, fn)


def test_table_interpolates_crossover_between_buckets():
    """Cost curves cross BETWEEN measured buckets: ring is priced cheaper
    at bucket 10 (1K), hd cheaper at bucket 20 (1M); linear-in-log2
    interpolation puts the crossover at bucket 15, so 16K (bucket 14)
    must still elect ring and 128K (bucket 17) hd."""
    table = _table([
        _entry("allreduce", "ring", 10, 100.0),
        _entry("allreduce", "ring", 20, 600.0),
        _entry("allreduce", "halving_doubling", 10, 200.0),
        _entry("allreduce", "halving_doubling", 20, 500.0),
    ])

    def fn(ctx, rank):
        tuning.install_table(ctx, table)
        ctx.trace_start()
        ctx.allreduce(np.zeros(4 * 1024, dtype=np.float32))   # 16K
        ctx.allreduce(np.zeros(32 * 1024, dtype=np.float32))  # 128K
        algos = _spans(json.loads(ctx.trace_json()), "allreduce")
        ctx.trace_stop()
        assert algos == ["ring", "halving_doubling"], algos

    spawn(2, fn)


def test_boundary_cell_prefers_covered_candidate():
    """Regression for the crossover extrapolation bug: beyond an arm's
    largest measured bucket its clamped edge cost is an extrapolation,
    and comparing it against a curve genuinely measured there let a
    ragged sweep elect an algorithm octaves outside its evidence. hd is
    priced very cheap but swept only to bucket 14 (16K); ring is dearer
    but measured through bucket 22. Inside hd's range the cheap arm
    wins; past it, election must fall to the covered curve — and only
    with NO covered candidate may clamped evidence still elect."""
    table = _table([
        _entry("allreduce", "halving_doubling", 10, 10.0),
        _entry("allreduce", "halving_doubling", 14, 20.0),
        _entry("allreduce", "ring", 10, 300.0),
        _entry("allreduce", "ring", 22, 400.0),
        _entry("allreduce", "recursive_doubling", 10, 500.0),
        _entry("allreduce", "recursive_doubling", 22, 600.0),
    ])

    def fn(ctx, rank):
        tuning.install_table(ctx, table)
        ctx.trace_start()
        ctx.allreduce(np.zeros(1024, dtype=np.float32))        # 4K: in range
        ctx.allreduce(np.zeros(256 * 1024, dtype=np.float32))  # 1M: beyond hd
        # 16M (bucket 24): beyond EVERY curve — with no covered
        # candidate the clamped comparison returns, and hd's cheap
        # 16K edge may elect again (edge evidence beats no evidence).
        ctx.allreduce(np.zeros(4 * 1024 * 1024, dtype=np.float32))
        algos = _spans(json.loads(ctx.trace_json()), "allreduce")
        ctx.trace_stop()
        assert algos == ["halving_doubling", "ring", "halving_doubling"], \
            algos

    spawn(2, fn)


# ---- TPUCOLL_TUNING_FILE env hook ----


def test_tuning_file_env_hook(tmp_path):
    path = os.path.join(tmp_path, "env_table.json")
    tuning.save_table(_table([
        _entry("allreduce", "ring", 10, 1.0),
        _entry("allreduce", "recursive_doubling", 10, 900.0),
        _entry("allreduce", "halving_doubling", 10, 900.0),
    ]), path)

    def fn(ctx, rank):
        got = tuning.installed_table(ctx)
        assert got is not None and len(got["entries"]) == 3
        ctx.trace_start()
        ctx.allreduce(np.zeros(1024, dtype=np.float32))
        algos = _spans(json.loads(ctx.trace_json()), "allreduce")
        ctx.trace_stop()
        assert algos == ["ring"], algos

    os.environ["TPUCOLL_TUNING_FILE"] = path
    try:
        spawn(2, fn)
    finally:
        del os.environ["TPUCOLL_TUNING_FILE"]


def test_tuning_file_env_hook_malformed_fails_loudly(tmp_path):
    path = os.path.join(tmp_path, "bad_table.json")
    with open(path, "w") as f:
        f.write("{definitely not a table")

    os.environ["TPUCOLL_TUNING_FILE"] = path
    try:
        with pytest.raises(AssertionError, match="JSON"):
            # connect_full_mesh must throw, not silently run untuned
            # (spawn wraps each rank's failure in AssertionError).
            spawn(2, lambda ctx, rank: None)
    finally:
        del os.environ["TPUCOLL_TUNING_FILE"]


# ---- tuner smoke: tiny sizes, in-process group ----


@pytest.mark.parametrize("size", [2, 3])
def test_tune_smoke_rank_consistent(size):
    """tune() at tiny sizes: all ranks install byte-identical tables,
    the table covers the swept collectives (including the np2 hd_fold /
    hd_blocks arms at P=3), and collectives still verify afterwards."""
    def fn(ctx, rank):
        table = tuning.tune(ctx, min_bytes=4096, max_bytes=16384, iters=2,
                            warmup=1)
        x = np.full(256, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)  # dispatches off the fresh table
        expected = sum(range(1, size + 1))
        np.testing.assert_allclose(x, expected)
        return json.dumps(table, sort_keys=True)

    results = spawn(size, fn, timeout=120, context_timeout=60)
    assert len(set(results)) == 1, "ranks elected different tables"
    table = json.loads(results[0])
    entries = table["entries"]
    assert entries, "tuner produced an empty table"
    assert all(e["world_size"] == size for e in entries)
    collectives = {e["collective"] for e in entries}
    assert collectives == {"allreduce", "reduce", "reduce_scatter"}
    algos = {e["algorithm"] for e in entries}
    if size == 3:  # non-power-of-2: both hd sub-variants swept
        assert {"hd_fold", "hd_blocks"} <= algos, algos
    else:
        assert "halving_doubling" in algos, algos
    buckets = {e["bucket"] for e in entries}
    assert buckets == {12, 13, 14}, buckets


def test_tune_single_rank_installs_empty_table():
    def fn(ctx, rank):
        table = tuning.tune(ctx)
        assert table["entries"] == []
        # Untuned fallback still drives dispatch.
        x = np.ones(16, dtype=np.float32)
        ctx.allreduce(x)
        np.testing.assert_allclose(x, 1.0)

    spawn(1, fn)


def test_tune_on_forked_context_broadcast_election():
    """Forked contexts have no rendezvous store; the election must ride
    the context's own broadcast instead."""
    def fn(ctx, rank):
        child = ctx.fork()
        table = tuning.tune(child, min_bytes=4096, max_bytes=8192, iters=2,
                            warmup=0)
        assert table["entries"]
        return json.dumps(table, sort_keys=True)

    results = spawn(2, fn, timeout=120, context_timeout=60)
    assert len(set(results)) == 1


# ---- multiprocess rank consistency (the deployment shape) ----


_MP_WORKER = """
import hashlib, json, os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import gloo_tpu
from gloo_tpu import tuning

rank = {rank}; size = {size}
store = gloo_tpu.FileStore({store!r})
ctx = gloo_tpu.Context(rank, size, timeout=60.0)
ctx.connect_full_mesh(store, gloo_tpu.Device())
table = tuning.tune(ctx, min_bytes=4096, max_bytes=16384, iters=2,
                    warmup=1)
blob = json.dumps(tuning.installed_table(ctx), sort_keys=True)
print("TABLEHASH", hashlib.sha256(blob.encode()).hexdigest())
print("ENTRIES", len(table["entries"]))
x = np.full(1024, float(rank + 1), dtype=np.float32)
ctx.allreduce(x)
assert x[0] == sum(range(1, size + 1)), x[0]
ctx.barrier()
ctx.close()
print("WORKER-OK")
"""


def test_tune_multiprocess_rank_consistency():
    """Real child processes over a FileStore (the deployment shape):
    every rank's installed table must hash identically — the store-
    published rank-0 election, not per-rank measurements."""
    size = 2
    store = tempfile.mkdtemp(prefix="tctune-")
    procs = [subprocess.Popen(
        [sys.executable, "-c",
         textwrap.dedent(_MP_WORKER).format(repo=_REPO, rank=r, size=size,
                                            store=store)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r in range(size)]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, (out[-1500:], err[-1500:])
        assert "WORKER-OK" in out
    hashes = set()
    for out, _ in outs:
        line = [l for l in out.splitlines() if l.startswith("TABLEHASH")]
        assert line, out
        hashes.add(line[0].split()[1])
        entries = [l for l in out.splitlines() if l.startswith("ENTRIES")]
        assert int(entries[0].split()[1]) > 0
    assert len(hashes) == 1, f"ranks installed different tables: {hashes}"
