"""Prometheus text-exposition lint for utils.metrics.to_prometheus
(satellite of ISSUE 16).

A scrape endpoint that violates the exposition format fails silently:
Prometheus drops the whole scrape, dashboards flatline, and nobody sees
an error. These tests pin the format contract over a worst-case
synthetic snapshot (every optional section populated, label values full
of quotes/backslashes/newlines):

- exactly one ``# HELP`` and one ``# TYPE`` per family, in that order,
  BEFORE the family's first sample;
- no duplicate (name, labels) series;
- every non-comment line parses as ``name{labels} value`` with properly
  escaped label values;
- histogram buckets are cumulative and end with ``+Inf``.
"""

import re

from gloo_tpu.utils.metrics import to_prometheus

_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^{}]*\})?'
    r' (?P<value>[0-9eE.+-]+|NaN)$')
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _snapshot() -> dict:
    """Every section to_prometheus renders, with hostile label values
    (a transport-failure message's quotes/newlines are typical)."""
    hist = {"buckets": [[64, 2], [128, 1]], "count": 3, "sum_us": 200,
            "max_us": 120}
    return {
        "rank": 3,
        "group": 's1/g2"quoted\\back\nline',
        "ops": {'allreduce"x': {"calls": 5, "bytes": 512, "errors": 1,
                                "latency_us": hist}},
        "phases": {"allreduce": {"ring": {"wire_wait": hist}}},
        "transport": {"0": {
            "sent_msgs": 9, "sent_bytes": 900, "recv_msgs": 8,
            "recv_bytes": 800, "last_progress_age_us": 17,
            "recv_wait_us": hist,
            "tx_posts": 4, "bw_ewma_bps": 1.5e9, "rtt_ewma_us": 42.5,
            "chan_tx": {"0": 600, "1": 300}, "chan_rx": {"0": 800}}},
        "channels": {"0": {"tx_bytes": 600, "rx_bytes": 800}},
        "loops": {"0": {"events": 11, "last_progress_age_us": 3}},
        "retries": 1, "stash_pauses": 2, "trace_events_dropped": 0,
        "plan_hits": 7, "plan_misses": 2, "plan_evictions": 1,
        "ubuf_creates": 4,
        "faults": {"total": 2, "drop": 1, 'de"lay': 1},
        "anomalies": {"total": 2, "kinds": {
            "persistent_straggler": {"3": 1, "10": 1}}},
        "async": {"in_flight": 1, "engines": [
            {"per_lane": [{"submitted": 3, "completed": 2, "errors": 0}]}]},
        "elastic": {"epoch": 4, "size": 8, "leases_renewed": 99,
                    "rebuilds": 1, "bumps_published": 2},
        "watchdog": {"stalls": 1, "last": {
            "op": "allreduce", "peer": 2, "waited_us": 5000}},
    }


def _parse(text: str):
    """-> (help_lines, type_lines, samples) with per-family ordering
    checks applied along the way."""
    helps, types, samples = {}, {}, []
    opened = []  # family open order: HELP must immediately precede TYPE
    for ln, line in enumerate(text.splitlines(), 1):
        assert line == line.strip(), f"line {ln}: stray whitespace"
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in helps, f"duplicate # HELP {name}"
            helps[name] = ln
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            assert name not in types, f"duplicate # TYPE {name}"
            assert kind in ("counter", "gauge", "histogram"), line
            assert helps.get(name) == ln - 1, \
                f"# TYPE {name} not immediately after its # HELP"
            types[name] = kind
            opened.append(name)
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = _SAMPLE.match(line)
        assert m, f"unparseable sample line {ln}: {line!r}"
        samples.append((m["name"], m["labels"] or "", ln))
    return helps, types, samples


def _base_family(sample_name: str, families) -> str:
    """histogram samples append _bucket/_sum/_count to the family."""
    for suffix in ("_bucket", "_sum", "_count"):
        if (sample_name.endswith(suffix)
                and sample_name[:-len(suffix)] in families):
            return sample_name[:-len(suffix)]
    return sample_name


def test_exposition_format_contract():
    text = to_prometheus(_snapshot())
    helps, types, samples = _parse(text)

    seen = set()
    for name, labels, ln in samples:
        family = _base_family(name, types)
        assert family in types, f"sample {name} has no # TYPE"
        assert helps[family] < ln, \
            f"sample {name} before its family header"
        if name != family:
            assert types[family] == "histogram", name
        key = (name, labels)
        assert key not in seen, f"duplicate series: {name}{labels}"
        seen.add(key)
        # Label syntax: every k="v" pair must round-trip the escaping.
        if labels:
            inner = labels[1:-1]
            consumed = ",".join(m.group(0)
                                for m in _LABEL.finditer(inner))
            assert consumed == inner, f"bad label syntax: {labels!r}"

    # Families opened but never sampled are fine (empty sections);
    # families sampled but never opened are not (checked above). The
    # new fleet families must exist with samples.
    sampled = {_base_family(n, types) for n, _, _ in samples}
    for family in ("gloo_tpu_pair_bytes_total",
                   "gloo_tpu_pair_posts_total",
                   "gloo_tpu_pair_bw_ewma",
                   "gloo_tpu_pair_rtt_ewma_us",
                   "gloo_tpu_anomaly_total"):
        assert family in sampled, f"{family} missing from exposition"


def test_escaping_of_hostile_label_values():
    text = to_prometheus(_snapshot())
    # The raw hostile group tag must never appear unescaped: a literal
    # newline inside a label value splits the line and kills the scrape.
    assert '\nline"' not in text
    assert '\\nline' in text          # escaped newline survives
    assert '\\"quoted' in text        # escaped double-quote
    assert '\\\\back' in text         # escaped backslash
    for line in text.splitlines():
        if not line.startswith("#"):
            assert _SAMPLE.match(line), repr(line)


def test_anomaly_family_blamed_rank_labels():
    """gloo_tpu_anomaly_total: the 'rank' label is the BLAMED rank, not
    the emitting rank — one series per (kind, blamed), numerically
    sorted (rank 10 after rank 3, not lexically before)."""
    text = to_prometheus(_snapshot())
    rows = [l for l in text.splitlines()
            if l.startswith("gloo_tpu_anomaly_total{")]
    assert len(rows) == 2
    assert 'rank="3"' in rows[0] and 'rank="10"' in rows[1]
    assert all('kind="persistent_straggler"' in r for r in rows)


def test_histogram_buckets_cumulative_with_inf():
    text = to_prometheus(_snapshot())
    buckets = [l for l in text.splitlines()
               if l.startswith("gloo_tpu_collective_latency_us_bucket")]
    values = [float(l.rsplit(" ", 1)[1]) for l in buckets]
    assert values == sorted(values), "buckets must be cumulative"
    assert 'le="+Inf"' in buckets[-1]
    assert values[-1] == 3  # == count
