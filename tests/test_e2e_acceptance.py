"""Flagship end-to-end acceptance run: the whole product in ONE job.

8 worker processes, each a simulated host with a 2-device local CPU
mesh (16 devices total), composing every subsystem in sequence:

  1. `gloo_tpu.init_from_env()` bootstrap from torchrun-style env vars
     (rank 0 serves the TcpStore; everyone full-meshes through it);
  2. hierarchical DDP training (`make_hierarchical_ddp`): gradients
     mean over the local device mesh inside the jitted step, then
     across hosts through the C++ transport;
  3. rank 7 SIGKILLs itself mid-training;
  4. survivors hit IoError, re-rendezvous with
     `gloo_tpu.resilience.rebuild_after_failure` through the SAME
     TcpStore, and come back as a contiguous 7-host group;
  5. `gloo_tpu.checkpoint.StepCheckpointer.load_latest` restores the
     last committed step and training resumes to completion in the
     shrunken world, with end-state parameters asserted identical
     across every surviving rank.

This is the single-run composition of SURVEY.md §7 M2's "ONE model
end-to-end" story — each piece has its own test elsewhere; this proves
they compose. Referenced from README ("The acceptance run").
"""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap

import pytest

pytest.importorskip("jax")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SIZE = 8
KILL_RANK = 7          # never rank 0: it owns the TcpStore server
KILL_STEP = 6
TOTAL_STEPS = 12
CKPT_EVERY = 2

WORKER = textwrap.dedent("""
    import os, signal, sys, time
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    import numpy as np
    import jax, jax.numpy as jnp, optax
    # The environment may have pinned JAX_PLATFORMS to a TPU plugin at
    # interpreter start (sitecustomize imports jax before this script
    # runs), so the env-var assignment above can be too late — override
    # through the config like tests/conftest.py does.
    jax.config.update("jax_platforms", "cpu")
    # The hierarchical layer must actually be hierarchical: without the
    # 2-device local mesh, make_hierarchical_ddp silently degrades to
    # plain value_and_grad and this test stops covering the device-mesh
    # stage it advertises.
    assert jax.local_device_count() == 2, jax.devices()
    import gloo_tpu
    from gloo_tpu.checkpoint import StepCheckpointer
    from gloo_tpu.resilience import rebuild_after_failure
    from gloo_tpu.tpu import HierarchicalGroup, make_hierarchical_ddp

    KILL_RANK, KILL_STEP = {kill_rank}, {kill_step}
    TOTAL_STEPS, CKPT_EVERY = {total_steps}, {ckpt_every}
    ckpt_dir = sys.argv[1]

    # 1. launcher-env bootstrap (torchrun-style vars set by the parent)
    ctx, server = gloo_tpu.init_from_env(timeout=60.0)
    rank, size = ctx.rank, ctx.size
    print(f"rank {{rank}}: bootstrapped {{rank}}/{{size}}", flush=True)

    # tiny least-squares model so loss strictly decreases under SGD
    w_true = np.linspace(-1.0, 1.0, 8).astype(np.float32)
    rng = np.random.RandomState(1234 + rank)

    def make_batch():
        x = rng.randn(4, 8).astype(np.float32)
        y = x @ w_true
        return {{"x": x, "y": y}}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean(jnp.square(pred - batch["y"]))

    params = {{"w": jnp.zeros(8, jnp.float32)}}
    optimizer = optax.sgd(0.1)
    opt_state = optimizer.init(params)

    def make_step(c):
        group = HierarchicalGroup(c)
        return make_hierarchical_ddp(loss_fn, optimizer, group)

    step_fn = make_step(ctx)
    ckpt = StepCheckpointer(ckpt_dir, keep=3)

    step = 0
    rebuilt = False
    first_loss = None
    while step < TOTAL_STEPS:
        if rank == KILL_RANK and step == KILL_STEP:
            os.kill(os.getpid(), signal.SIGKILL)   # 3. hard failure
        try:
            params, opt_state, loss = step_fn(params, opt_state,
                                              make_batch())
        except gloo_tpu.IoError as exc:
            assert not rebuilt, "second failure not part of this script"
            print(f"rank {{rank}}: step {{step}} failed "
                  f"({{str(exc)[:40]}}); rebuilding", flush=True)
            # 4. survivors re-rendezvous through the SAME store
            store = gloo_tpu.TcpStore(
                os.environ["MASTER_ADDR"], int(os.environ["MASTER_PORT"]))
            ctx.close()
            ctx, rank, size = rebuild_after_failure(
                store, gloo_tpu.Device(), old_rank=rank, old_size=size,
                generation=1, settle=3.0, timeout=60.0)
            assert ctx is not None and size == {size} - 1, (rank, size)
            step_fn = make_step(ctx)
            # 5. resume from the last committed checkpoint
            ck_step, state = ckpt.load_latest()
            assert ck_step is not None, "no committed checkpoint found"
            params = {{"w": jnp.asarray(state["w"])}}
            opt_state = optimizer.init(params)
            step = int(state["step"])
            rebuilt = True
            print(f"rank {{rank}}: resumed from step {{ck_step}} "
                  f"(train step {{step}}) in world of {{size}}",
                  flush=True)
            continue
        loss = float(loss)
        if first_loss is None:
            first_loss = loss
        if rank == 0 and step % CKPT_EVERY == 0:
            # force=True: post-resume replay re-saves steps that already
            # have committed directories from before the failure.
            ckpt.save(step, {{"w": np.asarray(params["w"]),
                              "step": step}}, force=True)
        step += 1

    assert rebuilt, "the failure/rebuild path never ran"
    assert loss < first_loss, (first_loss, loss)
    # end-state params bitwise-identical across the surviving world
    final = np.asarray(params["w"], dtype=np.float32)
    gathered = ctx.allgather(final)
    for row in gathered:
        assert np.array_equal(np.asarray(row), final), "params diverged"
    ctx.barrier()
    print(f"rank {{rank}}: DONE loss {{first_loss:.4f}} -> {{loss:.4f}}",
          flush=True)
""").format(repo=_REPO, kill_rank=KILL_RANK, kill_step=KILL_STEP,
            total_steps=TOTAL_STEPS, ckpt_every=CKPT_EVERY, size=SIZE)


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_flagship_acceptance_run():
    ckpt_dir = tempfile.mkdtemp()
    port = _free_port()
    procs = []
    for r in range(SIZE):
        env = dict(os.environ, RANK=str(r), WORLD_SIZE=str(SIZE),
                   MASTER_ADDR="127.0.0.1", MASTER_PORT=str(port))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER, ckpt_dir], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    try:
        outs = [p.communicate(timeout=900)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    codes = [p.returncode for p in procs]
    assert codes[KILL_RANK] == -signal.SIGKILL, (codes, outs[KILL_RANK])
    for r in range(SIZE):
        if r == KILL_RANK:
            continue
        assert codes[r] == 0, (r, codes, outs[r][-2000:])
        assert "resumed from step" in outs[r], (r, outs[r][-2000:])
        assert "DONE" in outs[r], (r, outs[r][-2000:])


if __name__ == "__main__":
    test_flagship_acceptance_run()
    print("acceptance run OK")
