"""Phase-level collective profiler + live telemetry endpoint (ISSUE 15,
docs/profiling.md):

- phase sums reconcile with the op's metrics-histogram latency;
- per-op breakdowns join the flight recorder's streams by cseq;
- the bounded ring counts drops; TPUCOLL_PROFILE=0 leaves no records;
- cross-rank attribution blames the rank a chaos schedule delayed;
- /healthz flips non-200 while the watchdog stall is fresh and recovers;
- strict env knob matrix (TPUCOLL_PROFILE, TPUCOLL_PROFILE_RING,
  TPUCOLL_TELEMETRY_PORT);
- same-seed chaos produces identical per-rank phase-sequence streams.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import gloo_tpu
from gloo_tpu import fault
from gloo_tpu.utils import metrics as metrics_util
from gloo_tpu.utils import profile as profile_util
from gloo_tpu.utils import telemetry
from harness import spawn

PHASE_NAMES = {"pack", "post", "wire_wait", "reduce", "unpack",
               "intra", "inter", "fanout"}


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read().decode() or "{}")


def test_phase_sums_reconcile_with_metrics_latency():
    """Sum of a profiled op's phases is bounded by (and, for a payload
    where waits dominate, close to) the op's metrics-histogram latency
    — the phases decompose the same wall time the histogram records."""

    def body(ctx, rank):
        x = np.ones(1 << 20, dtype=np.float32)  # 4 MiB
        ctx.allreduce(x, algorithm="ring")  # warm plans/registrations
        ctx.metrics(drain=True)
        ctx.allreduce(x, algorithm="ring")
        snap = ctx.metrics()
        prof = ctx.profile()
        return snap, prof

    for snap, prof in spawn(2, body):
        ops = [o for o in prof["ops"] if o["op"] == "allreduce"]
        timed = ops[-1]
        assert timed["algo"] == "ring", timed
        assert set(timed["phases"]) <= PHASE_NAMES, timed
        phase_sum = sum(timed["phases"].values())
        total = timed["total_us"]
        # Disjoint sub-intervals of the op: the sum can't exceed the
        # op's own span beyond clock granularity...
        assert phase_sum <= total * 1.05 + 200, (phase_sum, total)
        # ...and posts+waits+reduce dominate a 4 MiB ring op.
        assert phase_sum >= 0.3 * total, (phase_sum, total)
        # The metrics histogram recorded the same single op, a strict
        # superset of the profiled span (MetricsOp opens first).
        hist = snap["ops"]["allreduce"]["latency_us"]
        assert hist["count"] == 1, hist
        assert total <= hist["sum_us"] * 1.05 + 200, (total, hist)
        assert phase_sum <= hist["sum_us"] * 1.05 + 200


def test_cseq_joins_flightrec_streams():
    """Every profiled collective joins the flight recorder's record at
    the same cseq — same op name, same resolved algorithm — so one
    rank's phase breakdown can be lined up against another's."""

    def body(ctx, rank):
        x = np.ones(4096, dtype=np.float32)
        ctx.allreduce(x, algorithm="ring")
        ctx.barrier()
        out = np.zeros(4096 * 2, dtype=np.float32)
        ctx.allgather(x, output=out)
        return ctx.profile(), ctx.flightrec()

    results = spawn(2, body)
    for prof, fr in results:
        fr_by_cseq = {e["cseq"]: e for e in fr["events"]
                      if e.get("cseq") is not None}
        assert len(prof["ops"]) == 3
        for op in prof["ops"]:
            assert op["cseq"] in fr_by_cseq, (op, sorted(fr_by_cseq))
            event = fr_by_cseq[op["cseq"]]
            assert event["op"] == op["op"], (op, event)
            assert event["algo"] == op["algo"], (op, event)
    # And the cseq axis is cross-rank: rank 0 and rank 1 profiled the
    # same (cseq, op) sequence.
    seq0 = [(o["cseq"], o["op"]) for o in results[0][0]["ops"]]
    seq1 = [(o["cseq"], o["op"]) for o in results[1][0]["ops"]]
    assert seq0 == seq1


def test_bounded_ring_drop_counter(monkeypatch):
    monkeypatch.setenv("TPUCOLL_PROFILE_RING", "8")

    def body(ctx, rank):
        for _ in range(20):
            ctx.barrier()
        return ctx.profile()

    for prof in spawn(2, body):
        assert prof["capacity"] == 8, prof["capacity"]
        assert prof["next_seq"] == 20
        assert prof["dropped"] == 12
        assert len(prof["ops"]) == 8
        # The ring keeps the LAST 8 ops.
        assert [o["seq"] for o in prof["ops"]] == list(range(12, 20))


def test_profile_off_leaves_no_records(monkeypatch):
    """TPUCOLL_PROFILE=0: the entry gate is the only cost — no ring
    rows, no phase histograms in the metrics registry."""
    monkeypatch.setenv("TPUCOLL_PROFILE", "0")

    def body(ctx, rank):
        x = np.ones(1 << 16, dtype=np.float32)
        ctx.allreduce(x)
        ctx.barrier()
        return ctx.profile(), ctx.metrics()

    for prof, snap in spawn(2, body):
        assert prof["enabled"] is False
        assert prof["next_seq"] == 0
        assert prof["ops"] == []
        assert snap["phases"] == {}, snap["phases"]


def test_runtime_toggle():
    def body(ctx, rank):
        assert ctx.profile_enabled()
        ctx.profile_enable(False)
        ctx.barrier()
        off = ctx.profile()["next_seq"]
        ctx.profile_enable(True)
        ctx.barrier()
        on = ctx.profile()["next_seq"]
        return off, on

    for off, on in spawn(2, body):
        assert off == 0
        assert on == 1


def test_phase_histograms_flow_to_prometheus():
    """The per-(op, algorithm, phase) aggregates land in the metrics
    snapshot and render as the gloo_tpu_phase_latency_us family."""

    def body(ctx, rank):
        x = np.ones(1 << 18, dtype=np.float32)
        ctx.allreduce(x, algorithm="ring")
        return ctx.metrics()

    snap = spawn(2, body)[0]
    assert "ring" in snap["phases"]["allreduce"], snap["phases"]
    ring = snap["phases"]["allreduce"]["ring"]
    assert "wire_wait" in ring and ring["wire_wait"]["count"] >= 1
    text = metrics_util.to_prometheus(snap)
    assert 'gloo_tpu_phase_latency_us_count{algorithm="ring",' \
        in text and 'phase="wire_wait"' in text, text[:2000]
    # Drain resets the phase aggregates with the rest of the registry.


def test_metrics_disable_freezes_phase_aggregates():
    """ctx.metrics_enable(False) freezes the WHOLE registry — the
    phase-histogram flush honors the same gate as every other recorder
    — while the profiler's own per-op ring keeps recording (it has its
    own gate)."""

    def body(ctx, rank):
        ctx.metrics_enable(False)
        x = np.ones(4096, dtype=np.float32)
        ctx.allreduce(x, algorithm="ring")
        snap = ctx.metrics()
        prof = ctx.profile()
        ctx.metrics_enable(True)
        return snap, prof

    for snap, prof in spawn(2, body):
        assert snap["phases"] == {}, snap["phases"]
        # connect was counted before the disable; the op itself wasn't.
        assert "allreduce" not in snap["ops"], snap["ops"]
        assert prof["next_seq"] == 1 and prof["ops"], prof


def test_merge_duplicate_rank_snapshots_never_mix():
    """Two snapshots for one rank (stale dump beside a live fetch): the
    last wins wholesale — per-cseq ops from different snapshots of one
    rank must never interleave — and the rank is reported."""
    old = {"rank": 0, "size": 2, "ops": [
        {"cseq": 0, "op": "allreduce", "algo": "ring", "bytes": 4,
         "start_us": 0, "total_us": 10, "phases": {"wire_wait": 9}},
        {"cseq": 1, "op": "barrier", "algo": None, "bytes": 0,
         "start_us": 20, "total_us": 5, "phases": {"wire_wait": 4}}]}
    new = {"rank": 0, "size": 2, "ops": [
        {"cseq": 2, "op": "allreduce", "algo": "ring", "bytes": 4,
         "start_us": 40, "total_us": 12, "phases": {"wire_wait": 11}}]}
    peer = {"rank": 1, "size": 2, "ops": [
        {"cseq": 2, "op": "allreduce", "algo": "ring", "bytes": 4,
         "start_us": 7, "total_us": 12, "phases": {"wire_wait": 2}}]}
    merged = profile_util.merge([old, new, peer])
    assert merged["ranks"] == [0, 1]
    assert merged["duplicates"] == [0]
    # Only the LAST rank-0 snapshot's ops participate.
    assert sorted(merged["ops"]) == [2], merged["ops"]


def test_merge_never_joins_across_groups():
    """Split sub-groups renumber ranks and run independent schedules —
    their cseq axes must never be compared. merge() keeps one group
    (noting the skipped ones); merge_by_group partitions a mixed set."""
    def snap(rank, group, cseq):
        return {"rank": rank, "size": 2, "group": group, "ops": [
            {"cseq": cseq, "op": "allreduce", "algo": "ring",
             "bytes": 4, "start_us": 0, "total_us": 10,
             "phases": {"wire_wait": 5}}]}

    a0, a1 = snap(0, "s1.0.c0", 5), snap(1, "s1.0.c0", 5)
    b1 = snap(1, "s1.0.c1", 5)
    merged = profile_util.merge([a0, a1, b1])
    assert merged["group"] == "s1.0.c0"
    assert merged["ranks"] == [0, 1]
    assert merged["skipped_groups"] == ["s1.0.c1"]
    # Group B's rank 1 must not have displaced group A's.
    assert merged["ops"][5][1] is a1["ops"][0]
    by_group = profile_util.merge_by_group([a0, a1, b1])
    assert sorted(by_group) == ["s1.0.c0", "s1.0.c1"]
    assert by_group["s1.0.c1"]["ranks"] == [1]


def test_metrics_drain_resets_phase_histograms():
    def body(ctx, rank):
        x = np.ones(4096, dtype=np.float32)
        ctx.allreduce(x, algorithm="ring")
        ctx.metrics(drain=True)
        return ctx.metrics()

    snap = spawn(2, body)[0]
    for algos in snap["phases"].values():
        for phases in algos.values():
            for hist in phases.values():
                assert hist["count"] == 0, snap["phases"]


@pytest.mark.parametrize("var,value", [
    ("TPUCOLL_PROFILE", "banana"),
    ("TPUCOLL_PROFILE", "2"),
    ("TPUCOLL_PROFILE_RING", "0"),
    ("TPUCOLL_PROFILE_RING", "many"),
    ("TPUCOLL_PROFILE_RING", "-4"),
])
def test_strict_env_knobs(monkeypatch, var, value):
    """Malformed profiler knobs fail loudly at Context construction
    (common/env.h strict parsers), never silently fall back."""
    monkeypatch.setenv(var, value)
    with pytest.raises(gloo_tpu.Error, match=var):
        gloo_tpu.Context(0, 1)


@pytest.mark.parametrize("value", ["abc", "70000", "-1"])
def test_strict_telemetry_port(monkeypatch, value):
    monkeypatch.setenv("TPUCOLL_TELEMETRY_PORT", value)

    def body(ctx, rank):
        with pytest.raises(ValueError, match="TPUCOLL_TELEMETRY_PORT"):
            telemetry.serve_telemetry(ctx)

    spawn(1, body)


def test_telemetry_routes():
    """All five routes against a live context: /metrics exposition,
    /healthz 200, /profile.json + /flightrec rings, the guarded POST
    /flightrec/dump (405 on GET) — and, with a token configured, EVERY
    route requires it (403 without, header or ?token= accepted)."""

    def body(ctx, rank):
        x = np.ones(1 << 14, dtype=np.float32)
        ctx.allreduce(x)
        with telemetry.serve_telemetry(ctx, token="s3cret") as srv:
            # Unauthenticated: every route refuses, GET and POST alike.
            status, _ = _get(srv.url + "/healthz")
            assert status == 403
            req = urllib.request.Request(srv.url + "/flightrec/dump",
                                         method="POST")
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(req, timeout=10)
            assert err.value.code == 403
            # Authenticated via ?token= query parameter...
            status, hz = _get(srv.url + "/healthz?token=s3cret")
            assert status == 200 and hz["ok"], hz
            # ...and via the header.
            tok = {"X-TpuColl-Token": "s3cret"}

            def get(path):
                req = urllib.request.Request(srv.url + path, headers=tok)
                try:
                    with urllib.request.urlopen(req, timeout=10) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            status, text = get("/metrics")
            assert status == 200
            assert b"gloo_tpu_collective_calls_total" in text
            assert b"gloo_tpu_phase_latency_us" in text
            status, prof = get("/profile.json")
            assert status == 200 and json.loads(prof)["ops"]
            status, fr = get("/flightrec")
            assert status == 200 and json.loads(fr)["events"]
            status, _ = get("/flightrec/dump")
            assert status == 405
            req = urllib.request.Request(
                srv.url + "/flightrec/dump", method="POST", headers=tok)
            with urllib.request.urlopen(req, timeout=10) as resp:
                doc = json.load(resp)
            assert doc["path"].endswith(f"flightrec-rank{rank}.json")
            status, _ = get("/nope")
            assert status == 404
        # A split sub-context's dump route mirrors the native tagged
        # naming (flightrec-rank<r>-g<tag>.json), so same-rank contexts
        # sharing TPUCOLL_FLIGHTREC_DIR never overwrite each other.
        sub = ctx.split(0, tag=11)
        try:
            with telemetry.serve_telemetry(sub) as ssrv:
                req = urllib.request.Request(
                    ssrv.url + "/flightrec/dump", method="POST")
                with urllib.request.urlopen(req, timeout=10) as resp:
                    doc = json.load(resp)
            tag = sub.group_tag().replace("/", ".")
            assert tag and doc["path"].endswith(
                f"flightrec-rank{sub.rank}-g{tag}.json"), doc
        finally:
            sub.close()

    spawn(2, body)


def test_healthz_unresolved_stall_stays_unhealthy():
    """The watchdog fires at most once per blocked wait, so /healthz
    must not age a WEDGED rank back to healthy: with the blamed peer
    showing no transport progress since detection the verdict stays
    unhealthy regardless of the stall's age; once the peer progressed,
    age governs (pure-function check over synthetic snapshots)."""
    stall = {"op": "recv", "peer": 1, "slot": 7, "waited_us": 200_000,
             "at_us": 10_000_000, "age_us": 60_000_000}
    base = {"rank": 0, "group": "", "watchdog_ms": 150,
            "watchdog": {"stalls": 1, "last": dict(stall)},
            "transport": {1: {"last_progress_us": 9_000_000}}}
    # Peer never progressed past the stall: unhealthy despite 60s age.
    verdict = telemetry.healthz(base)
    assert not verdict["ok"] and "unresolved" in verdict["reasons"][0], \
        verdict
    # Peer progressed after detection + record aged out: healthy.
    resumed = dict(base,
                   transport={1: {"last_progress_us": 11_000_000}})
    assert telemetry.healthz(resumed)["ok"], telemetry.healthz(resumed)
    # Peer progressed but the record is still fresh: unhealthy.
    fresh = dict(resumed, watchdog={"stalls": 1,
                                    "last": dict(stall, age_us=100_000)})
    assert not telemetry.healthz(fresh)["ok"]
    # Unknown peer (recv-from-any): falls back to freshness alone.
    anypeer = dict(base, watchdog={"stalls": 1,
                                   "last": dict(stall, peer=-1)})
    assert telemetry.healthz(anypeer)["ok"]
    # String-keyed transport (raw JSON snapshot) resolves the same way.
    rawkeys = dict(base,
                   transport={"1": {"last_progress_us": 11_000_000}})
    assert telemetry.healthz(rawkeys)["ok"]


def test_attribution_blames_delayed_rank():
    """Chaos-grounded attribution: a PR 3 fault schedule delays rank
    1's data sends 50 ms mid-allreduce at P=3; the merged cross-rank
    attribution must blame rank 1 — the other ranks' wire_wait excess
    over the cross-rank minimum points at the straggler."""
    fault.install({"seed": 7, "faults": [
        {"when": {"rank": 1, "opcode": "data", "min_bytes": 1024},
         "action": "delay", "ms": 50, "count": 6}]})
    try:
        def body(ctx, rank):
            x = np.ones(1 << 18, dtype=np.float32)  # 1 MiB
            for _ in range(4):
                ctx.allreduce(x, algorithm="ring")
            return ctx.profile()

        snaps = spawn(3, body)
    finally:
        fired = fault.report()
        fault.clear()
    assert any(e["action"] == "delay" and e["rank"] == 1 for e in fired), \
        fired
    merged = profile_util.merge(snaps)
    attributed = profile_util.attribute(merged)
    board = profile_util.leaderboard(attributed)
    assert board[0]["rank"] == 1, board
    # The blamed time must reflect the injected delays (6 x 50 ms fired
    # across the job, each stalling at least one peer's wire phase).
    assert board[0]["blamed_us"] > 50_000, board
    blamed = [o["straggler"] for o in attributed["ops"]
              if o["excess_us"] > 30_000]
    assert blamed and all(r == 1 for r in blamed), attributed["ops"]


def test_healthz_flips_on_watchdog_stall_and_recovers():
    """A stalled peer trips the watchdog on the blocked rank; its
    /healthz serves 503 while the stall record is fresh and recovers to
    200 once the window passes. The stalling rank itself (which never
    waited) stays 200 throughout."""
    fault.install({"seed": 8, "faults": [
        {"when": {"rank": 1, "opcode": "data", "nth": 1},
         "action": "stall", "ms": 1200, "count": 1}]})
    try:
        def body(ctx, rank):
            ctx.set_watchdog(0.15)
            x = np.ones(1 << 16, dtype=np.float32)
            ctx.allreduce(x, algorithm="ring")
            snap = ctx.metrics()
            last = snap["watchdog"]["last"]
            if not last or last.get("peer") != 1:
                # Not the blocked observer (e.g. the stalling rank).
                with telemetry.serve_telemetry(ctx) as srv:
                    status, hz = _get(srv.url + "/healthz")
                return ("healthy", status, hz)
            age_ms = last["age_us"] / 1000.0
            window = age_ms + 2000.0
            with telemetry.serve_telemetry(
                    ctx, stall_window_ms=window) as srv:
                status1, hz1 = _get(srv.url + "/healthz")
                deadline = time.monotonic() + 15.0
                status2, hz2 = status1, hz1
                while time.monotonic() < deadline and status2 != 200:
                    time.sleep(0.3)
                    status2, hz2 = _get(srv.url + "/healthz")
            return ("stalled", status1, hz1, status2, hz2)

        results = spawn(3, body, timeout=90, context_timeout=60)
    finally:
        fault.clear()
    stalled = [r for r in results if r[0] == "stalled"]
    assert stalled, results  # someone must have observed the stall
    for _, status1, hz1, status2, hz2 in stalled:
        assert status1 == 503, hz1
        assert any("watchdog stall" in why for why in hz1["reasons"]), hz1
        assert status2 == 200, hz2
    for r in results:
        if r[0] == "healthy":
            assert r[1] == 200, r


def test_same_seed_chaos_identical_phase_streams():
    """Same seed + schedule + workload => every rank's profiled
    (cseq, op, algo) stream is identical across runs (timings differ;
    the SEQUENCE is deterministic, like the flight recorder's)."""
    schedule = {"seed": 21, "faults": [
        {"when": {"rank": 1, "opcode": "data"},
         "action": "delay", "ms": 5, "prob": 0.5, "count": 8}]}

    def run_once():
        fault.install(schedule)
        try:
            def body(ctx, rank):
                x = np.ones(1 << 14, dtype=np.float32)
                for _ in range(3):
                    ctx.allreduce(x, algorithm="ring")
                ctx.barrier()
                return [(o["cseq"], o["op"], o["algo"])
                        for o in ctx.profile()["ops"]]

            streams = spawn(3, body)
            return streams, fault.report()
        finally:
            fault.clear()

    streams_a, report_a = run_once()
    streams_b, report_b = run_once()
    assert streams_a == streams_b
    strip = lambda rep: [  # noqa: E731 - local normalization
        {k: e[k] for k in ("rule", "rank", "action", "n")}
        for e in rep]
    assert strip(report_a) == strip(report_b)
