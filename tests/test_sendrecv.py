"""Point-to-point semantics: tagged send/recv, offsets, recv-from-any,
zero-byte messages, self-send, abortable waits (reference analog:
gloo/test/send_recv_test.cc:26-512)."""

import time

import numpy as np
import pytest

from tests.harness import spawn


def test_pairwise_send_recv():
    """Every rank sends its rank value to every other rank."""
    size = 4

    def fn(ctx, rank):
        out = {}
        bufs = []
        recv_arrays = {}
        for peer in range(size):
            if peer == rank:
                continue
            send_arr = np.array([rank], dtype=np.int64)
            recv_arr = np.empty(1, dtype=np.int64)
            recv_arrays[peer] = recv_arr
            sbuf = ctx.register(send_arr)
            rbuf = ctx.register(recv_arr)
            sbuf.send(peer, slot=rank * size + peer)
            rbuf.recv(peer, slot=peer * size + rank)
            bufs.append((sbuf, rbuf, send_arr))
        for sbuf, rbuf, _ in bufs:
            assert sbuf.wait_send() is True
            assert rbuf.wait_recv() is not None
        for peer, arr in recv_arrays.items():
            out[peer] = int(arr[0])
        return out

    results = spawn(size, fn)
    for rank, got in enumerate(results):
        assert got == {p: p for p in range(size) if p != rank}


def test_send_recv_offsets():
    def fn(ctx, rank):
        if rank == 0:
            arr = np.arange(10, dtype=np.float32)
            buf = ctx.register(arr)
            # Send elements [4, 6) only.
            buf.send(1, slot=7, offset=16, nbytes=8)
            buf.wait_send()
            return None
        arr = np.zeros(4, dtype=np.float32)
        buf = ctx.register(arr)
        # Land them at elements [1, 3).
        buf.recv(0, slot=7, offset=4, nbytes=8)
        buf.wait_recv()
        return arr.tolist()

    results = spawn(2, fn)
    assert results[1] == [0.0, 4.0, 5.0, 0.0]


def test_zero_byte_then_nonempty():
    """Empty messages are real messages: ordering and matching still hold."""

    def fn(ctx, rank):
        if rank == 0:
            empty = np.empty(0, dtype=np.uint8)
            data = np.array([123], dtype=np.uint8)
            b1 = ctx.register(empty)
            b2 = ctx.register(data)
            b1.send(1, slot=1)
            b2.send(1, slot=2)
            b1.wait_send()
            b2.wait_send()
            return None
        empty = np.empty(0, dtype=np.uint8)
        data = np.zeros(1, dtype=np.uint8)
        b1 = ctx.register(empty)
        b2 = ctx.register(data)
        b1.recv(0, slot=1)
        b2.recv(0, slot=2)
        assert b1.wait_recv() == 0
        assert b2.wait_recv() == 0
        return int(data[0])

    assert spawn(2, fn)[1] == 123


def test_recv_from_any():
    """Rank 0 posts wildcard receives and must see every sender exactly once."""
    size = 4

    def fn(ctx, rank):
        if rank == 0:
            seen = []
            arr = np.zeros(1, dtype=np.int32)
            buf = ctx.register(arr)
            for _ in range(size - 1):
                buf.recv(list(range(1, size)), slot=5)
                src = buf.wait_recv()
                assert arr[0] == src * 10
                seen.append(src)
            return sorted(seen)
        arr = np.array([rank * 10], dtype=np.int32)
        buf = ctx.register(arr)
        buf.send(0, slot=5)
        buf.wait_send()
        return None

    assert spawn(size, fn)[0] == [1, 2, 3]


def test_self_send():
    def fn(ctx, rank):
        send = np.array([7.5], dtype=np.float64)
        recv = np.zeros(1, dtype=np.float64)
        sbuf = ctx.register(send)
        rbuf = ctx.register(recv)
        sbuf.send(rank, slot=3)
        rbuf.recv(rank, slot=3)
        sbuf.wait_send()
        assert rbuf.wait_recv() == rank
        return float(recv[0])

    assert spawn(2, fn) == [7.5, 7.5]


def test_abort_wait_recv():
    def fn(ctx, rank):
        if rank == 1:
            # Stay alive until rank 0 has finished its abort sequence.
            ctx.barrier(tag=42)
            return None
        arr = np.zeros(1, dtype=np.float32)
        buf = ctx.register(arr)
        buf.recv(1, slot=9)
        import threading

        threading.Timer(0.2, buf.abort_wait_recv).start()
        t0 = time.monotonic()
        result = buf.wait_recv(timeout=10.0)
        assert result is None  # aborted
        assert time.monotonic() - t0 < 5.0
        del buf  # cancels the still-posted recv
        ctx.barrier(tag=42)
        return "aborted"

    assert spawn(2, fn)[0] == "aborted"


def test_wait_recv_timeout():
    def fn(ctx, rank):
        if rank == 1:
            ctx.barrier(tag=99)
            return None
        arr = np.zeros(1, dtype=np.float32)
        buf = ctx.register(arr)
        buf.recv(1, slot=11)
        with pytest.raises(gloo_tpu_timeout()):
            buf.wait_recv(timeout=0.3)
        ctx.barrier(tag=99)
        return "timed-out"

    assert spawn(2, fn)[0] == "timed-out"


def gloo_tpu_timeout():
    import gloo_tpu

    return gloo_tpu.TimeoutError


def test_busy_poll_mode():
    """Sync/busy-poll devices (reference: tcp setSync + MSG_DONTWAIT)
    must run the same collectives and p2p traffic correctly — the mode
    only changes HOW completions are awaited (spin vs condvar)."""
    def fn(ctx, rank):
        x = np.full(1000, float(rank + 1), np.float32)
        ctx.allreduce(x)
        if rank == 0:
            ctx.send(np.arange(64, dtype=np.float64), dst=1, slot=77)
            return x[0]
        got = np.zeros(64, dtype=np.float64)
        ctx.recv(got, src=0, slot=77)
        np.testing.assert_array_equal(got, np.arange(64, dtype=np.float64))
        return x[0]

    from tests.harness import _device_kwargs
    results = spawn(2, fn,
                    device_kwargs={**_device_kwargs(), "busy_poll": True})
    assert results == [3.0, 3.0]
