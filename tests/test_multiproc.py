"""Mode-2 tests: real child processes rendezvousing over a FileStore, with
real failure injection (reference analog: gloo/test/multiproc_test.h:29-133
and transport_test.cc IoErrors/IoTimeouts — kill a rank, assert peers fail
fast with an IoError instead of hanging)."""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_worker(body: str, rank: int, size: int, store: str):
    """Launch a child process running `body` with ctx/rank/size bound."""
    prog = textwrap.dedent("""
        import os, signal, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = {rank}; size = {size}
        store = gloo_tpu.FileStore({store!r})
        ctx = gloo_tpu.Context(rank, size, timeout=10.0)
        ctx.connect_full_mesh(store, gloo_tpu.Device())
    """).format(repo=_REPO, rank=rank, size=size, store=store) + \
        textwrap.dedent(body)
    return subprocess.Popen([sys.executable, "-c", prog],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


KILL_BODY = """
if rank == 1:
    os.kill(os.getpid(), signal.SIGKILL)
x = np.ones(1 << 20, dtype=np.float32)
t0 = time.monotonic()
try:
    ctx.allreduce(x)
    print("UNEXPECTED-SUCCESS")
    sys.exit(3)
except gloo_tpu.IoError:
    elapsed = time.monotonic() - t0
    print(f"IOERROR {elapsed:.3f}")
    sys.exit(10)
"""


def test_peer_killed_mid_collective():
    """SIGKILL one rank; survivors must exit with IoError well inside the
    context timeout (fast failure detection, not timeout expiry)."""
    store = tempfile.mkdtemp()
    procs = [_spawn_worker(KILL_BODY, r, 3, store) for r in range(3)]
    outs = [p.communicate(timeout=60) for p in procs]
    codes = [p.returncode for p in procs]
    assert codes[1] == -signal.SIGKILL
    for r in (0, 2):
        assert codes[r] == 10, (r, codes[r], outs[r])
        line = [l for l in outs[r][0].splitlines() if l.startswith("IOERROR")]
        assert line, outs[r]
        elapsed = float(line[0].split()[1])
        assert elapsed < 5.0, f"rank {r} took {elapsed}s to detect failure"


TIMEOUT_BODY = """
if rank == 1:
    time.sleep(6)     # miss the collective entirely, then exit cleanly
    sys.exit(0)
x = np.ones(4, dtype=np.float32)
t0 = time.monotonic()
try:
    ctx.allreduce(x, timeout=2.0)
    print("UNEXPECTED-SUCCESS"); sys.exit(3)
except gloo_tpu.TimeoutError:
    print(f"TIMEOUT {time.monotonic()-t0:.3f}"); sys.exit(11)
except gloo_tpu.IoError:
    print(f"IOERROR {time.monotonic()-t0:.3f}"); sys.exit(12)
"""


def test_slow_peer_hits_op_timeout():
    """A peer that never enters the collective must trip the per-op timeout
    (reference analog: allreduce_test.cc timeout tests)."""
    store = tempfile.mkdtemp()
    procs = [_spawn_worker(TIMEOUT_BODY, r, 2, store) for r in range(2)]
    outs = [p.communicate(timeout=60) for p in procs]
    assert procs[1].returncode == 0, outs[1]
    assert procs[0].returncode == 11, outs[0]
    line = outs[0][0].splitlines()[0]
    elapsed = float(line.split()[1])
    assert 1.5 < elapsed < 4.0, f"timeout fired at {elapsed}s, wanted ~2s"


CLEAN_EXIT_BODY = """
x = np.full(1000, float(rank + 1), dtype=np.float32)
ctx.allreduce(x)
expected = size * (size + 1) / 2
assert x[0] == expected, x[0]
ctx.close()
print("OK")
"""


def test_multiproc_clean_run():
    store = tempfile.mkdtemp()
    procs = [_spawn_worker(CLEAN_EXIT_BODY, r, 4, store) for r in range(4)]
    outs = [p.communicate(timeout=60) for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "OK" in out[0]


def test_peer_killed_during_bootstrap():
    """Death before rendezvous (rank 1 never starts): survivors must fail
    connect_full_mesh with a timeout."""
    store = tempfile.mkdtemp()
    prog = textwrap.dedent("""
        import os, sys, time
        sys.path.insert(0, {repo!r})
        import gloo_tpu
        store = gloo_tpu.FileStore({store!r})
        ctx = gloo_tpu.Context(0, 2, timeout=2.0)
        try:
            ctx.connect_full_mesh(store, gloo_tpu.Device())
            print("UNEXPECTED-CONNECT"); sys.exit(3)
        except gloo_tpu.TimeoutError:
            print("BOOTSTRAP-TIMEOUT"); sys.exit(10)
    """).format(repo=_REPO, store=store)
    p = subprocess.Popen([sys.executable, "-c", prog], stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    out, err = p.communicate(timeout=60)
    assert p.returncode == 10, (out, err)


RECOVERY_BODY = """
from gloo_tpu.resilience import rebuild_after_failure
if rank == 2:
    os.kill(os.getpid(), signal.SIGKILL)
x = np.full(1 << 18, float(rank + 1), dtype=np.float32)
try:
    ctx.allreduce(x, timeout=2.0)
    print("UNEXPECTED-SUCCESS"); sys.exit(3)
except gloo_tpu.IoError:
    pass
# Survivors regroup into a fresh, smaller world and keep training. The
# settle window must cover detection skew (bounded by the 2s op timeout).
new_ctx, new_rank, new_size = rebuild_after_failure(
    store, gloo_tpu.Device(), old_rank=rank, old_size=size, generation=1,
    settle=3.0, timeout=30.0)
assert new_ctx is not None, "rebuild failed"
assert new_size == 2, new_size
y = np.full(100, float(new_rank + 1), dtype=np.float32)
new_ctx.allreduce(y)
assert y[0] == 3.0, y[0]
new_ctx.close()
print(f"RECOVERED {rank}->{new_rank}/{new_size}")
sys.exit(0)
"""


def test_survivors_rebuild_after_rank_death():
    """The documented recovery contract as working code: a SIGKILL'd rank
    poisons the group; survivors re-rendezvous into a smaller world over
    the same store and run collectives again."""
    store = tempfile.mkdtemp()
    procs = [_spawn_worker(RECOVERY_BODY, r, 3, store) for r in range(3)]
    outs = [p.communicate(timeout=90) for p in procs]
    assert procs[2].returncode == -signal.SIGKILL
    for r in (0, 1):
        assert procs[r].returncode == 0, (r, outs[r])
        assert "RECOVERED" in outs[r][0], outs[r]


TRAINING_RECOVERY_BODY = """
from gloo_tpu.resilience import rebuild_after_failure

rng = np.random.RandomState(0)
X = rng.randn(256, 8).astype(np.float32)
true_w = np.arange(8, dtype=np.float32)
y = X @ true_w
w = np.zeros(8, dtype=np.float32)
gen = 1

def loss_and_grad(w, lo, hi):
    xb, yb = X[lo:hi], y[lo:hi]
    err = xb @ w - yb
    return float(np.mean(err ** 2)), 2.0 * xb.T @ err / len(yb)

loss_at_failure = None
for step in range(120):
    lo = rank * (256 // size)
    hi = lo + 256 // size
    loss, grad = loss_and_grad(w, lo, hi)
    if rank == 2 and step == 5:
        os.kill(os.getpid(), signal.SIGKILL)
    try:
        ctx.allreduce(grad, timeout=2.0)
    except gloo_tpu.IoError:
        loss_at_failure = loss
        ctx, rank, size = rebuild_after_failure(
            store, gloo_tpu.Device(), old_rank=rank, old_size=size,
            generation=gen, settle=3.0, timeout=30.0)
        assert ctx is not None, "rebuild returned no context"
        gen += 1
        # Post-rebuild correctness at the new size: allreduce of rank+1
        # must equal the closed form over the new group.
        probe = np.full(100, float(rank + 1), dtype=np.float32)
        ctx.allreduce(probe)
        expected = size * (size + 1) / 2.0
        assert abs(probe[0] - expected) < 1e-6, (probe[0], expected)
        continue  # redo the step in the new world
    w -= 0.01 * grad / size

final_loss, _ = loss_and_grad(w, 0, 256)
assert loss_at_failure is not None, "this rank never saw the failure"
assert final_loss < loss_at_failure / 10, (final_loss, loss_at_failure)
print(f"RECOVERED final={final_loss:.6f} at_failure={loss_at_failure:.6f}")
sys.exit(0)
"""


def test_recovery_after_sigkill():
    """VERDICT r1 #9 as an invariant: SIGKILL a rank mid-allreduce; the
    survivors rebuild through gloo_tpu.resilience, post-rebuild
    collectives produce correct values at the new size, and training
    keeps converging (final loss well below the loss at failure)."""
    store = tempfile.mkdtemp()
    procs = [_spawn_worker(TRAINING_RECOVERY_BODY, r, 3, store)
             for r in range(3)]
    outs = [p.communicate(timeout=120) for p in procs]
    codes = [p.returncode for p in procs]
    assert codes[2] == -signal.SIGKILL
    for r in (0, 1):
        assert codes[r] == 0, (codes, outs[r])
        assert "RECOVERED" in outs[r][0], outs[r]
