"""Multi-rank test harness.

Mode 1 of the reference's test strategy (gloo/test/base_test.h:89-179): spawn
`size` threads in one process, each with its own Device + Context, all
rendezvousing over a shared in-process HashStore through loopback TCP.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional

import gloo_tpu


def _device_kwargs() -> dict:
    """Env-selectable transport security tier, so the whole collective
    suite can run authenticated/encrypted (TPUCOLL_TEST_AUTH_KEY=...,
    TPUCOLL_TEST_ENCRYPT=1)."""
    kwargs = {}
    key = os.environ.get("TPUCOLL_TEST_AUTH_KEY")
    if key:
        kwargs["auth_key"] = key
        kwargs["encrypt"] = os.environ.get("TPUCOLL_TEST_ENCRYPT") == "1"
    elif os.environ.get("TPUCOLL_TEST_ENCRYPT"):
        raise RuntimeError(
            "TPUCOLL_TEST_ENCRYPT is set but TPUCOLL_TEST_AUTH_KEY is not "
            "- the suite would silently run in plaintext")
    return kwargs


def spawn(size: int, fn: Callable, timeout: float = 30.0,
          context_timeout: float = 15.0,
          device_kwargs: Optional[dict] = None) -> List:
    """Run fn(ctx, rank) on `size` threads; returns per-rank results.

    The first exception raised by any rank is re-raised in the caller after
    all threads have been joined.
    """
    store = gloo_tpu.HashStore()
    results = [None] * size
    errors = []
    lock = threading.Lock()
    dev_kwargs = (_device_kwargs() if device_kwargs is None
                  else device_kwargs)

    def worker(rank: int) -> None:
        ctx = None
        try:
            device = gloo_tpu.Device(**dev_kwargs)
            ctx = gloo_tpu.Context(rank, size, timeout=context_timeout)
            ctx.connect_full_mesh(store, device)
            results[rank] = fn(ctx, rank)
        except BaseException as exc:  # noqa: BLE001 - surfaced to caller
            with lock:
                errors.append((rank, exc))
        finally:
            if ctx is not None:
                try:
                    ctx.close()
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(f"rank thread did not finish in {timeout}s")
    if errors:
        rank, exc = errors[0]
        raise AssertionError(f"rank {rank} failed: {exc!r}") from exc
    return results
