"""Multi-rank test harness.

Mode 1 of the reference's test strategy (gloo/test/base_test.h:89-179): spawn
`size` threads in one process, each with its own Device + Context, all
rendezvousing over a shared in-process HashStore through loopback TCP.
"""

from __future__ import annotations

import threading
from typing import Callable, List

import gloo_tpu


def spawn(size: int, fn: Callable, timeout: float = 30.0,
          context_timeout: float = 15.0) -> List:
    """Run fn(ctx, rank) on `size` threads; returns per-rank results.

    The first exception raised by any rank is re-raised in the caller after
    all threads have been joined.
    """
    store = gloo_tpu.HashStore()
    results = [None] * size
    errors = []
    lock = threading.Lock()

    def worker(rank: int) -> None:
        ctx = None
        try:
            device = gloo_tpu.Device()
            ctx = gloo_tpu.Context(rank, size, timeout=context_timeout)
            ctx.connect_full_mesh(store, device)
            results[rank] = fn(ctx, rank)
        except BaseException as exc:  # noqa: BLE001 - surfaced to caller
            with lock:
                errors.append((rank, exc))
        finally:
            if ctx is not None:
                try:
                    ctx.close()
                except Exception:
                    pass

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(f"rank thread did not finish in {timeout}s")
    if errors:
        rank, exc = errors[0]
        raise AssertionError(f"rank {rank} failed: {exc!r}") from exc
    return results
