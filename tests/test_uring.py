"""io_uring event engine: the same transport contract as the epoll engine
(level-triggered readiness, del() dispatch barrier, failure fan-out), driven
through the public API. Engine selection: Device(engine=...) or
TPUCOLL_ENGINE (docs/transport.md). The reference's analog tier is the
libuv transport (gloo/transport/uv) — an alternative event engine behind
the same pair semantics."""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

import gloo_tpu
from tests.harness import spawn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


pytestmark = pytest.mark.skipif(not gloo_tpu.uring_available(),
                                reason="io_uring unavailable in sandbox")


def test_bad_engine_raises():
    with pytest.raises(gloo_tpu.Error, match="epoll|uring|auto"):
        gloo_tpu.Device(engine="kqueue")


@pytest.mark.parametrize("size", [2, 4])
def test_collectives_over_uring(size):
    def fn(ctx, rank):
        x = np.arange(200_000, dtype=np.float32) + rank
        ctx.allreduce(x)
        g = ctx.allgather(np.full(7, rank, np.int32))
        out = ctx.reduce_scatter(np.full(size * 64, 1.0, np.float64))
        ctx.barrier()
        return x, g, out

    results = spawn(size, fn, device_kwargs={"engine": "uring"})
    base = np.arange(200_000, dtype=np.float64) * size + sum(range(size))
    for x, g, out in results:
        np.testing.assert_allclose(x, base, rtol=1e-6)
        np.testing.assert_array_equal(
            g, np.arange(size, dtype=np.int32)[:, None].repeat(7, axis=1))
        np.testing.assert_array_equal(out, np.full(64, float(size)))


def test_sendrecv_and_recv_any_over_uring():
    def fn(ctx, rank):
        if rank == 0:
            got = np.zeros(5, np.int64)
            src = ctx.recv(got, src=[1, 2], slot=40)
            got2 = np.zeros(5, np.int64)
            src2 = ctx.recv(got2, src=[1, 2], slot=40)
            return {int(src), int(src2)}, got[0] + got2[0]
        ctx.send(np.full(5, rank, np.int64), dst=0, slot=40)
        return None

    results = spawn(3, fn, device_kwargs={"engine": "uring"})
    srcs, total = results[0]
    assert srcs == {1, 2} and total == 3


def test_large_payload_read_budget_over_uring():
    """64 MiB messages force many oneshot re-arms through the pair's 8 MiB
    read budget — the level-triggered re-notification contract."""
    def fn(ctx, rank):
        x = np.full(16 * 1024 * 1024, float(rank + 1), np.float32)
        ctx.allreduce(x)
        return float(x[0]), float(x[-1])

    for first, last in spawn(2, fn, device_kwargs={"engine": "uring"}):
        assert first == 3.0 and last == 3.0


def test_kill_mid_collective_over_uring():
    """SIGKILL one rank: survivors must fail fast with IoError, not hang
    (the uring engine must surface EPOLLERR/HUP-equivalent poll results)."""
    store = tempfile.mkdtemp()
    body = textwrap.dedent("""
        import os, signal, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = {rank}; size = 2
        ctx = gloo_tpu.Context(rank, size, timeout=10.0)
        ctx.connect_full_mesh(gloo_tpu.FileStore({store!r}),
                              gloo_tpu.Device(engine="uring"))
        if rank == 1:
            os.kill(os.getpid(), signal.SIGKILL)
        x = np.ones(1 << 20, dtype=np.float32)
        try:
            ctx.allreduce(x)
            sys.exit(3)
        except gloo_tpu.IoError:
            sys.exit(10)
    """)
    procs = [subprocess.Popen(
        [sys.executable, "-c", body.format(repo=_REPO, rank=r, store=store)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r in range(2)]
    rc0 = procs[0].wait(timeout=60)
    procs[1].wait(timeout=60)
    assert rc0 == 10, procs[0].communicate()
    assert procs[1].returncode == -signal.SIGKILL


def test_engine_stats_zero_on_epoll():
    dev = gloo_tpu.Device(engine="epoll")
    assert dev.engine_stats() == {"enters": 0, "sqes": 0, "cqes": 0}


_SYSCALL_PROBE = textwrap.dedent("""
    import sys, threading
    sys.path.insert(0, {repo!r})
    import numpy as np
    import gloo_tpu

    engine = {engine!r}; size = 4

    def syscr():
        for line in open('/proc/self/io'):
            if line.startswith('syscr:'):
                return int(line.split(':')[1])

    store = gloo_tpu.HashStore()
    start = threading.Barrier(size + 1)
    done = threading.Barrier(size + 1)
    stats = [None] * size

    def worker(rank):
        dev = gloo_tpu.Device(engine=engine)
        ctx = gloo_tpu.Context(rank, size, timeout=15.0)
        ctx.connect_full_mesh(store, dev)
        ctx.barrier()
        s0 = dev.engine_stats()
        start.wait()
        x = np.full(2 << 20, float(rank + 1), dtype=np.float32)
        for _ in range(8):
            ctx.allreduce(x.copy())
        ctx.barrier()
        done.wait()
        s1 = dev.engine_stats()
        stats[rank] = {{k: s1[k] - s0[k] for k in s0}}
        ctx.close()

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
    for t in ts: t.start()
    start.wait(); r0 = syscr()
    done.wait(); r1 = syscr()
    for t in ts: t.join(60)
    print("SYSCR", r1 - r0)
    print("STATS", stats)
""")


def _run_probe(engine):
    body = _SYSCALL_PROBE.format(repo=_REPO, engine=engine)
    env = dict(os.environ, TPUCOLL_SHM="0")
    proc = subprocess.run([sys.executable, "-c", body], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    lines = dict(l.split(" ", 1) for l in proc.stdout.strip().splitlines())
    return int(lines["SYSCR"]), eval(lines["STATS"])  # noqa: S307 - own output


def test_payloads_ride_the_ring_with_shm_disabled():
    """The uring data path's reason to exist: payload bytes move via
    IORING_OP_RECV/SENDMSG submitted through io_uring_enter (which
    combines batch submission with the completion wait), NOT via
    readiness + per-chunk read()/send() syscalls. With shm OFF (so bulk
    payloads actually traverse the ring — same-host shm would otherwise
    bypass it), the kernel's own accounting (/proc/self/io syscr =
    read-family syscall count) must show the epoll tier paying hundreds
    of reads for a 4-rank bulk-allreduce workload while the uring tier
    pays ~none, and the engine counters must show the ops flowing
    through the SQ/CQ instead. Subprocess: shmEnabled() and the engine
    are latched per-process."""
    epoll_syscr, epoll_stats = _run_probe("epoll")
    uring_syscr, uring_stats = _run_probe("uring")

    # Readiness tier: the payload (8 x 8 MiB rounds across 4 in-process
    # ranks) is chunked through read() — hundreds of syscalls.
    assert epoll_syscr > 200, epoll_syscr
    # Data-path tier: socket I/O happens in-kernel; read-family syscall
    # count stays at noise level (stray /proc reads etc.).
    assert uring_syscr < epoll_syscr / 10, (uring_syscr, epoll_syscr)
    # And the ops really flowed through the ring: every device saw
    # steady-state completions, with submissions coalesced into enters
    # (epoll's engine counters are zero by definition). Every enter is
    # either a doorbell carrying >=1 SQE or a wait bounded by the
    # completion batches it drains, so enters cannot exceed
    # sqes + cqes by more than transient EINTR/EBUSY noise.
    for s in uring_stats:
        assert s["cqes"] > 30, s
        assert s["sqes"] > 30, s
        assert 0 < s["enters"] <= s["sqes"] + s["cqes"] + 64, s
    for s in epoll_stats:
        assert s == {"enters": 0, "sqes": 0, "cqes": 0}, s


def test_integration_binary_over_uring():
    """The whole C++ integration suite (every collective, fork, encrypted
    mesh, recvReduce, tamper, retry scenarios) on the uring engine."""
    binary = os.path.join(_REPO, "build", "tpucoll_integration")
    if not os.path.exists(binary):
        pytest.skip("native build not present")
    env = dict(os.environ, TPUCOLL_ENGINE="uring")
    proc = subprocess.run([binary], env=env, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr
