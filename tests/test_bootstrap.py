"""Launcher-environment bootstrap (gloo_tpu.init_from_env): the
reference mpi::Context's deployment story — ranks discover each other
from what the launcher (mpirun/srun/torchrun) put in the environment,
rank 0 serves the store (reference: gloo/mpi/context.cc:88-140; here
the same metadata feeds the TcpStore rendezvous)."""

import os
import subprocess
import sys
import textwrap

import pytest

import gloo_tpu

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_detect_launch_env_priority_and_forms():
    det = gloo_tpu.detect_launch_env
    assert det({}) is None
    assert det({"RANK": "3", "WORLD_SIZE": "8"}) == (3, 8)
    assert det({"OMPI_COMM_WORLD_RANK": "1",
                "OMPI_COMM_WORLD_SIZE": "4"}) == (1, 4)
    assert det({"PMI_RANK": "0", "PMI_SIZE": "2"}) == (0, 2)
    assert det({"SLURM_PROCID": "5", "SLURM_NTASKS": "6"}) == (5, 6)
    # torchrun-style RANK wins over launcher-native vars when both exist
    assert det({"RANK": "1", "WORLD_SIZE": "2",
                "OMPI_COMM_WORLD_RANK": "9",
                "OMPI_COMM_WORLD_SIZE": "9"}) == (1, 2)


def test_init_from_env_requires_a_launcher():
    with pytest.raises(RuntimeError, match="no launcher environment"):
        gloo_tpu.init_from_env(env={})


_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import gloo_tpu

    ctx, server = gloo_tpu.init_from_env(timeout=20.0)
    x = np.full(4096, float(ctx.rank + 1), dtype=np.float32)
    ctx.allreduce(x)
    size = ctx.size
    assert np.all(x == size * (size + 1) / 2), x[:4]
    ctx.barrier()
    ctx.close()
    del server
    print("OK", flush=True)
""").format(repo=_REPO)


def _launch(rank_env):
    env = {k: v for k, v in os.environ.items()
           if k not in ("RANK", "WORLD_SIZE", "MASTER_ADDR", "MASTER_PORT")}
    env.update(rank_env)
    return subprocess.Popen([sys.executable, "-c", _WORKER], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("style", ["torchrun", "openmpi", "slurm"])
def test_init_from_env_multiprocess(style):
    """Real processes, launcher-style env only — no store plumbing in
    user code. Rank 0 serves; clients retry while it comes up."""
    size = 3
    port = str(_free_port())

    def env_for(rank):
        if style == "torchrun":
            return {"RANK": str(rank), "WORLD_SIZE": str(size),
                    "MASTER_ADDR": "127.0.0.1", "MASTER_PORT": port}
        if style == "openmpi":
            return {"OMPI_COMM_WORLD_RANK": str(rank),
                    "OMPI_COMM_WORLD_SIZE": str(size),
                    "OMPI_COMM_WORLD_LOCAL_SIZE": str(size),
                    "MASTER_PORT": port}
        return {"SLURM_PROCID": str(rank), "SLURM_NTASKS": str(size),
                "SLURM_NNODES": "1", "MASTER_PORT": port}

    procs = [_launch(env_for(r)) for r in range(size)]
    outs = [p.communicate(timeout=90) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0 and "OK" in out, (out, err)
