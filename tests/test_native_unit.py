"""Runs the native unit-test binary (slot arithmetic, dtype conversions,
vector reduction kernels, HMAC vectors — internals the C API doesn't
expose directly)."""

import os
import subprocess

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_native_units():
    binary = os.path.join(_REPO, "build", "tpucoll_unit")
    result = subprocess.run([binary], capture_output=True, text=True,
                            timeout=60)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "all tests passed" in result.stdout


def test_native_integration():
    """Pure C++ 4-thread end-to-end (all collectives, p2p, fork); also the
    leak-check target for ASAN runs."""
    binary = os.path.join(_REPO, "build", "tpucoll_integration")
    result = subprocess.run([binary], capture_output=True, text=True,
                            timeout=120)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "all checks passed" in result.stdout


def test_bench_cli_smoke():
    """The benchmark CLI end-to-end at tiny sizes: 2 ranks over an inline
    TcpStore, one rooted op, one v-variant (uneven splits), and sendrecv —
    first iterations are verified element-wise by the harness itself."""
    import re
    import sys

    binary = os.path.join(_REPO, "build", "tpucoll_bench")
    if not os.path.exists(binary):
        import pytest
        pytest.skip("native build not present")
    for op in ("allreduce", "alltoallv", "sendrecv"):
        serve = subprocess.Popen(
            [binary, "--rank", "0", "--size", "2", "--serve", "0",
             "--op", op, "--elements", "1000", "--min-time", "0.2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        # --serve 0 binds an ephemeral port and prints it on stderr.
        port = None
        for _ in range(200):
            line = serve.stderr.readline()
            m = re.search(r"serving on port (\d+)", line)
            if m:
                port = m.group(1)
                break
        assert port, "store port never announced"
        peer = subprocess.run(
            [binary, "--rank", "1", "--size", "2", "--store",
             f"tcp:127.0.0.1:{port}", "--op", op, "--elements", "1000",
             "--min-time", "0.2"],
            capture_output=True, text=True, timeout=120)
        out, err = serve.communicate(timeout=120)
        assert serve.returncode == 0, (op, out, err)
        assert peer.returncode == 0, (op, peer.stdout, peer.stderr)
        assert re.search(r"^\s*\d+\s+\d+", out, re.M), (op, out)
