"""Runs the native unit-test binary (slot arithmetic, dtype conversions,
vector reduction kernels, HMAC vectors — internals the C API doesn't
expose directly)."""

import os
import subprocess

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_native_units():
    binary = os.path.join(_REPO, "build", "tpucoll_unit")
    result = subprocess.run([binary], capture_output=True, text=True,
                            timeout=60)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "all tests passed" in result.stdout


def test_native_integration():
    """Pure C++ 4-thread end-to-end (all collectives, p2p, fork); also the
    leak-check target for ASAN runs."""
    binary = os.path.join(_REPO, "build", "tpucoll_integration")
    result = subprocess.run([binary], capture_output=True, text=True,
                            timeout=120)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "all checks passed" in result.stdout
