"""Runs the native unit-test binary (slot arithmetic, dtype conversions,
vector reduction kernels, HMAC vectors — internals the C API doesn't
expose directly), plus the skip-unless-built sanitizer smoke target."""

import os
import subprocess
import sys
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_native_units():
    binary = os.path.join(_REPO, "build", "tpucoll_unit")
    result = subprocess.run([binary], capture_output=True, text=True,
                            timeout=60)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "all tests passed" in result.stdout


def test_native_integration():
    """Pure C++ 4-thread end-to-end (all collectives, p2p, fork); also the
    leak-check target for ASAN runs."""
    binary = os.path.join(_REPO, "build", "tpucoll_integration")
    result = subprocess.run([binary], capture_output=True, text=True,
                            timeout=120)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "all checks passed" in result.stdout


def test_bench_cli_smoke():
    """The benchmark CLI end-to-end at tiny sizes: 2 ranks over an inline
    TcpStore, one rooted op, one v-variant (uneven splits), and sendrecv —
    first iterations are verified element-wise by the harness itself."""
    import re
    import sys

    binary = os.path.join(_REPO, "build", "tpucoll_bench")
    if not os.path.exists(binary):
        import pytest
        pytest.skip("native build not present")
    for op in ("allreduce", "alltoallv", "sendrecv"):
        serve = subprocess.Popen(
            [binary, "--rank", "0", "--size", "2", "--serve", "0",
             "--op", op, "--elements", "1000", "--min-time", "0.2"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        # --serve 0 binds an ephemeral port and prints it on stderr.
        port = None
        for _ in range(200):
            line = serve.stderr.readline()
            m = re.search(r"serving on port (\d+)", line)
            if m:
                port = m.group(1)
                break
        assert port, "store port never announced"
        peer = subprocess.run(
            [binary, "--rank", "1", "--size", "2", "--store",
             f"tcp:127.0.0.1:{port}", "--op", op, "--elements", "1000",
             "--min-time", "0.2"],
            capture_output=True, text=True, timeout=120)
        out, err = serve.communicate(timeout=120)
        assert serve.returncode == 0, (op, out, err)
        assert peer.returncode == 0, (op, peer.stdout, peer.stderr)
        assert re.search(r"^\s*\d+\s+\d+", out, re.M), (op, out)


def test_tsan_async_engine_smoke():
    """Skip-unless-built ThreadSanitizer smoke of the async engine —
    lanes are a brand-new thread surface (queue handoff, Work
    completion, shutdown joining mid-collective), so run a 2-rank
    in-process battery of concurrent async collectives + bucketer +
    shutdown-with-work-in-flight under the TSan flavor
    (`make native SANITIZE=thread`). Any data-race report aborts the
    child with TSan's exit code."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_tsan.so")
    if not os.path.exists(lib):
        pytest.skip("TSan flavor not built (make native SANITIZE=thread)")
    prog = textwrap.dedent(f"""
        import sys, time
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        import gloo_tpu
        from tests.harness import spawn

        def fn(ctx, rank):
            with ctx.async_engine(lanes=2) as eng:
                works = []
                for i in range(6):
                    x = np.full(4096 + i, float(rank + 1), np.float32)
                    works.append(eng.allreduce_async(x))
                b = gloo_tpu.GradientBucketer(eng, bucket_bytes=32 << 10)
                for _ in range(12):
                    b.add(np.full(2000, float(rank + 1), np.float32))
                b.finish()
                for w in works:
                    w.wait()
                eng.stats()
                ctx.metrics()
            # Shutdown with work genuinely in flight: rank 0 issues ops
            # rank 1 never matches, then tears the engine down.
            eng2 = ctx.async_engine(lanes=2, tag_base=0xEEE00)
            leftovers = []
            if rank == 0:
                leftovers = [eng2.allreduce_async(
                    np.ones(50000, np.float32)) for _ in range(3)]
                time.sleep(0.1)
            eng2.shutdown()
            for w in leftovers:
                try:
                    w.wait(timeout=5)
                except (gloo_tpu.IoError, gloo_tpu.Aborted):
                    pass
            return True

        assert spawn(2, fn, timeout=120) == [True, True]
        print("TSAN-SMOKE-OK")
    """)
    preloads = []
    for name in ("libtsan.so", "libstdc++.so"):
        p = subprocess.run(["g++", "-print-file-name=" + name],
                           capture_output=True, text=True,
                           check=True).stdout.strip()
        if not os.path.isabs(p):
            pytest.skip(f"{name} runtime not found beside g++")
        preloads.append(p)
    env = dict(os.environ, TPUCOLL_LIB=lib, TPUCOLL_SKIP_BUILD="1",
               LD_PRELOAD=" ".join(preloads),
               # halt_on_error: the first report fails the child
               # immediately instead of letting a racy run "pass".
               TSAN_OPTIONS="halt_on_error=1 exitcode=66")
    result = subprocess.run([sys.executable, "-c", prog],
                            capture_output=True, text=True, timeout=300,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-4000:])
    assert "TSAN-SMOKE-OK" in result.stdout, result.stdout


def test_ubsan_smoke():
    """Skip-unless-built UndefinedBehaviorSanitizer smoke (`make native
    SANITIZE=undefined`): a 2-rank collective battery crossing the
    integer-width/shift/alignment territory UBSan patrols — dtype
    conversions (f16/bf16 bit twiddling), unaligned views, and the slot
    arithmetic. The flavor is compiled -fno-sanitize-recover=all, so any
    UB report aborts the child; no report scraping needed."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native",
                       "libtpucoll_ubsan.so")
    if not os.path.exists(lib):
        pytest.skip(
            "UBSan flavor not built (make native SANITIZE=undefined)")
    prog = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        from tests.harness import spawn

        def fn(ctx, rank):
            for dtype in (np.float32, np.float16, np.int32, np.uint8):
                x = np.full(4097, rank + 1, dtype=dtype)
                ctx.allreduce(x, tag=hash(np.dtype(dtype).name) & 0xFF)
                assert x[0] == 3, (dtype, x[0])
            # Unaligned view: offset slice exercises the vector kernels'
            # head/tail scalar paths where misaligned loads would be UB.
            buf = np.zeros(1026, dtype=np.float32)
            view = buf[1:1025]
            view[:] = rank + 1
            ctx.allreduce(view, tag=77)
            assert view[0] == 3.0, view[0]
            y = np.arange(256, dtype=np.float64) * (rank + 1)
            out = np.zeros(256, dtype=np.float64)
            ctx.send(y, dst=(rank + 1) % 2, slot=7 + rank)
            ctx.recv(out, src=(rank + 1) % 2, slot=7 + (rank + 1) % 2)
            ctx.barrier(tag=2)
            return float(out[1])

        res = spawn(2, fn, timeout=60)
        assert res == [2.0, 1.0], res
        print("UBSAN-SMOKE-OK")
    """)
    preloads = []
    for name in ("libubsan.so", "libstdc++.so"):
        p = subprocess.run(["g++", "-print-file-name=" + name],
                           capture_output=True, text=True,
                           check=True).stdout.strip()
        if not os.path.isabs(p):
            pytest.skip(f"{name} runtime not found beside g++")
        preloads.append(p)
    env = dict(os.environ, TPUCOLL_LIB=lib, TPUCOLL_SKIP_BUILD="1",
               LD_PRELOAD=" ".join(preloads),
               UBSAN_OPTIONS="print_stacktrace=1 halt_on_error=1")
    result = subprocess.run([sys.executable, "-c", prog],
                            capture_output=True, text=True, timeout=120,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "UBSAN-SMOKE-OK" in result.stdout, result.stdout


def test_asan_plan_replay_smoke():
    """Skip-unless-built ASan smoke for the persistent-plan steady
    state: replay ONE cached plan 100x (plus a reduce_scatter plan and
    an invalidation/rebuild cycle), which is exactly the reuse pattern
    that would expose a use-after-free of the plan's arena or cached
    UnboundBuffer registrations. Any ASan report aborts the child."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    prog = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        from tests.harness import spawn

        def fn(ctx, rank):
            x = np.full(4096, float(rank + 1), dtype=np.float32)
            plan = ctx.allreduce_plan(x, tag=1)
            ub = None
            for i in range(100):
                x[:] = rank + 1
                plan()
                assert x[0] == 3.0, (i, x[0])
                m = ctx.metrics()["ubuf_creates"]
                if ub is None:
                    ub = m
                else:
                    assert m == ub, "steady state registered buffers"
            out = np.empty(2048, dtype=np.float32)
            rsp = ctx.reduce_scatter_plan(x, tag=2, output=out)
            for i in range(25):
                x[:] = rank + 1
                rsp()
            # Invalidate mid-life, then rebuild and replay again: the
            # dropped plan's buffers must drain cleanly.
            ctx.plan_cache_clear()
            for i in range(25):
                x[:] = rank + 1
                plan()
                assert x[0] == 3.0
            ctx.barrier(tag=9)
            return True

        res = spawn(2, fn, timeout=120)
        assert res == [True, True], res
        print("ASAN-PLAN-SMOKE-OK")
    """)
    preloads = []
    for name in ("libasan.so", "libstdc++.so"):
        p = subprocess.run(["g++", "-print-file-name=" + name],
                           capture_output=True, text=True,
                           check=True).stdout.strip()
        if not os.path.isabs(p):
            pytest.skip(f"{name} runtime not found beside g++")
        preloads.append(p)
    env = dict(os.environ, TPUCOLL_LIB=lib, TPUCOLL_SKIP_BUILD="1",
               LD_PRELOAD=" ".join(preloads),
               ASAN_OPTIONS="detect_leaks=0,abort_on_error=1")
    result = subprocess.run([sys.executable, "-c", prog],
                            capture_output=True, text=True, timeout=300,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "ASAN-PLAN-SMOKE-OK" in result.stdout, result.stdout


def test_asan_schedule_replay_smoke():
    """Skip-unless-built ASan smoke for the schedule interpreter: warm
    scheduled replays (the pipelined ring the native enum cannot
    express), an install/clear invalidation cycle, and a reduce_scatter
    schedule — the arena/slot-bookkeeping reuse pattern that would
    expose a use-after-free in a resolved program or its plan."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    prog = textwrap.dedent(f"""
        import json
        import sys
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        from gloo_tpu import schedule
        from tests.harness import spawn

        def fn(ctx, rank):
            x = np.full(4096, float(rank + 1), dtype=np.float32)
            t = schedule.generate("ring", 2, {{"depth": 2}})
            t["elections"] = [{{
                "collective": "allreduce", "world_size": 2, "dtype": "",
                "bucket": x.nbytes.bit_length() - 1,
                "schedule": t["schedules"][0]["name"]}}]
            schedule.install(ctx, t)
            ub = None
            for i in range(50):
                x[:] = rank + 1
                ctx.allreduce(x, tag=1)
                assert x[0] == 3.0, (i, x[0])
                if i > 0:  # first call builds the plan
                    m = ctx.metrics()["ubuf_creates"]
                    if ub is None:
                        ub = m
                    else:
                        assert m == ub, "scheduled replay registered"
            # Invalidate mid-life (install drops every plan), rebuild,
            # replay: the dropped plan's scratch must drain cleanly.
            rs = schedule.generate("ring_rs", 2)
            rs["elections"] = [{{
                "collective": "reduce_scatter", "world_size": 2,
                "dtype": "", "bucket": x.nbytes.bit_length() - 1,
                "schedule": rs["schedules"][0]["name"]}}]
            schedule.install(ctx, schedule.merge(t, rs))
            for i in range(25):
                x[:] = rank + 1
                ctx.allreduce(x, tag=1)
                ctx.reduce_scatter(x.copy(), tag=2)
            schedule.clear(ctx)
            x[:] = rank + 1
            ctx.allreduce(x, tag=1)  # native dispatch after clear
            assert x[0] == 3.0
            ctx.barrier(tag=9)
            return True

        res = spawn(2, fn, timeout=120)
        assert res == [True, True], res
        print("ASAN-SCHED-SMOKE-OK")
    """)
    preloads = []
    for name in ("libasan.so", "libstdc++.so"):
        p = subprocess.run(["g++", "-print-file-name=" + name],
                           capture_output=True, text=True,
                           check=True).stdout.strip()
        if not os.path.isabs(p):
            pytest.skip(f"{name} runtime not found beside g++")
        preloads.append(p)
    env = dict(os.environ, TPUCOLL_LIB=lib, TPUCOLL_SKIP_BUILD="1",
               LD_PRELOAD=" ".join(preloads),
               ASAN_OPTIONS="detect_leaks=0,abort_on_error=1")
    result = subprocess.run([sys.executable, "-c", prog],
                            capture_output=True, text=True, timeout=300,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "ASAN-SCHED-SMOKE-OK" in result.stdout, result.stdout


def test_asan_smoke():
    """Skip-unless-built AddressSanitizer smoke: when the sanitizer
    flavor exists (`make native SANITIZE=address`), run a small 2-rank
    in-process allreduce + p2p exchange against it in a child process
    (TPUCOLL_LIB selects the instrumented library; TPUCOLL_SKIP_BUILD
    keeps conftest from rebuilding the production one). Any ASan report
    aborts the child with a nonzero exit."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    prog = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {_REPO!r})
        import numpy as np
        from tests.harness import spawn

        def fn(ctx, rank):
            x = np.full(4096, float(rank + 1), dtype=np.float32)
            ctx.allreduce(x, tag=1)
            assert x[0] == 3.0, x[0]
            y = np.arange(256, dtype=np.float64) * (rank + 1)
            out = np.zeros(256, dtype=np.float64)
            ctx.send(y, dst=(rank + 1) % 2, slot=7 + rank)
            ctx.recv(out, src=(rank + 1) % 2, slot=7 + (rank + 1) % 2)
            ctx.barrier(tag=2)
            return float(out[1])

        res = spawn(2, fn, timeout=60)
        assert res == [2.0, 1.0], res
        print("ASAN-SMOKE-OK")
    """)
    # Loading an instrumented .so into an uninstrumented interpreter
    # requires the ASan runtime first in the link order: preload it —
    # AND libstdc++, or REAL(__cxa_throw) is unresolved at interceptor
    # init and any C++ exception crossing the ctypes boundary aborts
    # the process with no report (.claude/skills/verify).
    preloads = []
    for name in ("libasan.so", "libstdc++.so"):
        p = subprocess.run(["g++", "-print-file-name=" + name],
                           capture_output=True, text=True,
                           check=True).stdout.strip()
        if not os.path.isabs(p):
            pytest.skip(f"{name} runtime not found beside g++")
        preloads.append(p)
    env = dict(os.environ, TPUCOLL_LIB=lib, TPUCOLL_SKIP_BUILD="1",
               LD_PRELOAD=" ".join(preloads),
               # The leak checker trips on Python interpreter internals;
               # the interesting reports (UAF, OOB, stack misuse) stay on.
               ASAN_OPTIONS="detect_leaks=0,abort_on_error=1")
    result = subprocess.run([sys.executable, "-c", prog],
                            capture_output=True, text=True, timeout=120,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr)
    assert "ASAN-SMOKE-OK" in result.stdout, result.stdout


def _sanitizer_env(runtime_names, lib, extra=None):
    """LD_PRELOAD env for loading a sanitizer-flavored libtpucoll into an
    uninstrumented interpreter (see test_asan_smoke for why libstdc++
    must ride along)."""
    preloads = []
    for name in runtime_names:
        p = subprocess.run(["g++", "-print-file-name=" + name],
                           capture_output=True, text=True,
                           check=True).stdout.strip()
        if not os.path.isabs(p):
            pytest.skip(f"{name} runtime not found beside g++")
        preloads.append(p)
    env = dict(os.environ, TPUCOLL_LIB=lib, TPUCOLL_SKIP_BUILD="1",
               LD_PRELOAD=" ".join(preloads))
    env.update(extra or {})
    return env


_SPLIT_HIER_PROG = f"""
import sys, threading
sys.path.insert(0, {_REPO!r})
import numpy as np
import gloo_tpu

size = 4
store = gloo_tpu.HashStore()
errors = []

def worker(rank):
    try:
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.set_host_id("sanhost%d" % (rank // 2))
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        topo = ctx.topology()
        assert topo["non_flat"], topo
        sub = ctx.split_by_host(tag=3)
        x = np.full(1024, float(rank + 1), dtype=np.float32)
        sub.allreduce(x)
        z = np.full(4096, float(rank + 1), dtype=np.float32)
        ctx.allreduce(z, algorithm="hier", tag=5)
        assert z[0] == 10.0, z[0]
        ctx.barrier(algorithm="hier", tag=7)
        sub.close()
        ctx.close()
    except BaseException as e:
        errors.append((rank, e))

threads = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
[t.start() for t in threads]
[t.join(180) for t in threads]
assert not errors, errors
print("SPLIT-HIER-SMOKE-OK")
"""


_ELASTIC_PROG = f"""
import sys, threading, time
sys.path.insert(0, {_REPO!r})
import numpy as np
import gloo_tpu
from gloo_tpu import elastic

store = gloo_tpu.HashStore()
errors = []

def worker(rank):
    try:
        ectx = elastic.ElasticContext(store, gloo_tpu.Device(), rank=rank,
                                      world_size=2, min_size=1,
                                      timeout=60.0)
        x = np.full(2048, float(ectx.rank + 1), dtype=np.float32)
        ectx.allreduce(x)
        assert x[0] == 3.0, x[0]
        assert ectx.group_tag() == "e1"
        if rank == 1:
            ectx.close()   # graceful leave: lease deleted, peers shrink
            return
        deadline = time.time() + 30
        while time.time() < deadline and not ectx.agent.poll():
            time.sleep(0.05)
        assert ectx.agent.poll(), "no epoch bump after graceful leave"
        ectx.rebuild()
        st = ectx.status()
        assert st["epoch"] == 2 and st["size"] == 1, st
        assert st["coordinator"] is True, st
        assert st["leases_renewed"] >= 2, st
        y = np.full(64, 7.0, dtype=np.float32)
        ectx.allreduce(y)
        assert y[0] == 7.0
        ectx.close()
    except BaseException as e:
        errors.append((rank, e))

threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
[t.start() for t in threads]
[t.join(180) for t in threads]
assert not errors, errors
print("ELASTIC-SMOKE-OK")
"""


def test_asan_elastic_smoke():
    """Skip-unless-built ASan smoke of the elastic membership plane
    through the ctypes surface: two in-process agents found epoch 1,
    heartbeat leases, run a collective, one leaves gracefully, the
    survivor observes the bump and rebuilds into the one-member epoch
    2 — the lease-heartbeat + epoch-rebuild lifecycle under ASan
    (TPUCOLL_LEASE_MS/GRACE shrunk so the pass is test-sized)."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    env = _sanitizer_env(("libasan.so", "libstdc++.so"), lib,
                         {"ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
                          "TPUCOLL_LEASE_MS": "200",
                          "TPUCOLL_LEASE_GRACE": "1000"})
    result = subprocess.run([sys.executable, "-c", _ELASTIC_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "ELASTIC-SMOKE-OK" in result.stdout, result.stdout


def test_ubsan_elastic_smoke():
    """UBSan flavor of the elastic lifecycle smoke (-fno-sanitize-
    recover: the first UB hit aborts the child)."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native",
                       "libtpucoll_ubsan.so")
    if not os.path.exists(lib):
        pytest.skip(
            "UBSan flavor not built (make native SANITIZE=undefined)")
    env = _sanitizer_env(("libubsan.so", "libstdc++.so"), lib,
                         {"TPUCOLL_LEASE_MS": "200",
                          "TPUCOLL_LEASE_GRACE": "1000"})
    result = subprocess.run([sys.executable, "-c", _ELASTIC_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "ELASTIC-SMOKE-OK" in result.stdout, result.stdout


def test_tsan_elastic_smoke():
    """TSan flavor of the elastic lifecycle smoke: two in-process
    agents each run a heartbeat + monitor thread against one shared
    HashStore while app threads rebuild through epoch transitions —
    exactly the shape that would expose a data race in the lease /
    epoch-document plumbing."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_tsan.so")
    if not os.path.exists(lib):
        pytest.skip("TSan flavor not built (make native SANITIZE=thread)")
    env = _sanitizer_env(("libtsan.so", "libstdc++.so"), lib,
                         {"TSAN_OPTIONS": "halt_on_error=1 "
                          "report_signal_unsafe=0 history_size=7",
                          "TPUCOLL_LEASE_MS": "200",
                          "TPUCOLL_LEASE_GRACE": "1000"})
    result = subprocess.run([sys.executable, "-c", _ELASTIC_PROG],
                            capture_output=True, text=True, timeout=600,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "ELASTIC-SMOKE-OK" in result.stdout, result.stdout


def test_asan_split_hier_smoke():
    """Skip-unless-built ASan smoke driving the process-group subsystem
    through the ctypes surface: topology discovery, split_by_host, a
    subgroup allreduce, and a kHier allreduce + barrier at P=4 over a
    simulated 2-host topology. Any ASan report aborts the child."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    env = _sanitizer_env(("libasan.so", "libstdc++.so"), lib,
                         {"ASAN_OPTIONS":
                          "detect_leaks=0,abort_on_error=1"})
    result = subprocess.run([sys.executable, "-c", _SPLIT_HIER_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "SPLIT-HIER-SMOKE-OK" in result.stdout, result.stdout


def test_ubsan_split_hier_smoke():
    """UBSan flavor of the split + kHier smoke (-fno-sanitize-recover:
    the first UB hit aborts the child)."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native",
                       "libtpucoll_ubsan.so")
    if not os.path.exists(lib):
        pytest.skip(
            "UBSan flavor not built (make native SANITIZE=undefined)")
    env = _sanitizer_env(("libubsan.so", "libstdc++.so"), lib)
    result = subprocess.run([sys.executable, "-c", _SPLIT_HIER_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "SPLIT-HIER-SMOKE-OK" in result.stdout, result.stdout


def test_tsan_split_hier_smoke():
    """TSan flavor of the split + kHier smoke: four in-process ranks
    exercising concurrent split bootstrap + hier phases is exactly the
    shape that would expose a data race in the new topology/split
    plumbing."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_tsan.so")
    if not os.path.exists(lib):
        pytest.skip("TSan flavor not built (make native SANITIZE=thread)")
    env = _sanitizer_env(("libtsan.so", "libstdc++.so"), lib,
                         {"TSAN_OPTIONS": "halt_on_error=1 "
                          "report_signal_unsafe=0 history_size=7"})
    result = subprocess.run([sys.executable, "-c", _SPLIT_HIER_PROG],
                            capture_output=True, text=True, timeout=600,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "SPLIT-HIER-SMOKE-OK" in result.stdout, result.stdout


_PROFILE_PROG = f"""
import json, sys, threading, urllib.request
sys.path.insert(0, {_REPO!r})
import numpy as np
import gloo_tpu
from gloo_tpu.utils import telemetry

size = 2
store = gloo_tpu.HashStore()
errors = []

def worker(rank):
    try:
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        x = np.full(1 << 16, 1.0, dtype=np.float32)
        for _ in range(4):
            ctx.allreduce(x, algorithm="ring")
            x[:] = 1.0
        prof = ctx.profile()
        assert len(prof["ops"]) == 4, prof
        assert all("wire_wait" in o["phases"] for o in prof["ops"]), prof
        assert "ring" in ctx.metrics()["phases"]["allreduce"]
        with telemetry.serve_telemetry(ctx) as srv:
            with urllib.request.urlopen(srv.url + "/healthz") as r:
                assert r.status == 200
            with urllib.request.urlopen(srv.url + "/profile.json") as r:
                assert json.load(r)["ops"], "empty live profile"
        ctx.profile_enable(False)
        ctx.barrier()
        assert ctx.profile()["next_seq"] == 4
        ctx.close()
    except BaseException as e:
        errors.append((rank, e))

threads = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
[t.start() for t in threads]
[t.join(180) for t in threads]
assert not errors, errors
print("PROFILE-SMOKE-OK")
"""


def test_asan_profile_smoke():
    """Skip-unless-built ASan smoke of the phase profiler + telemetry
    endpoint through the ctypes surface: profiled collectives, the
    per-op ring + phase histograms, a live /healthz + /profile.json
    scrape, and the runtime toggle — the lock-free ring publish and the
    keyed-histogram flush are the new memory-shape code under test."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    env = _sanitizer_env(("libasan.so", "libstdc++.so"), lib,
                         {"ASAN_OPTIONS":
                          "detect_leaks=0,abort_on_error=1"})
    result = subprocess.run([sys.executable, "-c", _PROFILE_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "PROFILE-SMOKE-OK" in result.stdout, result.stdout


def test_ubsan_profile_smoke():
    """UBSan flavor of the profiler smoke (-fno-sanitize-recover: the
    first UB hit aborts the child)."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native",
                       "libtpucoll_ubsan.so")
    if not os.path.exists(lib):
        pytest.skip(
            "UBSan flavor not built (make native SANITIZE=undefined)")
    env = _sanitizer_env(("libubsan.so", "libstdc++.so"), lib)
    result = subprocess.run([sys.executable, "-c", _PROFILE_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "PROFILE-SMOKE-OK" in result.stdout, result.stdout


def test_tsan_profile_smoke():
    """TSan flavor: two ranks publishing to their profiler rings while
    the telemetry thread snapshots them is exactly the writer/dumper
    race the claim-then-publish seq protocol must keep benign."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_tsan.so")
    if not os.path.exists(lib):
        pytest.skip("TSan flavor not built (make native SANITIZE=thread)")
    env = _sanitizer_env(("libtsan.so", "libstdc++.so"), lib,
                         {"TSAN_OPTIONS": "halt_on_error=1 "
                          "report_signal_unsafe=0 history_size=7"})
    result = subprocess.run([sys.executable, "-c", _PROFILE_PROG],
                            capture_output=True, text=True, timeout=600,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "PROFILE-SMOKE-OK" in result.stdout, result.stdout


_SPANS_PROG = f"""
import os, sys, threading
os.environ["TPUCOLL_SPANS"] = "1"
sys.path.insert(0, {_REPO!r})
import numpy as np
import gloo_tpu

size = 2
store = gloo_tpu.HashStore()
errors = []

def worker(rank):
    try:
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        assert ctx.spans_enabled()
        x = np.full(1 << 16, 1.0, dtype=np.float32)
        for _ in range(4):
            ctx.allreduce(x, algorithm="ring")
            x[:] = 1.0
        snap = ctx.spans()
        assert snap["enabled"] and snap["spans"], snap["next_seq"]
        kinds = set(s["kind"] for s in snap["spans"])
        assert "send" in kinds and "recv" in kinds, kinds
        ctx.spans_enable(False)
        ctx.barrier()
        frozen = ctx.spans()["next_seq"]
        ctx.allreduce(x, algorithm="ring")
        assert ctx.spans()["next_seq"] == frozen
        ctx.close()
    except BaseException as e:
        errors.append((rank, e))

threads = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
[t.start() for t in threads]
[t.join(180) for t in threads]
assert not errors, errors
print("SPANS-SMOKE-OK")
"""


def test_asan_spans_smoke():
    """Skip-unless-built ASan smoke of the causal span recorder through
    the ctypes surface: spans-enabled collectives filling the bounded
    ring, a snapshot walking it concurrently-shaped memory, and the
    runtime toggle — the span ring's claim-then-publish slots are the
    new memory-shape code under test."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    env = _sanitizer_env(("libasan.so", "libstdc++.so"), lib,
                         {"ASAN_OPTIONS":
                          "detect_leaks=0,abort_on_error=1"})
    result = subprocess.run([sys.executable, "-c", _SPANS_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "SPANS-SMOKE-OK" in result.stdout, result.stdout


def test_ubsan_spans_smoke():
    """UBSan flavor of the span-recorder smoke (-fno-sanitize-recover:
    the first UB hit aborts the child)."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native",
                       "libtpucoll_ubsan.so")
    if not os.path.exists(lib):
        pytest.skip(
            "UBSan flavor not built (make native SANITIZE=undefined)")
    env = _sanitizer_env(("libubsan.so", "libstdc++.so"), lib)
    result = subprocess.run([sys.executable, "-c", _SPANS_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "SPANS-SMOKE-OK" in result.stdout, result.stdout


def test_tsan_spans_smoke():
    """TSan flavor: two ranks' collective threads emitting spans while
    snapshots drain the ring is the writer/reader race the relaxed
    enable-check plus acquire/release slot protocol must keep benign."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_tsan.so")
    if not os.path.exists(lib):
        pytest.skip("TSan flavor not built (make native SANITIZE=thread)")
    env = _sanitizer_env(("libtsan.so", "libstdc++.so"), lib,
                         {"TSAN_OPTIONS": "halt_on_error=1 "
                          "report_signal_unsafe=0 history_size=7"})
    result = subprocess.run([sys.executable, "-c", _SPANS_PROG],
                            capture_output=True, text=True, timeout=600,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "SPANS-SMOKE-OK" in result.stdout, result.stdout


_FLEET_PROG = f"""
import sys, threading, time
sys.path.insert(0, {_REPO!r})
import numpy as np
import gloo_tpu
from gloo_tpu.utils import fleet as fleet_util

size = 4
store = gloo_tpu.HashStore()
errors = []
done = threading.Event()

def worker(rank):
    try:
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.set_host_id("sanflt%d" % (rank // 2))
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        ctx.fleetobs_start()
        assert ctx.fleetobs_running()
        x = np.full(1 << 12, 1.0, dtype=np.float32)
        for _ in range(4):
            ctx.allreduce(x, algorithm="ring")
            x[:] = 1.0
        if rank == 0:
            deadline = time.time() + 25
            while time.time() < deadline:
                if fleet_util.coverage(ctx.fleet())["complete"]:
                    break
                time.sleep(0.05)
            assert fleet_util.coverage(ctx.fleet())["complete"], ctx.fleet()
            done.set()
        else:
            assert done.wait(30), "rank 0 never reached coverage"
        ctx.fleetobs_stop()
        ctx.barrier()
        ctx.close()
    except BaseException as e:
        errors.append((rank, e))

threads = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
[t.start() for t in threads]
[t.join(180) for t in threads]
assert not errors, errors
print("FLEET-SMOKE-OK")
"""


def test_asan_fleet_smoke():
    """Skip-unless-built ASan smoke of the fleet observability plane
    through the ctypes surface: four ranks on two simulated hosts, the
    member -> leader -> rank 0 relay running to full coverage, then a
    clean stop — the per-link wire buffers, the bounded JSON builders,
    and the stop/teardown ordering are the memory-shape code under
    test (TPUCOLL_FLEETOBS_INTERVAL_MS pinned low so the relay
    actually cycles)."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    env = _sanitizer_env(("libasan.so", "libstdc++.so"), lib,
                         {"ASAN_OPTIONS":
                          "detect_leaks=0,abort_on_error=1",
                          "TPUCOLL_FLEETOBS_INTERVAL_MS": "80"})
    result = subprocess.run([sys.executable, "-c", _FLEET_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "FLEET-SMOKE-OK" in result.stdout, result.stdout


def test_ubsan_fleet_smoke():
    """UBSan flavor of the fleet-plane smoke (-fno-sanitize-recover:
    the first UB hit aborts the child)."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native",
                       "libtpucoll_ubsan.so")
    if not os.path.exists(lib):
        pytest.skip(
            "UBSan flavor not built (make native SANITIZE=undefined)")
    env = _sanitizer_env(("libubsan.so", "libstdc++.so"), lib,
                         {"TPUCOLL_FLEETOBS_INTERVAL_MS": "80"})
    result = subprocess.run([sys.executable, "-c", _FLEET_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "FLEET-SMOKE-OK" in result.stdout, result.stdout


def test_tsan_fleet_smoke():
    """TSan flavor: the aggregation thread's tick races the application
    ranks' collectives and the rank-0 fleet() reader — the fleetMu_/
    auxMu_ publish protocol and the stop() abort/join ordering are
    exactly what this must keep benign."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_tsan.so")
    if not os.path.exists(lib):
        pytest.skip("TSan flavor not built (make native SANITIZE=thread)")
    env = _sanitizer_env(("libtsan.so", "libstdc++.so"), lib,
                         {"TSAN_OPTIONS": "halt_on_error=1 "
                          "report_signal_unsafe=0 history_size=7",
                          "TPUCOLL_FLEETOBS_INTERVAL_MS": "80"})
    result = subprocess.run([sys.executable, "-c", _FLEET_PROG],
                            capture_output=True, text=True, timeout=600,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "FLEET-SMOKE-OK" in result.stdout, result.stdout


_LAZY_BOOT_PROG = f"""
import os, sys, threading
sys.path.insert(0, {_REPO!r})
import numpy as np
import gloo_tpu

size, rph = 6, 3
store = gloo_tpu.HashStore()
errors = []

def worker(rank):
    try:
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.set_host_id("sanhost%d" % (rank // rph))
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        for i in range(4):
            x = np.full(2048, float(rank + 1), dtype=np.float32)
            ctx.allreduce(x, tag=1)
            assert x[0] == size * (size + 1) / 2, x[0]
            a2a = np.full((size, 4), float(rank), dtype=np.float32)
            out = ctx.alltoall(a2a, tag=2)
            assert out[rank][0] == float(rank), out[rank][0]
        # One quiesced broker dial: exercises LRU eviction + redial
        # (TPUCOLL_MAX_PAIRS=1) under the sanitizer.
        ctx.barrier(tag=3)
        z = np.full(8, float(rank), dtype=np.float32)
        ctx.send(z, (rank + 2) % size, slot=9)
        w = np.empty(8, dtype=np.float32)
        ctx.recv(w, (rank - 2) % size, slot=9)
        assert w[0] == float((rank - 2) % size), w[0]
        boot = ctx.metrics()["boot"]
        assert boot["lazy"] is True, boot
        # Host leaders keep the eager leader mesh, leaving them a single
        # non-eager peer — the cap=1 LRU never has to evict for them.
        # Non-leaders churn 2-3 broker peers through the cap every round.
        if rank % rph != 0:
            assert boot["pairs_evicted"] > 0, boot
        ctx.barrier(tag=4)
        ctx.close()
    except BaseException as e:
        errors.append((rank, repr(e)))

threads = [threading.Thread(target=worker, args=(r,))
           for r in range(size)]
for t in threads:
    t.start()
for t in threads:
    t.join(240)
assert not errors, errors
print("LAZY-BOOT-SMOKE-OK")
"""


def test_asan_lazy_bootstrap_smoke():
    """Skip-unless-built ASan smoke of the lazy bootstrap plane
    (docs/bootstrap.md): 6 thread-ranks over 2 simulated hosts come up
    with TPUCOLL_BOOT_MODE=lazy, run collectives that broker-dial on
    first use, and churn the LRU cap (TPUCOLL_MAX_PAIRS=1) — the
    dial / evict / graveyard-reap lifecycle is exactly where a
    use-after-free in the pair broker would hide."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    env = _sanitizer_env(("libasan.so", "libstdc++.so"), lib,
                         {"ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
                          "TPUCOLL_BOOT_MODE": "lazy",
                          "TPUCOLL_MAX_PAIRS": "1"})
    result = subprocess.run([sys.executable, "-c", _LAZY_BOOT_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "LAZY-BOOT-SMOKE-OK" in result.stdout, result.stdout


_WIRE_PIPE_PROG = f"""
import sys, threading
sys.path.insert(0, {_REPO!r})
import numpy as np
import gloo_tpu

size = 3
store = gloo_tpu.HashStore()
errors = []

def worker(rank):
    try:
        ctx = gloo_tpu.Context(rank, size, timeout=60)
        ctx.connect_full_mesh(store, gloo_tpu.Device())
        total = size * (size + 1) / 2
        # Repeated pipelined q8/q4 allreduces on ONE buffer: a cached
        # plan replays the codec-pool fan-out and the slot-3 residual
        # arena every call.
        x = np.empty(3 * 256 * 5 + 17, dtype=np.float32)
        for i in range(8):
            x[:] = rank + 1
            ctx.allreduce(x, algorithm="ring_q8_wire", tag=1)
            assert abs(x[0] - total) < 0.1, (i, x[0])
        for i in range(4):
            x[:] = rank + 1
            ctx.allreduce(x, algorithm="ring_q4_wire", tag=2)
            assert abs(x[0] - total) < 0.5, (i, x[0])
        counts = [600, 700, 800]
        y = np.empty(sum(counts), dtype=np.float32)
        for i in range(4):
            y[:] = rank + 1
            out = ctx.reduce_scatter(y, recv_counts=counts, wire="q8",
                                     tag=3)
            assert abs(out[0] - total) < 0.1, (i, out[0])
        ctx.barrier(tag=9)
        ctx.close()
    except BaseException as e:
        errors.append((rank, repr(e)))

threads = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
[t.start() for t in threads]
[t.join(240) for t in threads]
assert not errors, errors
print("WIRE-PIPE-SMOKE-OK")
"""


def test_asan_wire_pipeline_smoke():
    """Skip-unless-built ASan smoke of the pipelined wire codec engine:
    3 ranks running q8/q4 allreduces and a wire reduce_scatter with the
    codec pool wide (TPUCOLL_CODEC_THREADS=4) and a deep hop pipeline
    (TPUCOLL_CODEC_PIPELINE=6) on cached plans — the async encode jobs
    writing tx staging, the decode-on-arrival jobs writing the work
    buffer, and the plan-persistent EF residual arena are the
    memory-shape code under test."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_asan.so")
    if not os.path.exists(lib):
        pytest.skip("ASan flavor not built (make native SANITIZE=address)")
    env = _sanitizer_env(("libasan.so", "libstdc++.so"), lib,
                         {"ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
                          "TPUCOLL_CODEC_THREADS": "4",
                          "TPUCOLL_CODEC_PIPELINE": "6"})
    result = subprocess.run([sys.executable, "-c", _WIRE_PIPE_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "WIRE-PIPE-SMOKE-OK" in result.stdout, result.stdout


def test_ubsan_wire_pipeline_smoke():
    """UBSan flavor of the pipelined-wire smoke: the nibble pack/unpack
    bit twiddling and the scale divisions are int-width/shift territory
    (-fno-sanitize-recover: the first UB hit aborts the child)."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native",
                       "libtpucoll_ubsan.so")
    if not os.path.exists(lib):
        pytest.skip(
            "UBSan flavor not built (make native SANITIZE=undefined)")
    env = _sanitizer_env(("libubsan.so", "libstdc++.so"), lib,
                         {"TPUCOLL_CODEC_THREADS": "4",
                          "TPUCOLL_CODEC_PIPELINE": "6"})
    result = subprocess.run([sys.executable, "-c", _WIRE_PIPE_PROG],
                            capture_output=True, text=True, timeout=420,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "WIRE-PIPE-SMOKE-OK" in result.stdout, result.stdout


def test_tsan_wire_pipeline_smoke():
    """TSan flavor — the one that earns its keep here: pool workers
    claim shards off the shared atomic counter while the op thread
    encodes alongside them, async sub-block encode tickets race the
    sends that publish them, and decode-on-arrival jobs write disjoint
    work-buffer spans concurrently. Any missing happens-before edge in
    the ticket/wait protocol is exactly what this run must surface."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_tsan.so")
    if not os.path.exists(lib):
        pytest.skip("TSan flavor not built (make native SANITIZE=thread)")
    env = _sanitizer_env(("libtsan.so", "libstdc++.so"), lib,
                         {"TSAN_OPTIONS": "halt_on_error=1 "
                          "report_signal_unsafe=0 history_size=7",
                          "TPUCOLL_CODEC_THREADS": "4",
                          "TPUCOLL_CODEC_PIPELINE": "6"})
    result = subprocess.run([sys.executable, "-c", _WIRE_PIPE_PROG],
                            capture_output=True, text=True, timeout=600,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "WIRE-PIPE-SMOKE-OK" in result.stdout, result.stdout


def test_tsan_lazy_bootstrap_smoke():
    """TSan flavor of the lazy bootstrap smoke: concurrent first-use
    dials, context-level recv matching against rx-only inbound pairs,
    and cap eviction from racing op threads — the broker's lock
    discipline under the race detector."""
    lib = os.path.join(_REPO, "gloo_tpu", "_native", "libtpucoll_tsan.so")
    if not os.path.exists(lib):
        pytest.skip("TSan flavor not built (make native SANITIZE=thread)")
    env = _sanitizer_env(("libtsan.so", "libstdc++.so"), lib,
                         {"TSAN_OPTIONS": "halt_on_error=1 "
                          "report_signal_unsafe=0 history_size=7",
                          "TPUCOLL_BOOT_MODE": "lazy",
                          "TPUCOLL_MAX_PAIRS": "1"})
    result = subprocess.run([sys.executable, "-c", _LAZY_BOOT_PROG],
                            capture_output=True, text=True, timeout=600,
                            env=env)
    assert result.returncode == 0, (result.stdout, result.stderr[-3000:])
    assert "LAZY-BOOT-SMOKE-OK" in result.stdout, result.stdout
