"""RoPE with explicit positions: the long-context/SP-critical property is
that per-shard GLOBAL offsets reproduce full-sequence rotation exactly."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from gloo_tpu.ops import apply_rope, rope_positions  # noqa: E402
from gloo_tpu.tpu import make_mesh  # noqa: E402


def test_rope_relative_invariance():
    """Attention scores depend only on relative distance: shifting every
    position by a constant leaves q . k unchanged."""
    d = 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 2, 8, d), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 8, d), jnp.float32)

    def scores(off):
        pos = rope_positions(8, off)
        return jnp.einsum("bhqd,bhkd->bhqk", apply_rope(q, pos),
                          apply_rope(k, pos))

    np.testing.assert_allclose(np.asarray(scores(0)),
                               np.asarray(scores(1000)), atol=2e-3)


def test_rope_shard_offsets_match_full_sequence():
    full = jnp.asarray(np.random.RandomState(1).randn(1, 2, 16, 32),
                       jnp.float32)
    whole = apply_rope(full, rope_positions(16))
    lo = apply_rope(full[:, :, :8], rope_positions(8, 0))
    hi = apply_rope(full[:, :, 8:], rope_positions(8, 8))
    np.testing.assert_array_equal(
        np.asarray(whole), np.asarray(jnp.concatenate([lo, hi], axis=2)))


def test_rope_ring_attention_global_positions():
    """RoPE + ring attention: each shard rotates by rank * t_local, and
    the distributed result matches full-sequence RoPE attention."""
    from gloo_tpu.parallel import ring_attention
    from gloo_tpu.tpu import spmd

    mesh = make_mesh({"seq": -1})
    p = mesh.shape["seq"]
    b, h, t, d = 1, 2, 8 * p, 32
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    def shard_fn(q, k, v):
        t_local = q.shape[2]
        pos = rope_positions(t_local, spmd.rank("seq") * t_local)
        return ring_attention(apply_rope(q, pos), apply_rope(k, pos), v,
                              "seq")

    got = np.asarray(jax.jit(jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(None, None, "seq"),) * 3,
        out_specs=P(None, None, "seq"), check_vma=False))(q, k, v))

    qr = apply_rope(q, rope_positions(t))
    kr = apply_rope(k, rope_positions(t))
    s = jnp.einsum("bhqd,bhkd->bhqk", qr, kr) / np.sqrt(d)
    s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -jnp.inf)
    want = np.asarray(jnp.einsum("bhqk,bhkd->bhqd",
                                 jax.nn.softmax(s, axis=-1), v))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_rope_odd_head_dim_rejected():
    with pytest.raises(ValueError, match="even"):
        apply_rope(jnp.zeros((1, 1, 4, 33)), rope_positions(4))


def test_transformer_rope_config():
    from gloo_tpu.models import Transformer, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                            n_layers=1, d_ff=128, max_seq_len=32,
                            use_rope=True, dtype=jnp.float32)
    m = Transformer(cfg)
    params = m.init(jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 32)))
    loss, grads = jax.value_and_grad(m.loss)(params, (toks, toks))
    assert np.isfinite(float(loss))
    # no dead learned positional table under RoPE
    assert "pos" not in params
