"""Chaos harness: drive the transport's failure machinery with the
deterministic fault-injection plane (csrc/tpucoll/fault/, docs/faults.md)
and assert the recovery CONTRACT, not just the happy path:

- tolerated faults (delay, dup, stall) complete with correct results;
- destructive faults (truncate, corrupt, kill) fail loudly with the
  faulted peer named, and `resilience.rebuild_after_failure` produces a
  working context afterwards;
- connect-path faults (connect_refuse) exercise the typed-handshake
  retry classification and still converge;
- the same seed + schedule fires a byte-identical sequence
  (tc_fault_report), so every red run here is replayable.

Multiprocess (P=3) over a FileStore, like test_multiproc.py — real
processes, real sockets, schedules delivered via TPUCOLL_FAULT_FILE so
the env hook is covered too.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_worker(body: str, rank: int, size: int, store: str,
                  schedule=None, extra_env=None):
    """Launch a child running `body` with ctx/rank/size/store bound and
    (optionally) a fault schedule installed via TPUCOLL_FAULT_FILE."""
    env = dict(os.environ)
    env.pop("TPUCOLL_FAULT_FILE", None)
    if schedule is not None:
        path = os.path.join(store, "fault_schedule.json")
        if not os.path.exists(path):
            with open(path, "w") as f:
                json.dump(schedule, f)
        env["TPUCOLL_FAULT_FILE"] = path
    if extra_env:
        env.update(extra_env)
    prog = textwrap.dedent("""
        import json, os, signal, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu
        from gloo_tpu import fault
        from gloo_tpu.resilience import rebuild_after_failure

        rank = {rank}; size = {size}
        store = gloo_tpu.FileStore({store!r})
        ctx = gloo_tpu.Context(rank, size, timeout=10.0)
        ctx.connect_full_mesh(store, gloo_tpu.Device())
    """).format(repo=_REPO, rank=rank, size=size, store=store) + \
        textwrap.dedent(body)
    return subprocess.Popen([sys.executable, "-c", prog],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env)


def _run(body, size, store, schedule=None, extra_env=None, timeout=120):
    procs = [_spawn_worker(body, r, size, store, schedule, extra_env)
             for r in range(size)]
    outs = [p.communicate(timeout=timeout) for p in procs]
    return procs, outs


def _assert_ok(procs, outs, ranks=None):
    for r, (p, out) in enumerate(zip(procs, outs)):
        if ranks is not None and r not in ranks:
            continue
        assert p.returncode == 0, (r, p.returncode, out)
        assert "OK" in out[0], (r, out)


# A shared body for the destructive fault classes: run an allreduce that
# the schedule breaks, assert the loud failure (pattern per rank), then
# rebuild over the same store and prove the new context computes a
# correct allreduce at full size (no process died — the fault plane
# breaks links, not ranks).
_BREAK_THEN_REBUILD = """
x = np.full(4096, float(rank + 1), dtype=np.float32)
err = None
try:
    ctx.allreduce(x, tag=1, timeout=3.0)
except gloo_tpu.IoError as exc:   # TimeoutError subclasses IoError
    err = str(exc)
assert err is not None, "allreduce unexpectedly survived the fault"
expect = {expect_err!r}
if expect.get(str(rank)):
    assert expect[str(rank)] in err, (rank, err)
new_ctx, new_rank, new_size = rebuild_after_failure(
    store, gloo_tpu.Device(), old_rank=rank, old_size=size, generation=1,
    settle=3.0, timeout=60.0, failed_context=ctx)
assert new_ctx is not None, "rebuild failed"
assert new_size == size, new_size
y = np.full(1024, float(new_rank + 1), dtype=np.float32)
new_ctx.allreduce(y, tag=2)
assert y[0] == size * (size + 1) / 2, y[0]
new_ctx.close()
print("OK", json.dumps(fault.report(rank=rank)))
"""


def test_chaos_delay_completes():
    """An injected link delay is invisible to correctness: collectives
    complete with the right values, and the firing is visible in the
    report AND the injecting rank's metrics registry."""
    store = tempfile.mkdtemp()
    schedule = {"seed": 1, "faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data"},
         "action": "delay", "ms": 120, "count": 2}]}
    body = """
for i in range(3):
    x = np.full(2048, float(rank + 1), dtype=np.float32)
    ctx.allreduce(x, tag=i)
    assert x[0] == size * (size + 1) / 2, (i, x[0])
if rank == 1:
    fired = fault.report(rank=1)
    assert sum(1 for e in fired if e["action"] == "delay") == 2, fired
    snap = ctx.metrics()
    assert snap["faults"].get("delay", 0) == 2, snap["faults"]
ctx.close()
print("OK")
"""
    procs, outs = _run(body, 3, store, schedule)
    _assert_ok(procs, outs)


def test_chaos_dup_completes():
    """Duplicated wire messages are tolerated: the first copy satisfies
    the posted receive, the stale duplicate lands in the stash and is
    dropped at close. Requires the app-level rule that slots/tags are
    not reused — which these unique-tag workloads obey."""
    store = tempfile.mkdtemp()
    schedule = {"seed": 2, "faults": [
        {"when": {"rank": 1, "opcode": "data", "min_bytes": 1},
         "action": "dup", "count": 2},
        {"when": {"rank": 1, "opcode": "put"}, "action": "dup"}]}
    body = """
# p2p ring with unique slots, then a collective with a unique tag.
mine = np.full(512, float(rank), dtype=np.float64)
got = np.zeros(512, dtype=np.float64)
sbuf = ctx.register(mine)
rbuf = ctx.register(got)
sbuf.send((rank + 1) % size, slot=100 + rank)
rbuf.recv((rank - 1) % size, slot=100 + (rank - 1) % size)
sbuf.wait_send(); rbuf.wait_recv()
assert got[0] == float((rank - 1) % size), got[0]
x = np.full(1000, float(rank + 1), dtype=np.float32)
ctx.allreduce(x, tag=7)
assert x[0] == size * (size + 1) / 2, x[0]
# Duplicated notify-put: the data write is idempotent and the duplicate
# goes out notify-less, so EXACTLY one arrival completes per put.
region = np.zeros(64, dtype=np.float64)
region_buf = ctx.register(region)
keys = [k.tobytes() for k in ctx.allgather(
    np.frombuffer(region_buf.get_remote_key(), dtype=np.uint8).copy(),
    tag=8)]
if rank == 1:
    payload = np.full(64, 42.0, dtype=np.float64)
    pbuf = ctx.register(payload)
    pbuf.put(keys[0], notify=True)
    pbuf.wait_send()
if rank == 0:
    assert region_buf.wait_put(timeout=10.0) == 1
    assert region[0] == 42.0, region[0]
    try:
        src = region_buf.wait_put(timeout=0.5)
        raise SystemExit(
            f"duplicate notify-put delivered a second arrival from {src}")
    except gloo_tpu.TimeoutError:
        pass  # exactly one arrival: the duplicate was notify-less
ctx.barrier(tag=9)
if rank == 1:
    fired = fault.report(rank=1)
    assert any(e["action"] == "dup" and e["opcode"] == "data"
               for e in fired), fired
    assert any(e["action"] == "dup" and e["opcode"] == "put"
               for e in fired), fired
ctx.close()
print("OK")
"""
    procs, outs = _run(body, 3, store, schedule)
    _assert_ok(procs, outs)


def test_chaos_stall_trips_watchdog():
    """A stalled peer trips the straggler watchdog on the blocked rank,
    which names the peer and slot — and the collective still completes
    once the stall clears (a stall is a delay, not a death)."""
    store = tempfile.mkdtemp()
    schedule = {"seed": 3, "faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 1},
         "action": "stall", "ms": 700}]}
    body = """
ctx.set_watchdog(0.15)
x = np.full(2048, float(rank + 1), dtype=np.float32)
ctx.allreduce(x, tag=1)
assert x[0] == size * (size + 1) / 2, x[0]
if rank == 0:
    snap = ctx.metrics()
    assert snap["watchdog"]["stalls"] >= 1, snap["watchdog"]
    assert snap["watchdog"]["last"]["peer"] == 1, snap["watchdog"]
if rank == 1:
    assert any(e["action"] == "stall" for e in fault.report(rank=1))
ctx.close()
print("OK")
"""
    procs, outs = _run(body, 3, store, schedule)
    _assert_ok(procs, outs)


def test_chaos_corrupt_fails_loudly_then_rebuild():
    """A corrupted wire header is detected at the protocol layer: the
    receiver poisons the pair naming the sender, every rank fails
    loudly, and a rebuild over the same store recovers all survivors."""
    store = tempfile.mkdtemp()
    schedule = {"seed": 4, "faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 1,
                  "min_bytes": 1024},
         "action": "corrupt"}]}
    body = _BREAK_THEN_REBUILD.format(
        expect_err={"0": "protocol violation from rank 1"})
    procs, outs = _run(body, 3, store, schedule)
    _assert_ok(procs, outs)
    # The corrupt fired exactly once, on rank 1 (deterministic nth=1).
    rank1_fired = json.loads(outs[1][0].split("OK ", 1)[1])
    assert [e["action"] for e in rank1_fired] == ["corrupt"], rank1_fired


def test_chaos_truncate_fails_loudly_then_rebuild():
    """A truncated message severs the stream mid-payload: the receiver
    observes EOF inside a message and names the peer; the sender's pair
    carries the injection message. Rebuild recovers everyone."""
    store = tempfile.mkdtemp()
    schedule = {"seed": 5, "faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 1,
                  "min_bytes": 1024},
         "action": "truncate"}]}
    body = _BREAK_THEN_REBUILD.format(
        expect_err={"0": "rank 1",
                    "1": "fault injection: truncated message to rank 0"})
    procs, outs = _run(body, 3, store, schedule)
    _assert_ok(procs, outs)


def test_chaos_kill_fails_loudly_then_rebuild():
    """A hard-killed pair drives the full resilience path: the injecting
    rank's collective raises naming the peer, the peer sees an
    unexpected EOF naming the injector, and rebuild_after_failure forms
    a working context (all processes survive a link kill)."""
    store = tempfile.mkdtemp()
    schedule = {"seed": 6, "faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 1,
                  "min_bytes": 1024},
         "action": "kill"}]}
    body = _BREAK_THEN_REBUILD.format(
        expect_err={"1": "fault injection: killed connection to rank 0",
                    "0": "rank 1"})
    procs, outs = _run(body, 3, store, schedule)
    _assert_ok(procs, outs)


def test_chaos_kill_mid_async_bucketed_allreduce():
    """Chaos with async work in flight (docs/async.md): a kill fault
    fires mid bucketed-async allreduce on rank 1's lane traffic. The
    victim bucket's Work.wait() raises naming the faulted peer, the
    failing lane is named in the message, rebuild_after_failure reforms
    a working full-size context afterwards, and — the determinism
    acceptance — two same-seed runs produce byte-identical per-(rank,
    domain) fault reports even though the firing lanes run concurrently
    (rule state is keyed per (rule, rank, channel, domain); lane k is
    domain k + 1)."""
    schedule = {"seed": 21, "faults": [
        # Only bucket-sized traffic matches (the engine's fork bootstrap
        # and the small control collectives stay under min_bytes); one
        # kill per (channel, domain) stream state, so each lane that
        # carries a bucket to rank 0 loses its pair deterministically.
        {"when": {"rank": 1, "peer": 0, "opcode": "data",
                  "min_bytes": 40000},
         "action": "kill", "count": 1}]}
    body = """
from gloo_tpu import GradientBucketer

engine = ctx.async_engine(lanes=2)
bucketer = GradientBucketer(engine, bucket_bytes=256 << 10)
rng = np.random.default_rng(5)  # identical stream on every rank
grads = [np.full(int(n), float(rank + 1), dtype=np.float32)
         for n in rng.integers(2000, 30000, size=24)]
err = None
try:
    for g in grads:
        bucketer.add(g)
    bucketer.finish()
except gloo_tpu.IoError as exc:   # TimeoutError subclasses IoError
    err = str(exc)
assert err is not None, "bucketed allreduce unexpectedly survived"
assert "lane" in err, err
if rank == 1:
    assert "fault injection: killed connection to rank 0" in err, err
fired = sorted(((e["domain"], e["n"], e["action"], e["peer"],
                 e["nbytes"]) for e in fault.report(rank=1)))
# settle must outlast the slowest rank's exit from the broken step: a
# rank whose buckets merely STALL (its pairs weren't the killed ones)
# only unblocks at its 10s collective timeout, well after the injector's
# EOF-fast failure.
new_ctx, new_rank, new_size = rebuild_after_failure(
    store, gloo_tpu.Device(), old_rank=rank, old_size=size, generation=1,
    settle=15.0, timeout=90.0, failed_context=ctx)
assert new_ctx is not None, "rebuild failed"
assert new_size == size, new_size
y = np.full(1024, float(new_rank + 1), dtype=np.float32)
new_ctx.allreduce(y, tag=2)
assert y[0] == size * (size + 1) / 2, y[0]
new_ctx.close()
print("OK", json.dumps(fired))
"""
    reports = []
    for attempt in range(2):
        store = tempfile.mkdtemp()
        procs, outs = _run(body, 3, store, schedule, timeout=180)
        _assert_ok(procs, outs)
        # Rank 1's canonicalized (domain, n)-sorted firing report; every
        # rank prints the same process-global table slice.
        line = [ln for ln in outs[1][0].splitlines()
                if ln.startswith("OK ")][0]
        fired = json.loads(line[3:])
        assert fired, "kill rule never fired"
        # The firing domains are lane domains (> 0): the faults really
        # hit async-lane traffic, not the parent context.
        assert all(entry[0] >= 1 for entry in fired), fired
        reports.append(fired)
    assert reports[0] == reports[1], reports


def test_chaos_connect_refuse_exercises_retry():
    """Refused connections during the handshake take the typed retry
    classification: bounded backoff retries, counted in the metrics
    registry, and the mesh still comes up."""
    store = tempfile.mkdtemp()
    schedule = {"seed": 7, "faults": [
        {"when": {"rank": 2}, "action": "connect_refuse", "count": 2}]}
    body = """
x = np.full(1000, float(rank + 1), dtype=np.float32)
ctx.allreduce(x, tag=1)
assert x[0] == size * (size + 1) / 2, x[0]
if rank == 2:
    fired = fault.report(rank=2)
    assert sum(1 for e in fired
               if e["action"] == "connect_refuse") == 2, fired
    assert ctx.metrics()["retries"] >= 2
ctx.close()
print("OK")
"""
    procs, outs = _run(body, 3, store, schedule)
    _assert_ok(procs, outs)


def test_chaos_same_seed_same_firing_sequence():
    """Acceptance: same seed + same schedule => byte-identical fault
    firing sequence, via tc_fault_report across two runs of the same
    deterministic workload (probabilistic rule, so the PRNG — not just
    the counters — must reproduce)."""
    from gloo_tpu import fault
    from tests.harness import spawn

    schedule = {"seed": 11, "faults": [
        {"when": {"rank": 1, "opcode": "data"},
         "action": "delay", "ms": 1, "prob": 0.5, "seed": 99}]}

    def workload():
        def fn(ctx, rank):
            data = np.arange(64, dtype=np.float64)
            out = np.zeros(64, dtype=np.float64)
            for i in range(40):
                if rank == 1:
                    ctx.send(data, dst=0, slot=500 + i)
                else:
                    ctx.recv(out, src=1, slot=500 + i)
            ctx.barrier(tag=999)

        spawn(2, fn, timeout=60)
        return json.dumps(fault.report(rank=1), sort_keys=True)

    fault.install(schedule)
    try:
        first = workload()
        fault.install(schedule)  # reinstall: reset counters + report
        second = workload()
    finally:
        fault.clear()
    assert first == second
    fired = json.loads(first)
    # The coin actually flipped both ways (0 or 40 fires would mean the
    # prob gate is broken, not deterministic).
    assert 0 < len(fired) < 40, len(fired)


def test_sigkill_mid_allreduce_rebuild_and_blame():
    """Satellite: SIGKILL one rank mid-allreduce. Survivors must (a)
    rebuild into a working smaller context via rebuild_after_failure and
    (b) publish failure evidence such that stall_reports names the dead
    rank — even though detection was EOF-fast and the watchdog never
    fired (the transport-failure record supplies the suspect)."""
    import gloo_tpu
    from gloo_tpu.resilience import stall_reports

    store = tempfile.mkdtemp()
    body = """
x = np.full(1 << 18, float(rank + 1), dtype=np.float32)
if rank == 2:
    os.kill(os.getpid(), signal.SIGKILL)
try:
    ctx.allreduce(x, tag=1, timeout=3.0)
    print("UNEXPECTED-SUCCESS"); sys.exit(3)
except gloo_tpu.IoError:
    pass
new_ctx, new_rank, new_size = rebuild_after_failure(
    store, gloo_tpu.Device(), old_rank=rank, old_size=size, generation=1,
    settle=3.0, timeout=60.0, failed_context=ctx)
assert new_ctx is not None, "rebuild failed"
assert new_size == 2, new_size
y = np.full(100, float(new_rank + 1), dtype=np.float32)
new_ctx.allreduce(y, tag=2)
assert y[0] == 3.0, y[0]
new_ctx.close()
print("OK")
"""
    procs, outs = _run(body, 3, store)
    assert procs[2].returncode == -signal.SIGKILL
    _assert_ok(procs, outs, ranks=(0, 1))
    reports = stall_reports(gloo_tpu.FileStore(store), generation=1,
                            old_size=3)
    assert reports, "no survivor published failure evidence"
    suspects = [r.get("suspect") for r in reports.values()]
    assert max(set(suspects), key=suspects.count) == 2, reports


def test_stash_backpressure_under_injected_delay():
    """Satellite: when the fault plane delays a rank's receive posting,
    the peer's early arrivals cross the TPUCOLL_MAX_STASH_BYTES
    watermark, backpressure engages, and the engagement is visible in
    the metrics registry (stash_pauses / per-peer rx_pauses) — then the
    delayed receives drain everything correctly."""
    from gloo_tpu import fault
    from tests.harness import spawn

    os.environ["TPUCOLL_MAX_STASH_BYTES"] = str(1 << 20)
    fault.install({"seed": 12, "faults": [
        {"when": {"rank": 0, "peer": 1, "opcode": "data", "nth": 1},
         "action": "delay", "ms": 800}]})
    chunk_words = (256 << 10) // 8  # 256 KiB per message
    n_chunks = 24                   # 6 MiB total, far past the 1 MiB mark

    def fn(ctx, rank):
        if rank == 1:
            bufs = []
            for i in range(n_chunks):
                data = np.full(chunk_words, float(i), dtype=np.float64)
                b = ctx.register(data)
                b.send(0, slot=100 + i)
                bufs.append((b, data))
            go = np.zeros(4, dtype=np.float64)
            ctx.recv(go, src=0, slot=1)
            for b, _ in bufs:
                b.wait_send(timeout=30.0)
            ctx.barrier(tag=999)
            return None
        # rank 0: the delayed send stalls this thread ~800ms before any
        # receive is posted — exactly "the fault plane delays posted
        # receives" — while rank 1's flood crosses the watermark.
        go = np.zeros(4, dtype=np.float64)
        ctx.send(go, dst=1, slot=1)   # fault fires here (sleeps)
        outs = [np.zeros(chunk_words, dtype=np.float64)
                for _ in range(n_chunks)]
        bufs = [ctx.register(o) for o in outs]
        for i, b in enumerate(bufs):
            b.recv(1, slot=100 + i)
        for b in bufs:
            assert b.wait_recv(timeout=30.0) == 1
        for i, o in enumerate(outs):
            assert o[0] == float(i), (i, o[0])
        snap = ctx.metrics()
        ctx.barrier(tag=999)
        return snap

    try:
        results = spawn(2, fn, timeout=90)
    finally:
        fault.clear()
        del os.environ["TPUCOLL_MAX_STASH_BYTES"]
    snap = results[0]
    assert snap["stash_pauses"] >= 1, snap["stash_pauses"]
    assert snap["transport"][1]["rx_pauses"] >= 1, snap["transport"][1]


def test_fault_schedule_malformed_fails_loudly():
    """An operator's explicit schedule must never be silently dropped:
    malformed JSON and unknown fields raise, both through install() and
    the TPUCOLL_FAULT_FILE hook."""
    import gloo_tpu
    from gloo_tpu import fault

    with pytest.raises(gloo_tpu.Error, match="fault schedule"):
        fault.install("{not json")
    with pytest.raises(gloo_tpu.Error, match="unknown action"):
        fault.install({"faults": [{"action": "explode"}]})
    with pytest.raises(gloo_tpu.Error, match="faults"):
        fault.install({"seed": 3})
    # Misspelled keys must not silently reinterpret the rule (a typo'd
    # "rank" would otherwise widen a kill to every rank).
    with pytest.raises(gloo_tpu.Error, match='unknown field "rnak"'):
        fault.install({"faults": [{"when": {"rnak": 1},
                                   "action": "kill"}]})
    with pytest.raises(gloo_tpu.Error, match='unknown field "mss"'):
        fault.install({"faults": [{"action": "delay", "mss": 500}]})
    # env-hook: a child process pointed at a bad file must fail connect.
    store = tempfile.mkdtemp()
    bad = os.path.join(store, "bad.json")
    with open(bad, "w") as f:
        f.write("{broken")
    prog = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {_REPO!r})
        import gloo_tpu
        ctx = gloo_tpu.Context(0, 1, timeout=5.0)
        try:
            ctx.connect_full_mesh(gloo_tpu.FileStore({store!r}),
                                  gloo_tpu.Device())
            print("UNEXPECTED"); sys.exit(3)
        except gloo_tpu.Error as e:
            assert "fault schedule" in str(e), e
            print("LOUD"); sys.exit(0)
    """)
    env = dict(os.environ, TPUCOLL_FAULT_FILE=bad)
    p = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, env=env, timeout=60)
    assert p.returncode == 0 and "LOUD" in p.stdout, (p.stdout, p.stderr)


def test_wildcard_destructive_rule_skips_connect_events():
    """A wildcard-opcode destructive rule (the fault.py docstring's own
    {"when": {"rank": 1}, "action": "kill", "count": 1} example) must
    not match — or silently burn its count on — connect events: the
    kill lands on rank 1's first SEND, and the report never claims a
    kill fired at opcode connect."""
    import gloo_tpu
    from gloo_tpu import fault
    from tests.harness import spawn

    fault.install({"faults": [
        {"when": {"rank": 1}, "action": "kill", "count": 1}]})

    def fn(ctx, rank):
        x = np.full(256, float(rank + 1), dtype=np.float32)
        try:
            ctx.allreduce(x, tag=1, timeout=5.0)
            return "survived"
        except gloo_tpu.Error:
            return "failed"

    try:
        results = spawn(2, fn, timeout=60)
        fired = fault.report()
    finally:
        fault.clear()
    assert "failed" in results, results
    assert all(e["opcode"] != "connect" for e in fired), fired
    assert any(e["action"] == "kill" and e["opcode"] == "data"
               for e in fired), fired


# ---------------------------------------------------------------------------
# ISSUE 13 satellites: chaos over the hierarchical (kHier) collectives
# ---------------------------------------------------------------------------

# Shared body for the hier SIGKILL arms: 2 simulated hosts x 2 ranks
# (TPUCOLL_HOST_ID per process), one healthy kHier allreduce so the
# split sub-groups exist, then `victim` SIGKILLs itself mid-kHier.
# Survivors assert a TYPED failure whose message names the hier phase +
# subgroup + subgroup->global rank map, then rebuild over the same store
# and prove the REBUILT context reforms working split groups (new
# split_by_host + subgroup allreduce + a kHier allreduce on the new
# topology).
_HIER_KILL_BODY = """
victim = {victim}
warm = np.full(256, 1.0, dtype=np.float32)
ctx.allreduce(warm, algorithm="hier", tag=1)
assert warm[0] == float(size), warm[0]
x = np.full(1 << 18, float(rank + 1), dtype=np.float32)
if rank == victim:
    os.kill(os.getpid(), signal.SIGKILL)
err = None
try:
    ctx.allreduce(x, algorithm="hier", tag=2, timeout=4.0)
except gloo_tpu.IoError as exc:
    err = str(exc)
assert err is not None, "kHier allreduce unexpectedly survived"
if rank == {named_rank}:
    # This survivor shares a plane with the victim: its failing phase
    # must name the hier collective, the subgroup, and the rank map.
    assert "hier allreduce" in err, err
    assert "subgroup" in err and "->" in err, err
# settle must exceed the slowest survivor's detection lag: hier
# failure detection CASCADES through phases (a healthy plane only
# notices at its own phase timeout), so the 4s op timeout bounds it.
new_ctx, new_rank, new_size = rebuild_after_failure(
    store, gloo_tpu.Device(), old_rank=rank, old_size=size, generation=1,
    settle=6.0, timeout=90.0, failed_context=ctx)
assert new_ctx is not None, "rebuild failed"
assert new_size == size - 1, new_size
# Reform split groups on the rebuilt context (TPUCOLL_HOST_ID still
# groups the survivors into hosts).
local = new_ctx.split_by_host(tag=4)
y = np.full(128, float(new_rank + 1), dtype=np.float32)
local.allreduce(y)
assert y[0] > 0
z = np.full(1024, 1.0, dtype=np.float32)
new_ctx.allreduce(z, algorithm="hier", tag=5)
assert z[0] == float(new_size), z[0]
local.close()
new_ctx.close()
print("OK")
"""


def _run_hier_kill(victim, named_rank):
    store = tempfile.mkdtemp()
    size = 4
    procs = []
    for r in range(size):
        procs.append(_spawn_worker(
            _HIER_KILL_BODY.format(victim=victim, named_rank=named_rank),
            r, size, store,
            extra_env={"TPUCOLL_HOST_ID": f"chaoshost{r // 2}"}))
    outs = [p.communicate(timeout=180) for p in procs]
    assert procs[victim].returncode == -signal.SIGKILL
    for r, (p, out) in enumerate(zip(procs, outs)):
        if r == victim:
            continue
        assert p.returncode == 0, (r, p.returncode, out)
        assert "OK" in out[0], (r, out)


def test_chaos_sigkill_nonleader_mid_hier_allreduce():
    """SIGKILL a NON-LEADER (rank 3, host 1) mid-kHier: its co-hosted
    leader (rank 2) fails typed in the intra-host phase naming the
    subgroup, and rebuild_after_failure reforms working split groups."""
    _run_hier_kill(victim=3, named_rank=2)


def test_chaos_sigkill_leader_mid_hier_allreduce():
    """SIGKILL a LEADER (rank 2, host 1) mid-kHier: both its co-hosted
    member (rank 3, intra-host phase) and the peer leader (rank 0,
    inter-host phase) observe the death; rank 0's typed error names the
    hier subgroup. Rebuild reforms split groups on the 3-survivor
    topology (host 1 degrades to one rank)."""
    _run_hier_kill(victim=2, named_rank=0)


# ---------------------------------------------------------------------------
# ISSUE 14 satellite: chaos under the elastic membership plane
# ---------------------------------------------------------------------------


def test_chaos_link_kill_under_elastic_recovers_full_size():
    """Hard-evidence recovery path of the elastic plane (docs/elastic.md):
    a fault-injected link kill breaks a collective while every PROCESS
    stays alive — so no lease ever expires. The survivors publish their
    failure evidence (transport-failure verdicts), the coordinator
    bumps the epoch with the SAME members after one grace, and the
    group resumes at FULL size on a fresh mesh — the recovery a mere
    broken TCP connection deserves, no shrink, no manual rebuild."""
    store = tempfile.mkdtemp()
    # min_bytes gates the kill onto the one large allreduce the
    # workload issues exactly once (state["big_tried"] is set BEFORE
    # the attempt, so the post-recovery retry goes small and the
    # count=1 rule cannot re-fire in the new epoch's fresh domain).
    schedule = {"seed": 41, "faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data",
                  "min_bytes": 40000},
         "action": "kill", "count": 1}]}
    body = """
from gloo_tpu import elastic

def step_fn(ectx, step, state):
    flag = np.zeros(1, dtype=np.float32)
    if ectx.rank == 0 and state["done"] >= 6 and state["big_tried"]:
        flag[0] = 1.0
    ectx.allreduce(flag, tag=0)
    if flag[0] > 0:
        raise StopIteration
    if step == 3 and not state["big_tried"]:
        state["big_tried"] = True
        big = np.full(1 << 16, float(ectx.rank + 1), dtype=np.float32)
        ectx.allreduce(big, tag=1)       # the kill fires here, once
        n = ectx.size
        assert big[0] == n * (n + 1) / 2, big[0]
    else:
        x = np.full(4096, float(ectx.rank + 1), dtype=np.float32)
        ectx.allreduce(x, tag=1)
        n = ectx.size
        assert x[0] == n * (n + 1) / 2, (step, x[0], n)
    state["done"] += 1
    return state

res = elastic.run_elastic(
    step_fn, store=store, device=gloo_tpu.Device(), rank=rank,
    world_size=size, min_size=2,
    state={"done": 0, "big_tried": False}, timeout=90.0)
fired = [(e["domain"], e["action"], e["opcode"]) for e in
         fault.report(rank=rank)]
print("OK", json.dumps({
    "sizes": [e["size"] for e in res["epochs"]],
    "epoch": res["elastic"]["epoch"],
    "members": res["elastic"]["members"],
    "rebuilds": res["rebuilds"], "fired": fired}))
"""
    procs = [_spawn_worker(body, r, 3, store, schedule,
                           extra_env={"TPUCOLL_LEASE_MS": "200",
                                      "TPUCOLL_LEASE_GRACE": "1200"})
             for r in range(3)]
    outs = [p.communicate(timeout=240) for p in procs]
    _assert_ok(procs, outs)
    for r in range(3):
        line = [ln for ln in outs[r][0].splitlines()
                if ln.startswith("OK ")][0]
        res = json.loads(line[3:])
        # Same members straight through: 3 -> 3 across the evidence
        # bump; nobody was excluded for a single broken link.
        assert res["sizes"] == [3, 3], res
        assert res["epoch"] == 2 and res["members"] == [0, 1, 2], res
        assert res["rebuilds"] == 1, res
        if r == 1:
            kills = [f for f in res["fired"] if f[1] == "kill"]
            assert len(kills) == 1, res["fired"]
            # The kill landed inside the epoch-1 group domain (>= 1000),
            # proving the elastic context — not a root-domain mesh —
            # carried the traffic.
            assert kills[0][0] >= 1000, res["fired"]


def test_chaos_same_seed_determinism_with_group_domains():
    """Same-seed fault determinism holds per (rank, domain) with GROUP
    domains: a probabilistic delay rule fires inside the hier split
    sub-groups (domain = hash of the group tag, >= 1000), and two runs
    of the same workload produce identical per-(rank, domain)
    subsequences."""
    import gloo_tpu
    from gloo_tpu import fault

    schedule = {"seed": 5, "faults": [
        {"when": {"opcode": "data"},
         "action": "delay", "ms": 1, "prob": 0.4, "seed": 17}]}

    def workload():
        import threading

        store = gloo_tpu.HashStore()
        reports = [None] * 4
        errors = []

        def worker(rank):
            try:
                ctx = gloo_tpu.Context(rank, 4, timeout=30)
                ctx.set_host_id(f"dh{rank // 2}")
                ctx.connect_full_mesh(store, gloo_tpu.Device())
                x = np.full(4096, 1.0, dtype=np.float32)
                for i in range(6):
                    ctx.allreduce(x, algorithm="hier", tag=i)
                    x[:] = 1.0
                ctx.barrier(tag=99)
                ctx.close()
            except BaseException as e:  # noqa: BLE001
                errors.append((rank, e))

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors
        entries = fault.report()
        # Canonicalize: the global interleaving is scheduling-dependent,
        # each (rank, domain) stream is the deterministic unit.
        entries.sort(key=lambda e: (e["rank"], e["domain"], e["n"]))
        return entries

    fault.install(schedule)
    try:
        first = workload()
        fault.install(schedule)
        second = workload()
    finally:
        fault.clear()
    assert first == second
    domains = {e["domain"] for e in first}
    # Group domains engaged: hier phases run on split sub-contexts whose
    # fault domains derive from the group tag (>= 1000), alongside the
    # parent's root domain 0 traffic.
    assert any(d >= 1000 for d in domains), domains
    assert first, "no faults fired"


def test_chaos_lazy_connect_refuse_on_first_use_dial():
    """Lazy boot (docs/bootstrap.md) with nothing eager: the broker's
    first-use dial hits the same typed connect-fault classification as
    the seed's bring-up dials — refused twice, retried with backoff,
    counted, and the collective still completes."""
    store = tempfile.mkdtemp()
    schedule = {"seed": 7, "faults": [
        {"when": {"rank": 0}, "action": "connect_refuse", "count": 2}]}
    body = """
x = np.full(1000, float(rank + 1), dtype=np.float32)
ctx.allreduce(x, tag=1)
assert x[0] == size * (size + 1) / 2, x[0]
boot = ctx.metrics()["boot"]
assert boot["lazy"] is True, boot
if rank == 0:
    fired = fault.report(rank=0)
    assert sum(1 for e in fired
               if e["action"] == "connect_refuse") == 2, fired
    assert ctx.metrics()["retries"] >= 2
ctx.close()
print("OK")
"""
    procs, outs = _run(body, 3, store, schedule,
                       extra_env={"TPUCOLL_BOOT_MODE": "lazy",
                                  "TPUCOLL_BOOT_EAGER": "none"})
    _assert_ok(procs, outs)


def test_chaos_lazy_evict_redial_same_seed_determinism():
    """Acceptance: broker eviction churn does not perturb the fault
    plane's determinism — a peer pair that is LRU-evicted and later
    redialed (TPUCOLL_MAX_PAIRS=1) sees the same same-seed firing
    sequence across two identical runs, byte for byte."""
    import threading

    import gloo_tpu
    from gloo_tpu import fault

    schedule = {"seed": 11, "faults": [
        {"when": {"rank": 1, "opcode": "data"},
         "action": "delay", "ms": 1, "prob": 0.5, "seed": 99}]}
    size = 4

    def workload():
        store = gloo_tpu.HashStore()
        evictions = [0] * size
        errors = []

        def worker(rank):
            try:
                ctx = gloo_tpu.Context(rank, size, timeout=30)
                ctx.set_host_id(f"edh{rank // 2}")
                ctx.connect_full_mesh(store, gloo_tpu.Device())
                data = np.arange(64, dtype=np.float64)
                out = np.zeros(64, dtype=np.float64)
                for i in range(20):
                    # Rank 1 alternates between the two cross-host
                    # peers: cap=1 evicts the idle one before each
                    # dial, so every other send rides a redial.
                    peer = 2 + (i % 2)
                    if rank == 1:
                        ctx.send(data, dst=peer, slot=600 + i)
                    elif rank == peer:
                        ctx.recv(out, src=1, slot=600 + i)
                ctx.barrier(tag=999)
                evictions[rank] = ctx.metrics()["boot"]["pairs_evicted"]
                ctx.close()
            except BaseException as e:  # noqa: BLE001
                errors.append((rank, e))

        env = {"TPUCOLL_BOOT_MODE": "lazy", "TPUCOLL_MAX_PAIRS": "1",
               "TPUCOLL_BOOT_EAGER": "none"}
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            threads = [threading.Thread(target=worker, args=(r,))
                       for r in range(size)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(90)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        assert not errors, errors
        assert evictions[1] > 0, evictions  # churn actually happened
        return json.dumps(fault.report(rank=1), sort_keys=True)

    fault.install(schedule)
    try:
        first = workload()
        fault.install(schedule)  # reinstall: reset counters + report
        second = workload()
    finally:
        fault.clear()
    assert first == second
    fired = json.loads(first)
    assert 0 < len(fired) < 20, len(fired)
