"""Build hook: compile the native core (csrc/) into the wheel.

`pip install .` must produce a package whose `gloo_tpu/_native/libtpucoll.so`
exists in site-packages — the installed tree has no csrc/ to auto-build from
(the in-checkout auto-build in gloo_tpu/_lib.py only works for source
checkouts). Mirrors the reference's CMake-first build
(/root/reference/CMakeLists.txt) driven from setuptools.
"""

import os
import shutil
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution

ROOT = os.path.dirname(os.path.abspath(__file__))


class BuildPyWithNative(build_py):
    def run(self):
        super().run()
        # A sanitizer flavor requested via the environment (SANITIZE=
        # address|thread) flows straight through to `make native`, which
        # reads it as a make variable and produces a SUFFIXED library
        # (libtpucoll_asan.so / libtpucoll_tsan.so) next to the normal
        # one. Wheels always ship the production libtpucoll.so; the
        # sanitizer artifacts are a test rig, not a distribution.
        if os.environ.get("SANITIZE"):
            raise RuntimeError(
                "refusing to build a wheel with SANITIZE set: sanitizer "
                "flavors are for `make native SANITIZE=...` test rigs, "
                "not distribution (unset SANITIZE to build the wheel)")
        lib = os.path.join(ROOT, "gloo_tpu", "_native", "libtpucoll.so")
        # Always (re)build: dependency tracking makes this a no-op when
        # up to date, and gating on os.path.exists(lib) would silently
        # package a stale binary after csrc/ edits. One build recipe: the
        # Makefile's `native` target (same one _lib.py's in-checkout
        # auto-build uses), which prefers cmake+ninja and falls back to a
        # plain compiler-driver build on minimal images; direct cmake
        # only where make itself is absent.
        if shutil.which("make"):
            subprocess.run(["make", "native"], cwd=ROOT, check=True)
        else:
            build_dir = os.path.join(ROOT, "build")
            gen = ["-G", "Ninja"] if shutil.which("ninja") else []
            subprocess.run(
                ["cmake", "-S", os.path.join(ROOT, "csrc"),
                 "-B", build_dir, *gen,
                 "-DCMAKE_BUILD_TYPE=RelWithDebInfo"], check=True)
            subprocess.run(["cmake", "--build", build_dir], check=True)
        dest = os.path.join(self.build_lib, "gloo_tpu", "_native")
        os.makedirs(dest, exist_ok=True)
        shutil.copy2(lib, dest)


class BinaryDistribution(Distribution):
    """The wheel carries a compiled .so: force a platform tag so a
    linux/x86-64 wheel is never installed onto a foreign platform."""

    def has_ext_modules(self):
        return True


setup(cmdclass={"build_py": BuildPyWithNative},
      distclass=BinaryDistribution)
