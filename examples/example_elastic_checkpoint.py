"""Elastic training with encryption + checkpoint/resume — the round-2
transport and resilience features working together.

Three encrypted ranks train a linear model with DDP gradient allreduce;
rank 2 is SIGKILLed mid-run. The survivors detect the failure in
milliseconds (EOF without goodbye), rebuild a 2-rank group through the
store, reload the last committed checkpoint, and train to convergence.

This is the MANUAL recovery pattern (the application catches the error
and drives rebuild_after_failure itself). The elastic membership plane
(gloo_tpu.elastic.run_elastic, docs/elastic.md) automates the whole
loop — lease-detected failures, epoch agreement, auto-rebuild, and
rejoin back to full size — with the same StepCheckpointer supplying
the state.

    python examples/example_elastic_checkpoint.py
"""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, signal, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")  # orbax pulls in jax
    import numpy as np
    import gloo_tpu
    from gloo_tpu.checkpoint import StepCheckpointer
    from gloo_tpu.resilience import rebuild_after_failure

    rank, size = int(sys.argv[1]), 3
    store = gloo_tpu.FileStore(sys.argv[2])
    device_kwargs = dict(auth_key="elastic-demo", encrypt=True)
    ctx = gloo_tpu.Context(rank, size, timeout=10.0)
    ctx.connect_full_mesh(store, gloo_tpu.Device(**device_kwargs))
    ckpt = StepCheckpointer(sys.argv[3], keep=2)

    rng = np.random.RandomState(0)
    X = rng.randn(240, 6).astype(np.float32)
    y = X @ np.arange(6, dtype=np.float32)
    w = np.zeros(6, dtype=np.float32)
    step, gen = 0, 1

    while step < 80:
        lo = rank * (240 // size); hi = lo + 240 // size
        err = X[lo:hi] @ w - y[lo:hi]
        grad = 2.0 * X[lo:hi].T @ err / len(err)
        if rank == 2 and step == 20:
            os.kill(os.getpid(), signal.SIGKILL)  # simulated hard failure
        try:
            ctx.allreduce(grad, timeout=8.0)
        except gloo_tpu.IoError:
            print(f"rank {{rank}}: failure at step {{step}}; rebuilding",
                  flush=True)
            # settle > op timeout: the roll call must outwait the slowest
            # survivor's failure detection.
            ctx, rank, size = rebuild_after_failure(
                store, gloo_tpu.Device(**device_kwargs), old_rank=rank,
                old_size=size, generation=gen, settle=10.0, timeout=60.0)
            assert ctx is not None
            gen += 1
            step_got, state = ckpt.load_latest()
            step, w = int(state["step"]), np.asarray(state["w"])
            print(f"rank {{rank}}: resumed {{size}}-wide at step {{step}}",
                  flush=True)
            continue
        w -= 0.02 * grad / size
        step += 1
        if rank == 0 and step % 10 == 0:
            ckpt.save(step, {{"w": w, "step": np.int64(step)}})

    loss = float(np.mean((X @ w - y) ** 2))
    print(f"rank {{rank}}: done, loss {{loss:.5f}}", flush=True)
    assert loss < 1.0
""").format(repo=_REPO)


def main():
    store, ckdir = tempfile.mkdtemp(), tempfile.mkdtemp()
    procs = [subprocess.Popen(
        [sys.executable, "-c", WORKER, str(r), store, ckdir])
        for r in range(3)]
    codes = [p.wait() for p in procs]
    assert codes[2] == -signal.SIGKILL
    assert codes[0] == 0 and codes[1] == 0
    print("elastic checkpoint example: OK")


if __name__ == "__main__":
    main()
