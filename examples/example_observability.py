"""Observability tour: tracer spans, metrics registry, Prometheus text,
and the straggler watchdog — on a 2-rank host-plane group in one process.

What this shows (docs/observability.md walks through the output):
 1. per-collective counters + latency histograms from `Context.metrics()`;
 2. Prometheus text exposition ready for a /metrics endpoint;
 3. a merged per-rank Chrome trace with labeled rank rows (Perfetto);
 4. the watchdog naming the peer a rank was stuck on.

Run: python examples/example_observability.py
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import gloo_tpu
from gloo_tpu.utils import (histogram_quantile, merge_snapshots,
                            merge_traces, to_prometheus)


def worker(store, rank, size, results):
    device = gloo_tpu.Device()
    ctx = gloo_tpu.Context(rank, size, timeout=30)
    ctx.connect_full_mesh(store, device)

    # Arm the straggler watchdog: waits blocked > 80ms get reported.
    ctx.set_watchdog(0.08)
    ctx.trace_start()

    x = np.ones(256 * 1024, dtype=np.float32)
    for _ in range(5):
        ctx.allreduce(x)
    ctx.broadcast(x, root=0)
    ctx.barrier()

    # Manufacture a straggler: rank 1 dawdles before serving rank 0's
    # receive, so rank 0's watchdog fires and names rank 1.
    y = np.zeros(8, dtype=np.float32)
    if rank == 0:
        ctx.recv(y, 1, slot=42, timeout=10)
    else:
        time.sleep(0.25)
        ctx.send(y, 0, slot=42)

    ctx.trace_stop()
    results[rank] = (ctx.metrics(), ctx.trace_json())
    ctx.barrier()
    ctx.close()


def main():
    size = 2
    store = gloo_tpu.HashStore()
    results = [None] * size
    threads = [threading.Thread(target=worker,
                                args=(store, r, size, results))
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
        assert not t.is_alive(), "worker timed out"

    snaps = [m for m, _ in results]
    ar = snaps[0]["ops"]["allreduce"]
    p50 = histogram_quantile(ar["latency_us"], 0.5)
    print(f"[metrics] allreduce: {ar['calls']} calls, "
          f"{ar['bytes']} bytes, p50 ~{p50:.0f}us")
    peer_stats = snaps[0]["transport"][1]
    print(f"[metrics] rank0 <-> rank1: sent {peer_stats['sent_bytes']}B "
          f"recv {peer_stats['recv_bytes']}B, last progress "
          f"{peer_stats['last_progress_age_us']}us ago")

    stall = snaps[0]["watchdog"]["last"]
    assert stall is not None and stall["peer"] == 1
    print(f"[watchdog] rank0 was blocked {stall['waited_us'] // 1000}ms "
          f"on peer {stall['peer']} slot {stall['slot']} — the straggler "
          f"is named, not guessed")

    prom = to_prometheus(snaps[0], extra_labels={"job": "example"})
    print("[prometheus] first lines of the exposition:")
    for line in prom.splitlines()[:4]:
        print("   ", line)

    job = merge_snapshots(snaps)
    print(f"[merged] job-level allreduce calls: "
          f"{job['ops']['allreduce']['calls']}")

    merged_trace = merge_traces([t for _, t in results])
    path = "/tmp/gloo_tpu_observability_trace.json"
    with open(path, "w") as f:
        f.write(merged_trace)
    events = json.loads(merged_trace)
    rows = [e for e in events if e.get("ph") == "M"
            and e["name"] == "process_name"]
    print(f"[trace] {len(events)} events across {len(rows)} labeled rank "
          f"rows -> {path} (open in Perfetto)")

    print("observability example OK")


if __name__ == "__main__":
    main()
