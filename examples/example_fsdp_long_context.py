"""FSDP + long-context tour: ZeRO-3-style sharded training and the three
sequence-parallel attention recipes (ring, ring-flash, Ulysses).

Runs on any JAX backend; to simulate a multi-chip TPU slice on CPU:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python examples/example_fsdp_long_context.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# Honor a JAX_PLATFORMS request even where site customization pinned the
# platform before this script ran (the env var alone is read too early
# to override that pin; jax.config is not).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gloo_tpu.models.mlp import MLP
from gloo_tpu.parallel import (make_fsdp_train_step, ring_attention,
                               shard_params, ulysses_attention,
                               unshard_params)
from gloo_tpu.tpu import make_mesh


def fsdp_demo(mesh):
    n = mesh.shape["data"]
    model = MLP([16, 64, 1])
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(8 * n, 16), jnp.float32)
    ys = jnp.sin(xs.sum(-1, keepdims=True))

    step = make_fsdp_train_step(model.loss, params, "data", lr=0.05)

    def run(p, x, y):
        sharded = shard_params(p, "data")  # 1/n of the model per device
        def body(i, carry):
            sh, _ = carry
            return step(sh, (x, y))
        sharded, loss = jax.lax.fori_loop(0, 20, body,
                                          (sharded, jnp.float32(0)))
        return unshard_params(sharded, p, "data"), loss

    params2, loss = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))(params, xs, ys)
    print(f"fsdp      : 20 SGD steps, final global loss {float(loss):.4f} "
          f"(params sharded 1/{n} per device, grads reduce-scattered by "
          "the all_gather transpose)")


def sequence_parallel_demo(mesh):
    n = mesh.shape["data"]
    b, h, t, d = 1, n, 16 * n, 32
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    specs = (P(None, None, "data"),) * 3
    ring = jax.jit(jax.shard_map(
        lambda a, k, v: ring_attention(a, k, v, "data"), mesh=mesh,
        in_specs=specs, out_specs=P(None, None, "data")))
    uly = jax.jit(jax.shard_map(
        lambda a, k, v: ulysses_attention(a, k, v, "data"), mesh=mesh,
        in_specs=specs, out_specs=P(None, None, "data"), check_vma=False))

    r, u = ring(q, q, q), uly(q, q, q)
    print(f"ring vs ulysses attention: max delta "
          f"{float(jnp.abs(r - u).max()):.2e} (same math, ppermute ring "
          "vs one all-to-all per direction)")


def main():
    mesh = make_mesh({"data": -1})
    print(f"mesh: {mesh.shape}")
    fsdp_demo(mesh)
    sequence_parallel_demo(mesh)
    print("fsdp + long-context example OK")


if __name__ == "__main__":
    main()
