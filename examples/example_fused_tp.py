"""Fused tensor parallelism: the Megatron-SP MLP with both collectives
fused into their matmuls (gloo_tpu.ops.overlap collective-matmul kernels).

The sequence dim stays sharded outside the block; inside, the gather-side
projection runs allgather_matmul (each ICI hop flies while the MXU
computes the next chunk) and the scatter-side projection runs
matmul_reduce_scatter — no standalone collective anywhere, forward or
backward (the two kernels are each other's VJP).

Runs on any JAX backend; to simulate a multi-chip TPU slice on CPU:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python examples/example_fused_tp.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# Honor a JAX_PLATFORMS request even where site customization pinned the
# platform before this script ran (the env var alone is read too early
# to override that pin; jax.config is not).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gloo_tpu.parallel.tp import (allgather_matmul_dense,
                                  row_parallel_dense_scattered)
from gloo_tpu.tpu import make_mesh

# The Pallas interpreter backs the kernels off-TPU; on a real slice drop
# interpret=True.
INTERPRET = jax.default_backend() != "tpu"


def main():
    mesh = make_mesh({"model": -1})
    n = mesh.shape["model"]
    seq, d_model, d_ff = 16 * n, 64, 32 * n
    rng = np.random.RandomState(0)
    x = rng.normal(size=(seq, d_model)).astype(np.float32) * 0.1
    w_up = rng.normal(size=(d_model, d_ff)).astype(np.float32) * 0.1
    w_down = rng.normal(size=(d_ff, d_model)).astype(np.float32) * 0.1

    def block(xs, wu, wd):
        h = allgather_matmul_dense(xs, wu, "model", interpret=INTERPRET)
        h = jax.nn.gelu(h)
        return row_parallel_dense_scattered(h, wd, "model",
                                            interpret=INTERPRET)

    fused = jax.jit(jax.shard_map(
        block, mesh=mesh,
        in_specs=(P("model", None), P(None, "model"), P("model", None)),
        out_specs=P("model", None), check_vma=False))

    y = np.asarray(fused(x, w_up, w_down))
    ref = np.asarray(jax.nn.gelu(jnp.asarray(x @ w_up))) @ w_down
    err = float(np.abs(y - ref).max())
    print(f"mesh: {mesh.shape}  fused MLP out {y.shape}  max|err| {err:.2e}")
    assert err < 2e-3

    # Gradients flow through the dual kernels (no unfused collective in
    # the backward either).
    def loss(xs, wu, wd):
        out = jax.shard_map(
            block, mesh=mesh,
            in_specs=(P("model", None), P(None, "model"), P("model", None)),
            out_specs=P("model", None), check_vma=False)(xs, wu, wd)
        return jnp.mean(out ** 2)

    g = jax.grad(loss, argnums=1)(x, w_up, w_down)
    print(f"dL/dw_up via fused VJPs: {np.asarray(g).shape}, "
          f"|g| {float(jnp.abs(g).mean()):.2e}")

    # Production entry point (r5): the *_auto variants decide per shape
    # whether fusing pays — the fused kernels give up some MXU
    # throughput to hide the collective, and on shapes where the
    # collective is cheap relative to that penalty (K-heavy shards,
    # small chunks — the measured 0.68x trap, BASELINE.md) they fall
    # back to plain dots + explicit collectives. Force either arm with
    # TPUCOLL_TP_OVERLAP=fused|unfused; feed
    # parallel.measure_fused_ratio() into use_fused_overlap for a
    # probe-measured decision on real hardware.
    from gloo_tpu.parallel import (allgather_matmul_dense_auto,
                                   row_parallel_dense_scattered_auto,
                                   use_fused_overlap)

    def block_auto(xs, wu, wd):
        h = allgather_matmul_dense_auto(xs, wu, "model",
                                        interpret=INTERPRET)
        h = jax.nn.gelu(h)
        return row_parallel_dense_scattered_auto(h, wd, "model",
                                                 interpret=INTERPRET)

    y2 = np.asarray(jax.jit(jax.shard_map(
        block_auto, mesh=mesh,
        in_specs=(P("model", None), P(None, "model"), P("model", None)),
        out_specs=P("model", None), check_vma=False))(x, w_up, w_down))
    assert float(np.abs(y2 - ref).max()) < 2e-3
    picked = use_fused_overlap(seq, d_ff // n, d_model, n)
    print(f"auto dispatcher on this shape/mesh picks: "
          f"{'fused' if picked else 'unfused'}")
    print("fused tensor-parallel example OK")


if __name__ == "__main__":
    main()
