"""Two-level (DCN x ICI) data-parallel training across host processes.

Each process simulates one HOST of a pod: a private 4-device mesh (ICI
analog — on real hardware, the host's TPU chips) plus a host-plane rank
over DCN-analog TCP. Gradients average over the local mesh inside the
jitted step, then across hosts through the C++ transport — co-located
processes exchange through the shm payload rings automatically.

Run (2 "hosts" on one machine):
    for R in 0 1; do
        RANK=$R SIZE=2 STORE=file:/tmp/hier_demo \
            python examples/example_hierarchical.py &
    done; wait
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import gloo_tpu  # noqa: E402
from gloo_tpu.tpu import HierarchicalGroup, make_hierarchical_ddp  # noqa: E402


def main():
    rank = int(os.environ["RANK"])
    size = int(os.environ["SIZE"])
    spec = os.environ.get("STORE", "file:/tmp/hier_demo")
    server = None
    if spec.startswith("file:"):
        store = gloo_tpu.FileStore(spec[5:])
    elif spec.startswith("tcp:"):
        host, port = spec[4:].rsplit(":", 1)
        if os.environ.get("SERVE"):
            server = gloo_tpu.TcpStoreServer("0.0.0.0", int(port))
        store = gloo_tpu.TcpStore(host, int(port))
    else:
        raise SystemExit(f"STORE must be file:PATH or tcp:HOST:PORT, "
                         f"got {spec!r}")

    ctx = gloo_tpu.Context(rank, size, timeout=60)
    ctx._store_server = server  # pin server lifetime to the context
    ctx.connect_full_mesh(store, gloo_tpu.Device())
    group = HierarchicalGroup(ctx)
    print(f"[host {rank}] local devices: {len(group.devices)}, "
          f"hosts: {size}, shm pairs: "
          f"{ctx.shm_stats()['active_pairs']}")

    def loss_fn(params, batch):
        x, y = batch
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        pred = h @ params["w2"] + params["b2"]
        return jnp.mean((pred - y) ** 2)

    opt = optax.adam(1e-2)
    key = jax.random.PRNGKey(0)  # same init everywhere: replicas agree
    k1, k2 = jax.random.split(key)
    params = {
        "w1": jax.random.normal(k1, (8, 8192)) * 0.3,
        "b1": jnp.zeros(8192),
        "w2": jax.random.normal(k2, (8192, 1)) * 0.03,
        "b2": jnp.zeros(1),
    }
    opt_state = opt.init(params)
    step = make_hierarchical_ddp(loss_fn, opt, group)

    rng = np.random.RandomState(100 + rank)  # per-host data shard
    w_true = np.linspace(-1, 1, 8).reshape(8, 1).astype(np.float32)
    for it in range(60):
        x = rng.rand(16, 8).astype(np.float32)
        y = (x @ w_true + 0.2).astype(np.float32)
        params, opt_state, loss = step(params, opt_state, (x, y))
        if it % 20 == 0 or it == 59:
            print(f"[host {rank}] step {it:3d} loss {float(loss):.5f}")

    group.barrier()
    shm = ctx.shm_stats()
    print(f"[host {rank}] done; grad bytes over DCN hop rode shm: "
          f"{shm['tx_bytes']} tx / {shm['rx_bytes']} rx")
    ctx.close()


if __name__ == "__main__":
    main()
