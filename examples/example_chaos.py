"""Chaos demo: inject a peer stall, watch the watchdog name the peer,
then kill the link and rebuild the context.

Two processes over a FileStore. A fault schedule (docs/faults.md) is
shared via TPUCOLL_FAULT_FILE:

 1. rank 1's first bulk message to rank 0 stalls 1.5s — rank 0's armed
    watchdog fires mid-wait and names rank 1 + the blocked slot, and the
    allreduce then completes correctly (a stall is a delay, not a death);
 2. rank 1's second bulk message hard-kills the pair — both ranks fail
    loudly, rebuild through gloo_tpu.resilience over the same store, and
    the evidence published by rebuild_after_failure(failed_context=...)
    lets stall_reports name the faulted rank.

Everything is deterministic: same schedule, same seed, same firing
sequence (gloo_tpu.fault.report()).
"""

import json
import os
import subprocess
import sys
import tempfile
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEDULE = {"seed": 2026, "faults": [
    {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 1,
              "min_bytes": 1024},
     "action": "stall", "ms": 1500},
    {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 2,
              "min_bytes": 1024},
     "action": "kill"},
]}

WORKER = textwrap.dedent("""
    import json, sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import gloo_tpu
    from gloo_tpu import fault
    from gloo_tpu.resilience import rebuild_after_failure, stall_reports

    import os
    from gloo_tpu.utils import merge_traces

    rank, store_dir = int(sys.argv[1]), sys.argv[2]
    store = gloo_tpu.FileStore(store_dir)
    ctx = gloo_tpu.Context(rank, 2, timeout=15.0)
    ctx.connect_full_mesh(store, gloo_tpu.Device())
    ctx.set_watchdog(0.3)   # anything blocked > 300ms names its peer
    ctx.trace_start()       # fired faults land in the trace as spans

    # --- act 1: the stall. The collective survives; the watchdog saw it.
    x = np.full(4096, float(rank + 1), dtype=np.float32)
    ctx.allreduce(x, tag=1)
    assert x[0] == 3.0, x[0]
    if rank == 0:
        wd = ctx.metrics()["watchdog"]
        assert wd["stalls"] >= 1 and wd["last"]["peer"] == 1, wd
        print(f"[watchdog] rank0 was blocked "
              f"{{wd['last']['waited_us'] // 1000}}ms on rank "
              f"{{wd['last']['peer']}} slot {{wd['last']['slot']}}",
              flush=True)

    # --- act 2: the kill. Fail loudly, rebuild, keep going.
    y = np.full(4096, float(rank + 1), dtype=np.float32)
    try:
        ctx.allreduce(y, tag=2, timeout=3.0)
        raise SystemExit("allreduce unexpectedly survived the kill")
    except gloo_tpu.IoError as exc:
        print(f"rank {{rank}}: failed loudly: {{str(exc)[:72]}}",
              flush=True)

    new_ctx, new_rank, new_size = rebuild_after_failure(
        store, gloo_tpu.Device(), old_rank=rank, old_size=2, generation=1,
        settle=2.0, timeout=60.0, failed_context=ctx)
    assert new_ctx is not None and new_size == 2
    z = np.full(1024, float(new_rank + 1), dtype=np.float32)
    new_ctx.allreduce(z, tag=3)
    assert z[0] == 3.0, z[0]
    if rank == 0:
        # At P=2 blame is symmetric (each survivor names the other end
        # of the dead link); what matters is that the HEALTHY side's
        # watchdog evidence names the faulted rank 1. At P>=3 the modal
        # suspect across reports isolates the culprit
        # (tests/test_chaos.py::test_sigkill_mid_allreduce_rebuild_and_blame).
        reports = stall_reports(store, generation=1, old_size=2)
        assert reports[0]["suspect"] == 1, reports
        print(f"rebuilt OK; per-survivor evidence: "
              f"{{ {{r: v.get('suspect') for r, v in reports.items()}} }}",
              flush=True)
    if rank == 1:
        print("fault firing sequence:",
              json.dumps(fault.report(rank=1)), flush=True)
    # Merge both ranks' traces (stall/kill spans included) into one
    # Perfetto timeline: each worker parks its doc in the store dir,
    # rank 0 merges after the new context's barrier orders the writes.
    with open(os.path.join(store_dir, f"trace_{{rank}}.json"), "w") as f:
        f.write(ctx.trace_json())
    new_ctx.barrier(tag=4)
    if rank == 0:
        docs = [open(os.path.join(store_dir, f"trace_{{r}}.json")).read()
                for r in range(2)]
        merged_path = os.path.join(store_dir, "chaos_trace.json")
        with open(merged_path, "w") as f:
            f.write(merge_traces(docs))
        print(f"merged chaos trace (Perfetto: labeled rank rows, "
              f"fault.* spans) -> {{merged_path}}", flush=True)
    new_ctx.close()
""").format(repo=_REPO)


def main():
    store = tempfile.mkdtemp()
    sched_path = os.path.join(store, "schedule.json")
    with open(sched_path, "w") as f:
        json.dump(SCHEDULE, f)
    env = dict(os.environ, TPUCOLL_FAULT_FILE=sched_path)
    procs = [subprocess.Popen([sys.executable, "-c", WORKER, str(r), store],
                              env=env)
             for r in range(2)]
    codes = [p.wait() for p in procs]
    assert codes == [0, 0], codes
    print("chaos example: OK")


if __name__ == "__main__":
    main()
