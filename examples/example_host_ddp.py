"""Multi-process data-parallel training over the host plane.

The reference's example1 pattern (env-var bootstrap + store rendezvous),
driving SURVEY §7 M2: a jax MLP trained data-parallel with gradient
averaging through the framework's own C++ allreduce.

Run (4 processes on one host):
    for R in 0 1 2 3; do
        RANK=$R SIZE=4 STORE=tcp:127.0.0.1:29500 SERVE=$([ $R = 0 ] && echo 1) \
            python examples/example_host_ddp.py &
    done; wait
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np
import optax

import gloo_tpu
from gloo_tpu.models import MLP
from gloo_tpu.parallel import HostGradSync


def make_store():
    spec = os.environ.get("STORE", "tcp:127.0.0.1:29500")
    if spec.startswith("file:"):
        return gloo_tpu.FileStore(spec[5:]), None
    host, port = spec[4:].rsplit(":", 1)
    server = None
    if os.environ.get("SERVE"):
        server = gloo_tpu.TcpStoreServer("0.0.0.0", int(port))
    return gloo_tpu.TcpStore(host, int(port)), server


def main():
    rank = int(os.environ["RANK"])
    size = int(os.environ["SIZE"])
    store, server = make_store()
    ctx = gloo_tpu.Context(rank, size, timeout=30.0)
    ctx.connect_full_mesh(store, gloo_tpu.Device())
    sync = HostGradSync(ctx)

    model = MLP([16, 64, 1])
    params = model.init(jax.random.PRNGKey(0))  # same seed: same init
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)
    grad_fn = jax.jit(jax.value_and_grad(model.loss))

    rng = np.random.RandomState(1000 + rank)  # each rank its own shard
    for step in range(50):
        x = rng.randn(32, 16).astype(np.float32)
        y = x.sum(axis=1, keepdims=True) * 0.1
        loss, grads = grad_fn(params, (x, y))
        grads = sync.average(grads)  # <-- the framework's C++ allreduce
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if rank == 0 and step % 10 == 0:
            print(f"step {step:3d} loss {float(loss):.4f}")

    ctx.barrier()
    ctx.close()
    if rank == 0:
        print("done")


if __name__ == "__main__":
    main()
