"""Failure recovery: survivors rebuild the group after a rank dies.

Run 3 processes; rank 2 kills itself mid-training. The survivors detect
the failure (IoError within milliseconds), re-rendezvous through
gloo_tpu.resilience, and continue in a smaller world.

    for R in 0 1 2; do RANK=$R SIZE=3 STORE=$(mktemp -d) ... ; done
    (see the __main__ block: it spawns all ranks itself for convenience)
"""

import os
import signal
import subprocess
import sys
import tempfile
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, signal, sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import gloo_tpu
    from gloo_tpu.resilience import rebuild_after_failure

    rank, size, store_dir = int(sys.argv[1]), 3, sys.argv[2]
    store = gloo_tpu.FileStore(store_dir)
    ctx = gloo_tpu.Context(rank, size, timeout=10.0)
    ctx.connect_full_mesh(store, gloo_tpu.Device())

    grads = np.full(1 << 16, float(rank + 1), dtype=np.float32)
    for step in range(100):
        if rank == 2 and step == 10:
            os.kill(os.getpid(), signal.SIGKILL)  # simulated hard failure
        try:
            ctx.allreduce(grads, timeout=2.0)
        except gloo_tpu.IoError as exc:
            print(f"rank {{rank}}: step {{step}} failed ({{str(exc)[:40]}}); "
                  "rebuilding", flush=True)
            ctx, rank2, size2 = rebuild_after_failure(
                store, gloo_tpu.Device(), old_rank=rank, old_size=size,
                generation=1, settle=3.0, timeout=30.0)
            assert ctx is not None
            print(f"rank {{rank}} -> {{rank2}}/{{size2}}; resuming",
                  flush=True)
            rank, size = rank2, size2
        grads[:] = float(rank + 1)
    print(f"rank {{rank}}: finished 100 steps in world of {{size}}",
          flush=True)
""").format(repo=_REPO)


def main():
    store = tempfile.mkdtemp()
    procs = [subprocess.Popen([sys.executable, "-c", WORKER, str(r), store])
             for r in range(3)]
    codes = [p.wait() for p in procs]
    assert codes[2] == -signal.SIGKILL
    assert codes[0] == 0 and codes[1] == 0
    print("recovery example: OK")


if __name__ == "__main__":
    main()
