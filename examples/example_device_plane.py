"""Device-plane tour: mesh collectives, DDP training step, ring attention.

Runs on any JAX backend; to simulate a multi-chip TPU slice on CPU:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python examples/example_device_plane.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# Honor a JAX_PLATFORMS request even where site customization pinned the
# platform before this script ran (the env var alone is read too early
# to override that pin; jax.config is not).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import numpy as np
import optax

from gloo_tpu.models import Transformer, TransformerConfig
from gloo_tpu.parallel import make_ddp_train_step
from gloo_tpu.tpu import TpuProcessGroup, make_mesh


def main():
    mesh = make_mesh({"data": -1})
    pg = TpuProcessGroup(mesh)
    print(f"mesh: {mesh.shape}, group size {pg.size}")

    # Array-level collectives (host-API mirror)
    x = pg.shard(np.arange(pg.size * 4, dtype=np.float32).reshape(pg.size, 4))
    print("allreduce :", pg.unshard(pg.allreduce(x))[0])
    print("broadcast :", pg.unshard(pg.broadcast(x, root=0))[0])
    pg.barrier()

    # DDP training step: batch sharded over the mesh, grads psum'd on ICI
    cfg = TransformerConfig(vocab_size=128, d_model=64, n_heads=2,
                            n_layers=2, d_ff=128, max_seq_len=32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optax.adam(3e-3)
    opt_state = optimizer.init(params)
    step = make_ddp_train_step(model.loss, optimizer, mesh)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab_size,
                         (4 * pg.size, cfg.max_seq_len)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    for i in range(20):
        params, opt_state, loss = step(params, opt_state, (tokens, targets))
        if i % 5 == 0:
            print(f"ddp step {i:2d} loss {float(loss):.4f}")

    print("done")


if __name__ == "__main__":
    main()
