"""Post-mortem demo: chaos -> flight recorder -> dump -> merge -> blame.

Three processes over a FileStore, with the always-on flight recorder
(docs/flightrec.md) pointed at a dump directory:

 1. a fault schedule stalls rank 1's first bulk message mid-allreduce —
    rank 0's armed watchdog fires while blocked and auto-dumps its ring
    (reason "stall", blaming peer 1) with the allreduce still in flight;
 2. after the run the other ranks dump explicitly, `flightrec.merge`
    folds the per-rank dumps into one timeline, and `flightrec.analyze`
    blames rank 1 naming the in-flight op;
 3. the same machinery detects the UNRECOVERABLE failure class: ranks
    deliberately issue different collectives at one sequence number, and
    the fingerprint comparison raises the typed DesyncError saying who
    ran what ("rank 2 is at seq N (broadcast ...) while rank 0 ...");
 4. the merged timeline converts to Perfetto JSON for the browser view.

Run me:  python examples/example_flightrec.py
(or `make postmortem-demo`)
"""

import json
import os
import subprocess
import sys
import tempfile
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SCHEDULE = {"seed": 404, "faults": [
    {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 1,
              "min_bytes": 1024},
     "action": "stall", "ms": 1200},
]}

WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import gloo_tpu
    from gloo_tpu.utils import flightrec

    rank, store_dir, fr_dir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    store = gloo_tpu.FileStore(store_dir)
    ctx = gloo_tpu.Context(rank, 3, timeout=15.0)
    ctx.connect_full_mesh(store, gloo_tpu.Device())
    if rank == 0:
        ctx.set_watchdog(0.2)  # the blocked wait will auto-dump

    # --- act 1: a stalled allreduce. The recorder was already on — it
    # always is — so rank 0's watchdog dump catches the op IN FLIGHT.
    x = np.full(4096, float(rank + 1), dtype=np.float32)
    ctx.allreduce(x, tag=1)
    assert x[0] == 6.0, x[0]

    # --- act 2: a deliberate schedule desync at the next seq. Rank 2
    # issues a broadcast where everyone else issues an allreduce; the
    # collectives time out (this divergence is unrecoverable by design).
    y = np.full(1024, float(rank + 1), dtype=np.float32)
    try:
        if rank == 2:
            ctx.broadcast(y, root=2, tag=2, timeout=2.0)
        else:
            ctx.allreduce(y, tag=2, timeout=2.0)
        # rank 2's broadcast may complete locally (its sends land in
        # peers' stashes) — only the allreduce ranks are guaranteed to
        # time out.
        assert rank == 2, "desynced allreduce unexpectedly completed"
    except gloo_tpu.Error as exc:
        print(f"rank {{rank}}: desync victim: {{str(exc)[:64]}}",
              flush=True)

    # Ranks 1/2 dump explicitly; rank 0 keeps its mid-stall auto dump.
    if rank != 0:
        flightrec.dump(ctx, fr_dir)
    print(f"rank {{rank}}: recorded {{ctx.flightrec_seq()}} ops",
          flush=True)
""").format(repo=_REPO)


def main():
    from gloo_tpu.utils import flightrec
    from gloo_tpu.utils.flightrec import DesyncError

    store = tempfile.mkdtemp()
    fr_dir = os.path.join(store, "flightrec-demo")
    sched_path = os.path.join(store, "schedule.json")
    with open(sched_path, "w") as f:
        json.dump(SCHEDULE, f)
    env = dict(os.environ, TPUCOLL_FAULT_FILE=sched_path,
               TPUCOLL_FLIGHTREC_DIR=fr_dir)
    procs = [subprocess.Popen(
        [sys.executable, "-c", WORKER, str(r), store, fr_dir], env=env)
        for r in range(3)]
    codes = [p.wait() for p in procs]
    assert codes == [0, 0, 0], codes

    # --- the post-mortem, exactly as an operator would run it.
    merged = flightrec.merge(fr_dir)
    assert sorted(merged["ranks"]) == [0, 1, 2], merged["missing"]
    r0 = merged["ranks"][0]
    print(f"\nrank 0 dump: reason={r0['reason']} "
          f"blamed_peer={r0['blamed_peer']} (written mid-stall: its "
          f"allreduce is '{r0['events'][0]['state']}')")
    assert r0["reason"] == "stall" and r0["blamed_peer"] == 1

    try:
        flightrec.raise_on_desync(merged)
        raise SystemExit("desync went undetected")
    except DesyncError as exc:
        print(f"desync verdict: {exc}")
        assert "broadcast" in str(exc) and "allreduce" in str(exc)

    perfetto_path = os.path.join(fr_dir, "postmortem_trace.json")
    with open(perfetto_path, "w") as f:
        f.write(flightrec.to_perfetto(merged))
    print(f"merged Perfetto timeline -> {perfetto_path}")
    print("flightrec example: OK")


if __name__ == "__main__":
    main()
