"""Collective autotuning plane: measured tuning tables for kAuto dispatch.

Every ``algorithm="auto"`` dispatch in the native core historically ran
off compile-time thresholds measured once, on one loopback host. This
module replaces those guesses with deployment measurements: ``tune()``
sweeps the registered algorithm variants (ring / halving-doubling and its
fold/blocks sub-variants / recursive-doubling / bcube / bf16-wire for
allreduce; binomial vs ring for reduce; ring / halving-doubling / direct
for reduce_scatter) over log2 payload buckets on the live fabric, using
the metrics registry's latency histograms as the measurement source, and
installs the elected table on every rank. ``kAuto`` dispatch then
consults the table (interpolating crossovers between buckets) and falls
back to the historical constants when no table is installed, so untuned
contexts behave exactly as before.

Determinism contract
--------------------
Algorithm election must agree on every rank or a collective deadlocks.
``tune()`` guarantees this: rank 0's measurements are elected, serialized
once, published through the rendezvous store (or the context's own
broadcast for forked contexts), and every rank — rank 0 included —
installs the table parsed from those same bytes. ``install_table()`` is
the manual path and the caller owns that contract: install the SAME
table on every rank, never per-rank measurements.

Workflow
--------
>>> table = tuning.tune(ctx)                  # all ranks, collectively
>>> if ctx.rank == 0:
...     tuning.save_table(table, "prod.json") # commit per deployment
then in later jobs either ``TPUCOLL_TUNING_FILE=prod.json`` (loaded and
installed at context connect, no code changes) or::
>>> tuning.install_table(ctx, tuning.load_table("prod.json"))

``bench.py --autotune`` drives the sweep standalone and reports the
measured deltas against the default thresholds; see docs/tuning.md for
the table format and election protocol.
"""

from __future__ import annotations

import ctypes
import json
from typing import Optional, Union

from gloo_tpu import _lib
from gloo_tpu._lib import check
from gloo_tpu.core import Context

__all__ = [
    "tune",
    "install_table",
    "installed_table",
    "clear_table",
    "save_table",
    "load_table",
]

TableLike = Union[dict, str]


def _read_buf(out, out_len) -> str:
    try:
        return bytes(bytearray(out[: out_len.value])).decode()
    finally:
        _lib.lib.tc_buf_free(out)


def _to_json_str(table: TableLike) -> str:
    if isinstance(table, str):
        return table
    return json.dumps(table)


def tune(context: Context, min_bytes: int = 1 << 10,
         max_bytes: int = 4 << 20, iters: int = 8, warmup: int = 2,
         tag: int = 0, timeout: Optional[float] = None) -> dict:
    """Sweep, elect, and install a tuning table on `context`.

    COLLECTIVE: every rank of the group must call concurrently with
    identical arguments (the sweep runs real collectives, and the
    elected table is published to the whole group). One cell is measured
    per (collective, algorithm, log2 size bucket) from `min_bytes`
    through `max_bytes`; each cell runs `warmup` untimed plus `iters`
    timed iterations. `tag` namespaces the sweep's collectives — it must
    not collide with application collectives running concurrently.

    Returns the installed table as a dict (identical on every rank);
    pass it to save_table() to persist. Expect the sweep to take roughly
    iters * arms * buckets * (per-op latency); shrink the size range or
    iters for smoke runs.
    """
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    check(_lib.lib.tc_tune(
        context._handle, min_bytes, max_bytes, iters, warmup, tag,
        context._resolve_timeout_ms(timeout),
        ctypes.byref(out), ctypes.byref(out_len)))
    return json.loads(_read_buf(out, out_len))


def install_table(context: Context, table: TableLike) -> None:
    """Install a table (dict or JSON string) on THIS rank's context.

    The caller owns the rank-consistency contract: every rank must
    install the same table, or groups will elect different algorithms
    and deadlock mid-collective. Malformed tables raise Error (never
    silently install as empty).
    """
    check(_lib.lib.tc_tuning_install(
        context._handle, _to_json_str(table).encode()))


def installed_table(context: Context) -> Optional[dict]:
    """The context's installed table as a dict, or None when untuned."""
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    check(_lib.lib.tc_tuning_json(context._handle, ctypes.byref(out),
                                  ctypes.byref(out_len)))
    raw = _read_buf(out, out_len)
    return json.loads(raw) if raw else None


def clear_table(context: Context) -> None:
    """Remove the installed table; kAuto falls back to the built-in
    thresholds (TPUCOLL_ALLREDUCE_HD_MAX and friends)."""
    check(_lib.lib.tc_tuning_install(context._handle, None))


def save_table(table: TableLike, path: str) -> None:
    """Write a table to a JSON file (the TPUCOLL_TUNING_FILE format)."""
    with open(path, "w") as f:
        f.write(_to_json_str(table))
        f.write("\n")


def load_table(path: str) -> dict:
    """Read a table written by save_table() / tc_tune."""
    with open(path) as f:
        return json.load(f)


def set_transport_hints(table: TableLike, channels: Optional[int] = None,
                        stripe_bytes: Optional[int] = None) -> dict:
    """Attach tuned TRANSPORT knobs to a table: the per-pair data-channel
    count and the stripe threshold (docs/transport.md). A context that
    installs the table (or loads it via TPUCOLL_TUNING_FILE) applies
    them at connect time unless the TPUCOLL_CHANNELS /
    TPUCOLL_STRIPE_BYTES env overrides them. Pick the values from a
    ``bench.py --channel-sweep`` run on the target host. Returns the
    table as a dict. The same every-rank-same-table contract applies:
    channel counts must agree across ranks or connect fails loudly."""
    t = json.loads(_to_json_str(table))
    hints = dict(t.get("transport", {}))
    if channels is not None:
        # Ceiling mirrors transport::kMaxStripeChannels (csrc wire.h).
        if not 1 <= int(channels) <= 8:
            raise ValueError(f"channels must be in [1, 8], got {channels}")
        hints["channels"] = int(channels)
    if stripe_bytes is not None:
        if int(stripe_bytes) < 0:
            raise ValueError(f"stripe_bytes must be >= 0, got {stripe_bytes}")
        hints["stripe_bytes"] = int(stripe_bytes)
    if hints:
        t["transport"] = hints
    return t
