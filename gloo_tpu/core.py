"""Host data plane: stores, devices, contexts, collectives over numpy arrays.

This is the user-facing Python surface of the native core — the gloo_tpu
equivalent of the reference's C++ public API (context + rendezvous +
collectives), with numpy arrays standing in for raw pointers. The TPU device
plane (jax arrays over an ICI mesh) lives in gloo_tpu.tpu.
"""

from __future__ import annotations

import ctypes
import json
import os
import weakref
from typing import Optional, Sequence

import numpy as np

from gloo_tpu import _lib
from gloo_tpu._lib import Aborted, Error, IoError, TimeoutError, check, check_handle

__all__ = [
    "Aborted",
    "AsyncEngine",
    "CollectivePlan",
    "Context",
    "set_connect_debug_logger",
    "Device",
    "Error",
    "FileStore",
    "HashStore",
    "IoError",
    "PrefixStore",
    "ReduceOp",
    "Store",
    "TcpStore",
    "TcpStoreServer",
    "TimeoutError",
    "UnboundBuffer",
    "Work",
    "codec_pipeline",
    "codec_threads",
    "q4_block",
    "q4_decode",
    "q4_encode",
    "q4_wire_bytes",
    "q8_block",
    "q8_decode",
    "q8_encode",
    "q8_wire_bytes",
]

_DTYPE_CODES = {
    "int8": 0,
    "uint8": 1,
    "int32": 2,
    "uint32": 3,
    "int64": 4,
    "uint64": 5,
    "float16": 6,
    "bfloat16": 7,
    "float32": 8,
    "float64": 9,
}


class ReduceOp:
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3

    _BY_NAME = {"sum": SUM, "product": PRODUCT, "prod": PRODUCT, "min": MIN,
                "max": MAX}

    @classmethod
    def parse(cls, op) -> int:
        if isinstance(op, str):
            return cls._BY_NAME[op.lower()]
        return int(op)


_REDUCE_CFUNC = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_size_t)


def _wrap_reduce_fn(fn, dtype):
    """Wrap a Python accumulate callable as the C ReduceFn ABI.

    `fn(acc, inp)` receives two length-n numpy views of the collective's
    dtype and must write the combined result into `acc` in place. The
    operation must be commutative and associative — ring/halving-doubling/
    bcube schedules apply it in rank-dependent orders (reference:
    gloo/algorithm.h:59-95 ReductionFunction CUSTOM; gloo/allreduce.h:36
    arbitrary Func).

    Exceptions raised inside `fn` cannot propagate across the C boundary
    mid-collective (the affected segment is left unreduced, and peers may
    receive it), so the first one is captured and re-raised to THIS caller
    after the collective returns — treat it as poisoning the result on
    all ranks. Call raise_pending() after the C call.
    """
    dt = np.dtype(dtype)
    pending = []

    def thunk(acc_ptr, in_ptr, n):
        try:
            nbytes = int(n) * dt.itemsize
            acc = np.frombuffer(
                (ctypes.c_char * nbytes).from_address(acc_ptr), dtype=dt)
            inp = np.frombuffer(
                (ctypes.c_char * nbytes).from_address(in_ptr), dtype=dt)
            fn(acc, inp)
        except BaseException as e:  # noqa: BLE001 — must not cross C frame
            if not pending:
                pending.append(e)

    def raise_pending():
        if pending:
            raise Error(
                "custom reduction callable raised; the collective result "
                "is invalid on all ranks") from pending[0]

    cb = _REDUCE_CFUNC(thunk)
    return cb, ctypes.cast(cb, ctypes.c_void_p), raise_pending


def _dtype_code(arr: np.ndarray) -> int:
    name = arr.dtype.name
    if name not in _DTYPE_CODES:
        raise Error(f"unsupported dtype: {name}")
    return _DTYPE_CODES[name]


def _check_array(arr: np.ndarray, name: str = "array") -> np.ndarray:
    if not isinstance(arr, np.ndarray):
        raise TypeError(f"{name} must be a numpy array, got {type(arr)}")
    if not arr.flags.c_contiguous:
        raise Error(f"{name} must be C-contiguous")
    return arr


def _ptr(arr: np.ndarray):
    return ctypes.c_void_p(arr.ctypes.data)


def _counts_arg(counts: Sequence[int]):
    return (ctypes.c_size_t * len(counts))(*counts)


def _timeout_ms(timeout: Optional[float]) -> int:
    # 0 tells the native side to use the context default.
    return 0 if timeout is None else max(1, int(timeout * 1000))


_copy_out = _lib.copy_out


def _resolve_output(output, dtype, count: int, op_name: str) -> np.ndarray:
    """Allocate (or validate a preallocated) result array: `count`
    elements of `dtype`. Preallocation is the plan-cache hot path — a
    stable output pointer lets repeated calls replay a cached plan."""
    if output is None:
        return np.empty(count, dtype=dtype)
    out = _check_array(output, "output")
    if out.dtype != dtype or out.size != count:
        raise Error(f"{op_name} output must match dtype {np.dtype(dtype)} "
                    f"and hold {count} elements")
    return out


def _resolve_recv_counts(recv_counts, array: np.ndarray, size: int):
    """Shared reduce_scatter recv_counts contract: default to the even
    split, enforce one entry per rank and a total matching the input
    (typed errors — an assert would vanish under python -O and a short
    vector would read past the ctypes array in the C layer)."""
    if recv_counts is None:
        if array.size % size != 0:
            raise Error("reduce_scatter: array size not divisible by "
                        "group size (pass recv_counts)")
        return [array.size // size] * size
    recv_counts = list(recv_counts)
    if len(recv_counts) != size:
        raise Error(f"reduce_scatter: recv_counts needs one entry per "
                    f"rank ({size}), got {len(recv_counts)}")
    if sum(recv_counts) != array.size:
        raise Error("reduce_scatter: sum(recv_counts) != array.size")
    return recv_counts


class Store:
    """Base rendezvous store handle."""

    # Class-level fallbacks so __del__ is safe when __init__ raised
    # before assignment.
    _handle = None
    _free = staticmethod(lambda handle: None)

    def __init__(self, handle: int):
        self._handle = handle
        # Bound at construction: module globals may already be cleared when
        # __del__ runs during interpreter shutdown.
        self._free = _lib.lib.tc_store_free

    def __del__(self):
        handle, self._handle = self._handle, None
        if handle:
            self._free(handle)

    def set(self, key: str, value: bytes) -> None:
        data = (ctypes.c_uint8 * len(value)).from_buffer_copy(value) \
            if value else (ctypes.c_uint8 * 0)()
        check(_lib.lib.tc_store_set(self._handle, key.encode(), data,
                                    len(value)))

    def get(self, key: str, timeout: float = 30.0) -> bytes:
        return _copy_out(_lib.lib.tc_store_get, self._handle,
                         key.encode(), int(timeout * 1000))

    def add(self, key: str, delta: int) -> int:
        result = ctypes.c_int64()
        check(_lib.lib.tc_store_add(self._handle, key.encode(), delta,
                                    ctypes.byref(result)))
        return result.value

    def delete(self, key: str) -> bool:
        """Remove `key`; True when it existed. A waiter blocked on a
        deleted key keeps waiting — deletion is namespace hygiene
        (lease reaping, retired rebuild/epoch namespaces), not
        signalling (docs/rendezvous.md)."""
        deleted = ctypes.c_int(0)
        check(_lib.lib.tc_store_delete(self._handle, key.encode(),
                                       ctypes.byref(deleted)))
        return bool(deleted.value)

    def list(self, prefix: str = "") -> "list[str]":
        """Keys currently present under `prefix` (relative to this
        store's namespace), unspecified order. Snapshot semantics only:
        keys created or deleted concurrently may or may not appear."""
        return json.loads(_copy_out(_lib.lib.tc_store_list, self._handle,
                                    prefix.encode()))


class HashStore(Store):
    """In-process store for multi-rank-in-one-process tests."""

    def __init__(self):
        super().__init__(check_handle(_lib.lib.tc_hash_store_new()))


class FileStore(Store):
    """Store over a shared filesystem directory."""

    def __init__(self, path: str):
        super().__init__(
            check_handle(_lib.lib.tc_file_store_new(path.encode())))


class PrefixStore(Store):
    """Namespacing decorator over another store."""

    def __init__(self, base: Store, prefix: str):
        super().__init__(
            check_handle(_lib.lib.tc_prefix_store_new(base._handle,
                                                      prefix.encode())))
        self._base = base  # keep the base handle alive


class TcpStoreServer:
    """Hosts the rendezvous key/value service (typically on rank 0)."""

    # Class-level fallbacks so __del__ is safe when __init__ raised
    # before assignment.
    _handle = None
    _free = staticmethod(lambda handle: None)

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._handle = check_handle(
            _lib.lib.tc_tcp_store_server_new(host.encode(), port))
        self.port = _lib.lib.tc_tcp_store_server_port(self._handle)
        self._free = _lib.lib.tc_tcp_store_server_free

    def __del__(self):
        handle, self._handle = self._handle, None
        if handle:
            self._free(handle)


class TcpStore(Store):
    """Client for a TcpStoreServer; retries while the server comes up."""

    def __init__(self, host: str, port: int):
        super().__init__(
            check_handle(_lib.lib.tc_tcp_store_new(host.encode(), port)))


def q8_block() -> int:
    """Resolved TPUCOLL_Q8_BLOCK: elements per q8 wire block (default
    256). Must match on every rank — both ends of each wire parse the
    same unit size (docs/env.md)."""
    block = int(_lib.lib.tc_q8_block())
    if block == 0:
        raise Error(_lib.last_error())
    return block


def q8_wire_bytes(count: int) -> int:
    """Wire bytes a `count`-element float32 stream occupies in the q8
    codec: one float32 scale per block plus one int8 code per element."""
    nbytes = int(_lib.lib.tc_q8_wire_bytes(count))
    if nbytes == 0 and count > 0:
        # 0 is the C boundary's error sentinel (malformed
        # TPUCOLL_Q8_BLOCK) — a mis-sized wire buffer must not be the
        # first symptom.
        raise Error(_lib.last_error())
    return nbytes


def q8_encode(array: np.ndarray) -> np.ndarray:
    """Encode a float32 array into its q8 wire stream (uint8 array) —
    the exact per-hop codec AllreduceAlgorithm ring_q8_wire runs, for
    tests and offline inspection."""
    _check_array(array)
    if array.dtype != np.float32:
        raise Error("q8_encode requires a float32 array")
    out = np.empty(q8_wire_bytes(array.size), dtype=np.uint8)
    check(_lib.lib.tc_q8_encode(_ptr(array), array.size, _ptr(out),
                                out.nbytes))
    return out


def q8_decode(wire: np.ndarray, count: int) -> np.ndarray:
    """Decode a q8 wire stream (uint8 array from q8_encode) back to
    `count` float32 elements."""
    _check_array(wire, "wire")
    if wire.dtype != np.uint8:
        raise Error("q8_decode requires a uint8 wire array")
    out = np.empty(count, dtype=np.float32)
    check(_lib.lib.tc_q8_decode(_ptr(wire), wire.nbytes, _ptr(out), count))
    return out


def q4_block() -> int:
    """Resolved TPUCOLL_Q4_BLOCK: elements per q4 wire block (default
    256). Must match on every rank, like TPUCOLL_Q8_BLOCK."""
    block = int(_lib.lib.tc_q4_block())
    if block == 0:
        raise Error(_lib.last_error())
    return block


def q4_wire_bytes(count: int) -> int:
    """Wire bytes a `count`-element float32 stream occupies in the q4
    codec: one float32 scale per block plus one packed-nibble byte per
    element pair."""
    nbytes = int(_lib.lib.tc_q4_wire_bytes(count))
    if nbytes == 0 and count > 0:
        raise Error(_lib.last_error())
    return nbytes


def q4_encode(array: np.ndarray) -> np.ndarray:
    """Encode a float32 array into its q4 wire stream (uint8 array) —
    the exact per-hop codec AllreduceAlgorithm ring_q4_wire runs.
    Round-trip error is bounded by max|block| / 14 per block."""
    _check_array(array)
    if array.dtype != np.float32:
        raise Error("q4_encode requires a float32 array")
    out = np.empty(q4_wire_bytes(array.size), dtype=np.uint8)
    check(_lib.lib.tc_q4_encode(_ptr(array), array.size, _ptr(out),
                                out.nbytes))
    return out


def q4_decode(wire: np.ndarray, count: int) -> np.ndarray:
    """Decode a q4 wire stream (uint8 array from q4_encode) back to
    `count` float32 elements."""
    _check_array(wire, "wire")
    if wire.dtype != np.uint8:
        raise Error("q4_decode requires a uint8 wire array")
    out = np.empty(count, dtype=np.float32)
    check(_lib.lib.tc_q4_decode(_ptr(wire), wire.nbytes, _ptr(out), count))
    return out


def codec_threads() -> int:
    """Resolved TPUCOLL_CODEC_THREADS: codec pool width the wire rings
    shard encode/dequant-accumulate across (defaults to
    TPUCOLL_LOOP_THREADS). Sharding is byte-identical to serial."""
    n = int(_lib.lib.tc_codec_threads())
    if n == 0:
        raise Error(_lib.last_error())
    return n


def codec_pipeline() -> int:
    """Resolved TPUCOLL_CODEC_PIPELINE: sub-blocks each wire-ring hop is
    split into so encode of block k+1 overlaps transmission of block k.
    Must match on every rank (it shapes the per-hop wire protocol)."""
    n = int(_lib.lib.tc_codec_pipeline())
    if n == 0:
        raise Error(_lib.last_error())
    return n


def uring_available() -> bool:
    """True when the io_uring event engine can run here (kernel + sandbox).
    Device(engine="uring") raises when it cannot; this probes first."""
    return bool(_lib.lib.tc_uring_available())


def derive_keyring(root_key: str, rank: int, size: int) -> str:
    """Launcher-side per-rank identity derivation (docs/transport.md
    "Per-rank identity"): from a root secret the launcher keeps, derive
    rank `rank`'s keyring of pairwise keys K[rank, s] and hand ONLY the
    returned string to that worker (Device(keyring=...)). Workers never
    see the root; a leaked keyring impersonates its one rank, not the
    mesh. Rotation = new root, re-derive, restart."""
    out = ctypes.POINTER(ctypes.c_uint8)()
    check(_lib.lib.tc_derive_keyring(root_key.encode(), rank, size,
                                     ctypes.byref(out)))
    s = ctypes.cast(out, ctypes.c_char_p).value.decode()
    _lib.lib.tc_buf_free(out)
    return s


def crypto_isa_tier() -> int:
    """AEAD bulk tier this process dispatches to: 2 = fused AVX-512,
    1 = AVX2 8-block, 0 = scalar. All tiers are wire-compatible;
    TPUCOLL_NO_AVX512=1 forces the fallback (tests/diagnostics)."""
    return int(_lib.lib.tc_crypto_isa_tier())


class Device:
    """Transport endpoint: event-engine loop thread + shared listener."""

    # Class-level fallbacks so __del__ is safe when __init__ raised
    # before assignment.
    _handle = None
    _free = staticmethod(lambda handle: None)

    def __init__(self, hostname: str = "127.0.0.1", port: int = 0,
                 auth_key: Optional[str] = None, encrypt: bool = False,
                 iface: Optional[str] = None, busy_poll: bool = False,
                 engine: Optional[str] = None,
                 keyring: Optional[str] = None):
        """auth_key: pre-shared key enabling the mutual HMAC handshake on
        every connection (all ranks must agree; see docs/transport.md).
        keyring: per-rank identity tier instead — a serialized keyring
        from derive_keyring(); connections then authenticate with the
        PAIRWISE key only the two endpoints hold, so a leaked worker
        credential impersonates one rank, not the mesh. Mutually
        exclusive with auth_key. encrypt=True additionally encrypts the
        data plane with per-connection ChaCha20-Poly1305 keys derived
        from the handshake (requires auth_key or keyring; all ranks must
        agree — plaintext and encrypted peers reject each other at
        hello). iface binds by interface NAME (its first address
        overrides hostname). busy_poll=True spins instead of sleeping
        (loop thread and blocking waits) — the reference's sync mode for
        the sub-10us latency regime; burns a core. engine picks the
        event engine: "epoll" | "uring" (io_uring) | "auto"; default =
        TPUCOLL_ENGINE env, else auto (docs/transport.md)."""
        if encrypt and not (auth_key or keyring):
            raise ValueError("encrypt=True requires auth_key or keyring")
        if auth_key and keyring:
            raise ValueError("auth_key and keyring are mutually exclusive")
        self._handle = check_handle(
            _lib.lib.tc_device_new(hostname.encode(), port,
                                   auth_key.encode() if auth_key else None,
                                   1 if encrypt else 0,
                                   iface.encode() if iface else None,
                                   1 if busy_poll else 0,
                                   engine.encode() if engine else None,
                                   keyring.encode() if keyring else None))
        self._free = _lib.lib.tc_device_free

    def engine_stats(self) -> dict:
        """Cumulative event-engine submission counters since device
        creation: {"enters": io_uring_enter syscalls, "sqes": ops
        submitted, "cqes": completions drained}. The uring engine batches
        many SQEs per enter (sqes > enters); readiness engines pay one
        syscall per I/O op by construction, and the epoll engine reports
        zeros here. See docs/transport.md."""
        enters = ctypes.c_uint64()
        sqes = ctypes.c_uint64()
        cqes = ctypes.c_uint64()
        _lib.lib.tc_device_engine_stats(
            self._handle, ctypes.byref(enters), ctypes.byref(sqes),
            ctypes.byref(cqes))
        return {"enters": enters.value, "sqes": sqes.value,
                "cqes": cqes.value}

    def __del__(self):
        handle, self._handle = self._handle, None
        if handle:
            self._free(handle)


_CONNECT_LOGGER_CFUNC = ctypes.CFUNCTYPE(
    None, ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
    ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_char_p)
# Trampolines are retained for the process lifetime: an in-flight connect
# on another thread may hold a snapshot of the previous hook, so freeing a
# replaced trampoline could crash it. Debug-hook registration is rare;
# the retention is bounded by the number of set_* calls.
_connect_logger_keepalive = []


def set_connect_debug_logger(fn) -> None:
    """Register a process-wide hook receiving a dict per outbound
    connection attempt: {self_rank, peer_rank, remote, local, attempt,
    ok, will_retry, error} (reference: gloo tcp debug_data.h
    ConnectDebugData -> DebugLogger). Runs on connecting threads — keep
    it cheap. Pass None to clear."""
    if fn is None:
        _lib.lib.tc_set_connect_debug_logger(None)
        return

    def thunk(self_rank, peer_rank, remote, local, attempt, ok, will_retry,
              error):
        try:
            fn({"self_rank": self_rank, "peer_rank": peer_rank,
                "remote": (remote or b"").decode(),
                "local": (local or b"").decode(), "attempt": attempt,
                "ok": bool(ok), "will_retry": bool(will_retry),
                "error": (error or b"").decode()})
        except Exception:  # noqa: BLE001 — must not cross the C frame
            pass

    cb = _CONNECT_LOGGER_CFUNC(thunk)
    _connect_logger_keepalive.append(cb)
    _lib.lib.tc_set_connect_debug_logger(
        ctypes.cast(cb, ctypes.c_void_p))


class UnboundBuffer:
    """Registered region for tagged point-to-point send/recv."""

    # Class-level fallbacks so __del__ is safe when __init__ raised
    # before assignment.
    _handle = None
    _free = staticmethod(lambda handle: None)

    def __init__(self, context: "Context", array: np.ndarray):
        _check_array(array)
        self._array = array  # pin the memory
        self._context = context
        self._handle = check_handle(
            _lib.lib.tc_buffer_new(context._handle, _ptr(array),
                                   array.nbytes))
        self._free = _lib.lib.tc_buffer_free

    def __del__(self):
        handle, self._handle = self._handle, None
        if handle:
            self._free(handle)

    def send(self, dst: int, slot: int, offset: int = 0,
             nbytes: Optional[int] = None) -> None:
        if nbytes is None:
            nbytes = self._array.nbytes - offset
        check(_lib.lib.tc_buffer_send(self._handle, dst, slot, offset,
                                      nbytes))

    def recv(self, src, slot: int, offset: int = 0,
             nbytes: Optional[int] = None) -> None:
        if nbytes is None:
            nbytes = self._array.nbytes - offset
        if isinstance(src, int):
            check(_lib.lib.tc_buffer_recv(self._handle, src, slot, offset,
                                          nbytes))
        else:
            srcs = (ctypes.c_int * len(src))(*src)
            check(_lib.lib.tc_buffer_recv_any(self._handle, srcs, len(src),
                                              slot, offset, nbytes))

    def wait_send(self, timeout: Optional[float] = None) -> bool:
        code = _lib.lib.tc_buffer_wait_send(
            self._handle, self._context._resolve_timeout_ms(timeout))
        if code == _lib._TC_ERR_ABORTED:
            return False
        check(code)
        return True

    def wait_recv(self, timeout: Optional[float] = None) -> Optional[int]:
        """Returns the source rank, or None if the wait was aborted."""
        src = ctypes.c_int(-1)
        code = _lib.lib.tc_buffer_wait_recv(
            self._handle, self._context._resolve_timeout_ms(timeout),
            ctypes.byref(src))
        if code == _lib._TC_ERR_ABORTED:
            return None
        check(code)
        return src.value

    def wait_put(self, timeout: Optional[float] = None) -> Optional[int]:
        """Wait for one notify-put arrival into this buffer's exported
        region (bound-buffer waitRecv analog); returns the source rank,
        or None if aborted. A SEPARATE queue from wait_recv: one-sided
        arrivals never satisfy a posted tagged recv or vice versa."""
        src = ctypes.c_int(-1)
        code = _lib.lib.tc_buffer_wait_put(
            self._handle, self._context._resolve_timeout_ms(timeout),
            ctypes.byref(src))
        if code == _lib._TC_ERR_ABORTED:
            return None
        check(code)
        return src.value

    def abort_wait_send(self) -> None:
        _lib.lib.tc_buffer_abort_wait_send(self._handle)

    def abort_wait_recv(self) -> None:
        _lib.lib.tc_buffer_abort_wait_recv(self._handle)

    # ---- one-sided put/get (reference: gloo transport RemoteKey) ----

    def get_remote_key(self) -> bytes:
        """Export this buffer as a one-sided target. The returned bytes
        are exchangeable over any channel (typically allgathered); peers
        put()/get() against them with no posted operation on this side.
        The registration lives as long as this buffer."""
        n = _lib.lib.tc_remote_key_size()
        out = ctypes.create_string_buffer(n)
        check(_lib.lib.tc_buffer_remote_key(self._handle, out, n))
        return out.raw

    def put(self, remote_key: bytes, offset: int = 0, roffset: int = 0,
            nbytes: Optional[int] = None, notify: bool = False) -> None:
        """One-sided write: local [offset, offset+nbytes) into the remote
        region at roffset. Completion via wait_send; the target posts
        nothing. notify=True additionally completes a wait_put on the
        EXPORTING buffer when the payload lands (the reference's bound-
        buffer contract: registered memory + arrival notification).
        Bounds are validated against the key synchronously."""
        if nbytes is None:
            nbytes = self._array.nbytes - offset
        check(_lib.lib.tc_buffer_put(self._handle, remote_key,
                                     len(remote_key), offset, roffset,
                                     nbytes, 1 if notify else 0))

    def get(self, remote_key: bytes, slot: int, offset: int = 0,
            roffset: int = 0, nbytes: Optional[int] = None) -> None:
        """One-sided read: remote region [roffset, roffset+nbytes) into
        local [offset, ...). Completion via wait_recv; `slot` must not be
        used by other traffic with that peer."""
        if nbytes is None:
            nbytes = self._array.nbytes - offset
        check(_lib.lib.tc_buffer_get(self._handle, remote_key,
                                     len(remote_key), slot, offset, roffset,
                                     nbytes))


class Work:
    """Handle for one async collective issued on an :class:`AsyncEngine`.

    The collective runs on its engine lane's private forked context; this
    handle pins the numpy buffers until completion and surfaces the
    result. Errors surface TYPED at :meth:`wait` — `TimeoutError`,
    `IoError`, or `Aborted` (engine shut down with the op in flight) —
    with the blamed lane and op named in the message. The collective ran
    in place, so after an error the buffer contents are UNDEFINED from
    the moment the op was ISSUED, not from wait() (docs/errors.md,
    "In-place collectives"; docs/async.md)."""

    # Class-level fallbacks so __del__ is safe when __init__ raised
    # before assignment.
    _handle = None
    _free = staticmethod(lambda handle: None)

    def __init__(self, engine: "AsyncEngine", handle: int, op: str,
                 arrays, result=None):
        self._engine = engine
        self._handle = handle
        self.op = op
        self._arrays = arrays  # pin the buffers until completion
        #: Output array for allgather/reduce_scatter (the reduced array
        #: itself for in-place allreduce).
        self.result = result
        self._free = _lib.lib.tc_work_free

    def __del__(self):
        handle, self._handle = self._handle, None
        if not handle:
            return
        if _lib.lib.tc_work_status(handle) >= 2:  # done/error
            self._free(handle)
        else:
            # Op still in flight — or the status probe itself failed
            # (tc_work_status < 0): its lane thread may keep reading/
            # writing our numpy buffers through raw pointers, so
            # dropping the references now would be a use-after-free.
            # Park buffers and handle on the engine; released at
            # shutdown(), after the lane threads are joined.
            self._engine._park(handle, self._arrays)

    def wait(self, timeout: Optional[float] = None):
        """Block until the op completes; raises its typed error if it
        failed. timeout=None waits with no wait-side deadline — the op's
        own collective timeout (set at issue time) still bounds every
        blocking step, so a dead peer surfaces as TimeoutError/IoError
        here rather than a hang. A wait-side timeout raises TimeoutError
        but does NOT cancel the op. Returns :attr:`result`."""
        ms = 0 if timeout is None else max(1, int(timeout * 1000))
        check(_lib.lib.tc_work_wait(self._handle, ms))
        return self.result

    def test(self) -> bool:
        """Non-blocking: True once the op finished (successfully or
        not). A failure still surfaces only at wait()."""
        st = _lib.lib.tc_work_status(self._handle)
        if st < 0:
            # The probe itself failed; a poll loop must not read that
            # as "still in flight" and spin forever.
            raise _lib.Error(_lib.last_error())
        return st >= 2

    def error(self) -> Optional[str]:
        """Error message of a failed op, or None (pending/succeeded)."""
        msg = _copy_out(_lib.lib.tc_work_error_message,
                        self._handle).decode()
        return msg or None


class AsyncEngine:
    """Async collective work queue over a pool of lanes (docs/async.md).

    Each lane is a worker thread owning a privately-tagged forked
    sub-context of the parent, so collectives in flight on different
    lanes can never cross-match; submissions are assigned round-robin in
    issue order (submission i runs on lane i % lanes), which keeps every
    lane's op stream identical across ranks and the flight recorder's
    cross-rank cseq/fingerprint comparison sound.

    CONSTRUCTION IS A COLLECTIVE (it forks over the parent): every rank
    must construct concurrently with the same lane count — as must every
    issue_* call, in the same order, exactly like blocking collectives.
    Prefer :meth:`Context.async_engine`, which also wires the engine
    into the context's close()."""

    # Class-level fallbacks so __del__ is safe when __init__ raised
    # before assignment.
    _handle = None
    _free = staticmethod(lambda handle: None)
    _parked = ()
    _work_free = staticmethod(lambda handle: None)

    def __init__(self, context: "Context", lanes: Optional[int] = None,
                 tag_base: int = 0):
        if lanes is None:
            raw = os.environ.get("TPUCOLL_ASYNC_LANES", "2")
            try:
                lanes = int(raw)
                if lanes < 1:
                    raise ValueError(raw)
            except ValueError:
                raise Error(f"TPUCOLL_ASYNC_LANES: not a positive "
                            f"integer: {raw!r}") from None
        # (handle, arrays) of Works dropped while still in flight; their
        # buffers must outlive the lane threads (see Work.__del__).
        self._parked = []
        self._work_free = _lib.lib.tc_work_free
        self._handle = check_handle(
            _lib.lib.tc_async_new(context._handle, lanes, tag_base))
        self._context = context
        self.lanes = lanes
        self._free = _lib.lib.tc_async_free

    def __del__(self):
        # tc_async_free shuts down first: queued work fails typed
        # (Aborted), the in-flight op is aborted via its lane context.
        handle, self._handle = self._handle, None
        if handle:
            self._free(handle)
        self._release_parked()

    def _park(self, work_handle: int, arrays) -> None:
        self._parked.append((work_handle, arrays))

    def _release_parked(self) -> None:
        # Only safe once the lane threads are joined (shutdown/free).
        parked, self._parked = self._parked, []
        for handle, _ in parked:
            self._work_free(handle)

    def shutdown(self) -> None:
        """Fail queued work loudly (Aborted, naming lane/op), abort the
        in-flight op on every lane, join the lane threads. Idempotent;
        every waiter unblocks with a typed error."""
        if self._handle:
            check(_lib.lib.tc_async_shutdown(self._handle))
            self._release_parked()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False

    def allreduce_async(self, array: np.ndarray, op="sum",
                        algorithm: str = "auto",
                        timeout: Optional[float] = None,
                        wire: Optional[str] = None) -> Work:
        """In-place async allreduce; returns a :class:`Work`. Same
        semantics as Context.allreduce (including the wire= compression
        opt-in) except custom-callable reductions are unsupported (they
        would run on a lane thread). From issue until wait() returns,
        `array` must not be read or written — the undefined-contents
        window of docs/errors.md opens HERE."""
        algorithm = Context._resolve_wire(wire, algorithm)
        _check_array(array)
        if callable(op):
            raise Error("async allreduce does not support callable "
                        "reductions (lane threads cannot enter Python)")
        # In-place entry: the stable buffer pointer keys the per-lane
        # plan cache, so a training loop's repeated buckets replay with
        # zero allocations/registrations on the lane contexts too.
        handle = check_handle(_lib.lib.tc_async_allreduce_inplace(
            self._handle, _ptr(array), array.size,
            _dtype_code(array), ReduceOp.parse(op),
            Context._ALGORITHMS[algorithm], _timeout_ms(timeout)))
        return Work(self, handle, "allreduce", (array,), result=array)

    def reduce_scatter_async(self, array: np.ndarray,
                             recv_counts: Optional[Sequence[int]] = None,
                             op="sum", algorithm: str = "auto",
                             timeout: Optional[float] = None,
                             wire: Optional[str] = None,
                             output: Optional[np.ndarray] = None) -> Work:
        """Async reduce_scatter; the output array is ``work.result``.
        wire="q8" opts into the int8 block-quantized wire (float32 sum
        only; docs/algorithms.md). A preallocated `output`
        (recv_counts[rank] elements) keeps the result pointer stable
        across steps — the per-lane plan-cache hot path."""
        algorithm = Context._resolve_rs_wire(wire, algorithm)
        _check_array(array)
        if callable(op):
            raise Error("async reduce_scatter does not support callable "
                        "reductions (lane threads cannot enter Python)")
        size = self._context.size
        recv_counts = _resolve_recv_counts(recv_counts, array, size)
        out = _resolve_output(output, array.dtype,
                              int(recv_counts[self._context.rank]),
                              "reduce_scatter")
        handle = check_handle(_lib.lib.tc_async_reduce_scatter(
            self._handle, _ptr(array), _ptr(out),
            _counts_arg(recv_counts), size, _dtype_code(array),
            ReduceOp.parse(op), Context._RS_ALGORITHMS[algorithm],
            _timeout_ms(timeout)))
        return Work(self, handle, "reduce_scatter", (array, out),
                    result=out)

    def allgather_async(self, array: np.ndarray,
                        timeout: Optional[float] = None,
                        output: Optional[np.ndarray] = None,
                        algorithm: str = "auto") -> Work:
        """Async allgather; the (size, *shape) output is ``work.result``.
        A preallocated `output` (size * array.size elements) keeps the
        result pointer stable — the per-lane plan-cache hot path.
        algorithm="hier" as for Context.allgather."""
        _check_array(array)
        out = _resolve_output(output, array.dtype,
                              self._context.size * array.size,
                              "allgather")
        if output is None:
            out = out.reshape((self._context.size,) + array.shape)
        handle = check_handle(_lib.lib.tc_async_allgather(
            self._handle, _ptr(array), _ptr(out), array.size,
            _dtype_code(array), Context._HIER_ALGORITHMS[algorithm],
            _timeout_ms(timeout)))
        return Work(self, handle, "allgather", (array, out), result=out)

    def stats(self) -> dict:
        """Engine counters: {"lanes", "in_flight", "submitted",
        "completed", "errors", "per_lane": [{"submitted", "completed",
        "errors", "queue_depth", "poisoned"}, ...]}."""
        return json.loads(_copy_out(_lib.lib.tc_async_stats_json,
                                    self._handle))

    def _lane_handle(self, lane: int) -> int:
        return check_handle(
            _lib.lib.tc_async_lane_context(self._handle, lane))

    def lane_metrics(self, lane: int, drain: bool = False) -> dict:
        """Metrics snapshot of lane `lane`'s forked sub-context (async
        ops are recorded there, not on the parent) — same shape as
        Context.metrics()."""
        snap = json.loads(_copy_out(_lib.lib.tc_metrics_json,
                                    self._lane_handle(lane),
                                    1 if drain else 0))
        snap["transport"] = {int(k): v
                             for k, v in snap["transport"].items()}
        return snap

    def lane_profile(self, lane: int) -> dict:
        """Phase-profiler snapshot of lane `lane`'s sub-context — same
        shape as Context.profile(). Like the flight recorder, lane k's
        cseq axis is cross-rank comparable per lane: merge lane k
        against the peers' lane k, never across lanes."""
        return json.loads(_copy_out(_lib.lib.tc_profile_json,
                                    self._lane_handle(lane)))

    def lane_flightrec(self, lane: int) -> dict:
        """Flight-recorder snapshot of lane `lane`'s sub-context — same
        shape as Context.flightrec(). Lane k's cseq/fingerprint stream
        is cross-rank comparable on its own (round-robin assignment is
        deterministic), so merge per lane, never across lanes."""
        return json.loads(_copy_out(_lib.lib.tc_flightrec_json,
                                    self._lane_handle(lane)))

    def flightrec_dump(self, directory: str) -> dict:
        """Dump every lane's flight recorder under `directory`, one
        merge-ready subdirectory per lane
        (``<directory>/lane<k>/flightrec-rank<r>.json``). Returns
        {lane: path}. Merge each lane subdirectory separately with
        gloo_tpu.utils.flightrec.merge()."""
        paths = {}
        for lane in range(self.lanes):
            lane_dir = os.path.join(directory, f"lane{lane}")
            os.makedirs(lane_dir, exist_ok=True)
            path = os.path.join(
                lane_dir, f"flightrec-rank{self._context.rank}.json")
            check(_lib.lib.tc_flightrec_dump(self._lane_handle(lane),
                                             path.encode()))
            paths[lane] = path
        return paths


class CollectivePlan:
    """Persistent handle for one repeated collective — the reference's
    Algorithm-object pattern (create once with pre-registered buffers,
    replay every step), surfaced in Python.

    Built by :meth:`Context.allreduce_plan` /
    :meth:`Context.reduce_scatter_plan` / :meth:`Context.allgather_plan`.
    Validation and ctypes argument marshalling happen ONCE at
    construction; each ``plan()`` call is a single foreign call whose
    stable buffer pointers hit the native plan cache, so the steady
    state replays with zero allocations and zero buffer registrations
    (docs/design.md "Persistent collective plans").

    The plan pins its numpy buffers; the collective runs in place on
    them every call (``result`` is the output array). All the usual
    collective contracts apply per call — every rank must call matching
    plans in matching order, and on error the buffer contents are
    undefined (docs/errors.md)."""

    __slots__ = ("_context", "_fn", "_args", "_arrays", "result")

    def __init__(self, context, fn, args, arrays, result):
        # Pin the owning Context: the marshalled args embed its native
        # handle, so a plan outliving the Context object would call
        # into freed memory otherwise.
        self._context = context
        self._fn = fn
        self._args = args
        self._arrays = arrays  # pin every buffer the native side touches
        self.result = result

    def __call__(self):
        check(self._fn(*self._args))
        return self.result


class Context:
    """A connected process group: collectives + point-to-point messaging.

    One Context per (process, group). All collective calls are blocking and
    must be entered by every rank with matching arguments; concurrent
    collectives on one context need distinct tags. For non-blocking
    collectives with inter-collective pipelining, see
    :meth:`async_engine` (docs/async.md).
    """

    # Class-level fallbacks so __del__ is safe when __init__ raised
    # before assignment.
    _handle = None
    _free = staticmethod(lambda handle: None)

    def __init__(self, rank: int, size: int, timeout: float = 30.0):
        self.rank = rank
        self.size = size
        self._timeout = timeout
        self._handle = check_handle(_lib.lib.tc_context_new(rank, size))
        _lib.lib.tc_context_set_timeout(self._handle, int(timeout * 1000))
        self._store = None
        self._device = None
        # Weak refs (an engine holds a strong ref to its context, so a
        # strong list here would cycle): close() shuts live engines down
        # before tearing the parent transport down.
        self._engines = []
        self._free = _lib.lib.tc_context_free

    def __del__(self):
        handle, self._handle = self._handle, None
        if handle:
            self._free(handle)

    def _resolve_timeout_ms(self, timeout: Optional[float]) -> int:
        return _timeout_ms(self._timeout if timeout is None else timeout)

    def connect_full_mesh(self, store: Store, device: Device) -> None:
        check(_lib.lib.tc_context_connect(self._handle, store._handle,
                                          device._handle))
        self._store = store
        self._device = device

    def fork(self, tag: int = 0xFFFFFF0) -> "Context":
        """Create a fresh, independently-tagged context over this one's
        device, exchanging bootstrap blobs through this context's own
        collectives instead of a store (the reference's ContextFactory
        pattern). Cheap re-bootstrap for libraries that need private
        communicators."""
        child = Context(self.rank, self.size, timeout=self._timeout)
        check(_lib.lib.tc_context_fork(child._handle, self._handle, tag))
        child._device = self._device
        return child

    # ---- process-group subsystem: topology + native split ----

    @classmethod
    def _from_handle(cls, handle: int, timeout: float,
                     parent: "Context") -> "Context":
        """Wrap a native context handle produced by tc_split (ownership
        transfers to the wrapper)."""
        obj = cls.__new__(cls)
        obj.rank = int(_lib.lib.tc_context_rank(handle))
        obj.size = int(_lib.lib.tc_context_size(handle))
        obj._timeout = timeout
        obj._handle = handle
        obj._store = None
        obj._device = parent._device
        obj._engines = []
        obj._parent = parent  # pin the parent (shared device, store)
        obj._free = _lib.lib.tc_context_free
        return obj

    def set_host_id(self, host_id: str) -> None:
        """Override this context's host fingerprint for topology
        discovery; must be called BEFORE connect_full_mesh. Ranks with
        equal fingerprints are treated as co-hosted: they may negotiate
        the shm payload plane, split_by_host() groups them, and the
        hierarchical collectives put them on one intra-host plane.
        Defaults (unset): TPUCOLL_HOST_ID, else hostname + boot id.
        Overriding is how tests simulate an H-host topology on one
        machine (docs/topology.md)."""
        check(_lib.lib.tc_context_set_host_id(self._handle,
                                              host_id.encode()))

    def topology(self) -> dict:
        """Host topology discovered at bootstrap: {"rank", "host_index",
        "local_rank", "local_size", "leader", "is_leader", "n_hosts",
        "non_flat", "hosts": [{"fingerprint", "ranks"}, ...]}. Hosts are
        numbered by lowest member rank; the leader of a host is its
        lowest global rank (docs/topology.md)."""
        return json.loads(_copy_out(_lib.lib.tc_topology_json,
                                    self._handle))

    def group_tag(self) -> str:
        """Group-tag namespace of this communicator: "" for a bootstrap
        context, "s<tag>.<gen>.c<color>" segments for split subgroups
        (nested splits join with "/"). Scopes post-bootstrap store keys,
        flight-recorder dump names, and the metrics "group" field."""
        return _copy_out(_lib.lib.tc_context_group_tag,
                         self._handle).decode()

    def split(self, color: int, key: int = 0,
              tag: int = 0) -> Optional["Context"]:
        """Split this communicator (MPI_Comm_split semantics): ranks
        passing the same non-negative `color` form a subset Context with
        fresh contiguous ranks ordered by (key, parent rank); a negative
        color opts out and returns None.

        A COLLECTIVE over the parent: every rank must call concurrently
        with the same `tag`; concurrent splits must use distinct tags
        (the tag scopes both the store keys and, on store-less forked
        parents, the exchange collectives — which also consume parent
        tags [tag, tag+2]).

        The child is a full communicator: members-only mesh, own
        tag/slot namespace, own plan cache / metrics / flight recorder /
        fault domain / store namespace, topology = the member subset.
        All collectives, plans, and async engines work on it."""
        out = ctypes.c_void_p()
        check(_lib.lib.tc_split(self._handle, int(color), int(key), tag,
                                ctypes.byref(out)))
        if not out.value:
            return None
        return Context._from_handle(out.value, self._timeout, self)

    def split_by_host(self, tag: int = 0) -> "Context":
        """split(color = host index, key = rank): the intra-host
        communicator (every member co-hosted, shm-reachable)."""
        out = ctypes.c_void_p()
        check(_lib.lib.tc_split_by_host(self._handle, tag,
                                        ctypes.byref(out)))
        return Context._from_handle(
            check_handle(out.value), self._timeout, self)

    def close(self) -> None:
        """Close the context. Any async engine created through
        :meth:`async_engine` is shut down FIRST: queued async work fails
        loudly (Aborted, naming the lane/op), in-flight ops abort with a
        typed IoError at their Work.wait() — never a hang or a segfault
        (docs/async.md, "Lifecycle")."""
        for ref in self._engines:
            engine = ref()
            if engine is not None:
                engine.shutdown()
        check(_lib.lib.tc_context_close(self._handle))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def next_slot(self, num: int = 1) -> int:
        return _lib.lib.tc_next_slot(self._handle, num)

    def debug_dump(self) -> None:
        """Print transport state (posted receives, stash occupancy,
        backpressure flags) to stderr — the deadlock diagnosis tool."""
        _lib.lib.tc_debug_dump(self._handle)

    def shm_stats(self) -> dict:
        """Shared-memory payload-plane stats: bytes moved through the
        same-host rings and how many pairs negotiated the plane (0 when
        peers are remote or TPUCOLL_SHM=0)."""
        tx = ctypes.c_uint64()
        rx = ctypes.c_uint64()
        pairs = ctypes.c_int()
        _lib.lib.tc_context_shm_stats(self._handle, ctypes.byref(tx),
                                      ctypes.byref(rx), ctypes.byref(pairs))
        return {"tx_bytes": tx.value, "rx_bytes": rx.value,
                "active_pairs": pairs.value}

    # ---- tracing (capability the reference lacks) ----

    def trace_start(self) -> None:
        """Begin recording one span per collective on this context."""
        _lib.lib.tc_trace_start(self._handle)

    def trace_stop(self) -> None:
        _lib.lib.tc_trace_stop(self._handle)

    def trace_json(self) -> str:
        """Drain recorded spans as Chrome trace-event JSON (load the file
        in Perfetto / chrome://tracing; merge ranks by concatenating their
        event arrays)."""
        return _copy_out(_lib.lib.tc_trace_json, self._handle).decode()

    def trace_dump(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.trace_json())

    # ---- flight recorder (always-on post-mortem ring) ----

    def flightrec(self) -> dict:
        """Snapshot the context's always-on flight recorder as a dict.

        Shape (docs/flightrec.md): {"rank", "size", "reason",
        "blamed_peer", "now_us", "next_seq", "capacity", "dropped",
        "events": [{"seq", "cseq", "op", "algo", "slot", "peer",
        "bytes", "dtype", "fp", "state", "ts_enqueued_us",
        "ts_started_us", "ts_completed_us"}, ...]} where `seq` is the
        ring sequence over every recorded op, `cseq` the cross-rank-
        comparable COLLECTIVE sequence number (null for p2p ops), `fp`
        the desync fingerprint (hash of op/dtype/rank-invariant
        bytes/root), and `state` one of enqueued/started/completed.
        Non-draining: the ring keeps rolling. See
        gloo_tpu.utils.flightrec for dump/merge/analyze."""
        return json.loads(_copy_out(_lib.lib.tc_flightrec_json,
                                    self._handle))

    def flightrec_dump(self, path: str) -> str:
        """Write the flight-recorder ring to `path` as JSON (the explicit
        dump trigger; stalls, transport failures, and — opt-in — fatal
        signals dump automatically to TPUCOLL_FLIGHTREC_DIR). Returns
        the path for chaining into merge()."""
        check(_lib.lib.tc_flightrec_dump(self._handle, path.encode()))
        return path

    def flightrec_seq(self) -> int:
        """Ops recorded so far (== the next op's sequence number)."""
        return int(_lib.lib.tc_flightrec_seq(self._handle))

    # ---- phase-level collective profiler (docs/profiling.md) ----

    def profile(self) -> dict:
        """Snapshot the context's phase profiler as a dict.

        Shape: {"rank", "size", "group", "enabled", "now_us",
        "next_seq", "capacity", "dropped", "ops": [{"seq", "cseq",
        "op", "algo", "bytes", "start_us", "total_us",
        "phases": {"pack"|"post"|"wire_wait"|"reduce"|"unpack"|
        "intra"|"inter"|"fanout": us, ...}}, ...]} where `cseq` is the
        flight recorder's cross-rank collective sequence number — merge
        per-rank snapshots with gloo_tpu.utils.profile.merge() and
        attribute stragglers with .attribute(). Non-draining: the
        bounded ring (TPUCOLL_PROFILE_RING) keeps rolling; `dropped`
        counts overwritten rows. Aggregate per-(op, algorithm, phase)
        histograms land in metrics()["phases"]."""
        return json.loads(_copy_out(_lib.lib.tc_profile_json,
                                    self._handle))

    def profile_enable(self, on: bool = True) -> None:
        """Toggle the phase profiler at runtime (overrides the
        TPUCOLL_PROFILE environment gate for this context). Off, every
        collective pays exactly one relaxed atomic load."""
        _lib.lib.tc_profile_enable(self._handle, 1 if on else 0)

    def profile_enabled(self) -> bool:
        return bool(_lib.lib.tc_profile_enabled(self._handle))

    # ---- causal span recorder (docs/critpath.md) ----

    def spans(self) -> dict:
        """Snapshot the context's causal span recorder as a dict.

        Shape: {"rank", "size", "group", "enabled", "now_us",
        "next_seq", "capacity", "dropped", "spans": [{"seq", "cseq",
        "id", "kind": "send"|"recv"|"wait"|"local", "phase", "peer",
        "slot", "bytes", "t0_us", "t1_us", "op"}, ...]} where `cseq`
        is the flight recorder's cross-rank collective sequence (null
        for p2p ops), `id` the per-op emission ordinal (the k-th send
        rank a posts toward b pairs with the k-th recv b posts from a),
        and `peer` the remote rank for send/recv spans (null
        otherwise). Merge per-rank snapshots and extract the critical
        path with gloo_tpu.utils.critpath. Off by default
        (TPUCOLL_SPANS=0); non-draining bounded ring
        (TPUCOLL_SPANS_RING)."""
        return json.loads(_copy_out(_lib.lib.tc_spans_json,
                                    self._handle))

    def spans_enable(self, on: bool = True) -> None:
        """Toggle the causal span recorder at runtime (overrides the
        TPUCOLL_SPANS environment gate for this context). Off, every
        collective pays exactly one relaxed atomic load."""
        _lib.lib.tc_spans_enable(self._handle, 1 if on else 0)

    def spans_enabled(self) -> bool:
        return bool(_lib.lib.tc_spans_enabled(self._handle))

    # ---- in-band fleet observability plane (docs/fleet.md) ----

    def fleetobs_start(self) -> None:
        """Start the hierarchical telemetry fold for this rank's
        topology role: members push fixed-size reports to their host
        leader over the transport mesh, leaders pre-aggregate one host
        document and relay it to rank 0, which merges the fleet view
        and runs the continuous anomaly detectors
        (persistent_straggler / slow_link / lease_jitter). Requires a
        connected context; a no-op under TPUCOLL_FLEETOBS=0 or when
        already running. Rank 0's merged view is fleet(), also served
        as /fleet by serve_telemetry()."""
        check(_lib.lib.tc_fleetobs_start(self._handle))

    def fleetobs_stop(self) -> None:
        """Stop and join the aggregation thread (automatic at
        close()). Safe when never started."""
        check(_lib.lib.tc_fleetobs_stop(self._handle))

    def fleetobs_running(self) -> bool:
        return bool(_lib.lib.tc_fleetobs_running(self._handle))

    def fleetobs_set_aux(self, aux: dict) -> None:
        """Attach a JSON-serializable dict to this rank's next fleet
        report as its "aux" field — the side-channel for state the
        native core cannot see (e.g. ElasticAgent.status() under an
        "elastic" key, which feeds the lease_jitter detector).
        Raises if the plane was never started."""
        check(_lib.lib.tc_fleetobs_set_aux(
            self._handle, json.dumps(aux).encode()))

    def fleet(self) -> dict:
        """The merged fleet document as a dict. On rank 0 (with the
        plane running): coverage, per-host summaries with embedded
        per-rank reports, the in-band straggler leaderboard, slow
        links, and recent anomalies (see docs/fleet.md for the
        schema). On other ranks or with the plane off: a stub whose
        "role"/"note" say where the real view lives."""
        return json.loads(_copy_out(_lib.lib.tc_fleet_json,
                                    self._handle))

    # ---- metrics + straggler watchdog (capability the reference lacks) --

    def metrics(self, drain: bool = False) -> dict:
        """Snapshot the context's metrics registry as a dict.

        Shape: {"rank", "size", "enabled", "watchdog_ms", "now_us",
        "retries", "stash_pauses", "trace_events_dropped",
        "plan_hits", "plan_misses", "plan_evictions", "ubuf_creates",
        "faults": {"total", <action>: n...},
        "anomalies": {"total", "kinds": {kind: {rank: n}}} (fleet
        observability detectors, docs/fleet.md),
        "transport_failure": null | {"peer", "count", "message"},
        "ops": {name: {"calls", "bytes", "errors",
        "latency_us": hist}},
        "phases": {op: {algorithm: {phase: hist}}} (the phase
        profiler's aggregates, docs/profiling.md),
        "transport": {peer: {"sent_msgs",
        "sent_bytes", "recv_msgs", "recv_bytes", "last_progress_us",
        "last_progress_age_us", "rx_pauses", "tx_posts",
        "bw_ewma_bps", "rtt_ewma_us", "recv_wait_us": hist,
        "chan_tx": {channel: bytes}, "chan_rx": {channel: bytes}}},
        "watchdog":
        {"stalls", "last"}} where hist is {"count", "sum_us", "max_us",
        "buckets": [[le_us, n], ...]} with per-bucket (non-cumulative)
        counts in power-of-two microsecond buckets. Timestamps are
        steady-clock microseconds (compare against "now_us", not wall
        time). drain=True atomically resets counters after the snapshot
        (scrape-style usage); configuration and progress timestamps
        survive a drain. See gloo_tpu.utils.metrics for Prometheus text
        exposition and quantile estimation.
        """
        snap = json.loads(_copy_out(_lib.lib.tc_metrics_json,
                                    self._handle, 1 if drain else 0))
        # JSON keys are strings; peer ranks are ints.
        snap["transport"] = {int(k): v
                             for k, v in snap["transport"].items()}
        # Async engines record their collectives on their lane contexts
        # (lane_metrics); the parent snapshot carries the engine-level
        # gauges so one scrape sees the in-flight depth.
        engines = [e() for e in self._engines]
        engines = [e for e in engines if e is not None and e._handle]
        if engines:
            snap["async"] = {
                "in_flight": sum(e.stats()["in_flight"] for e in engines),
                "engines": [e.stats() for e in engines],
            }
        return snap

    def metrics_enable(self, on: bool = True) -> None:
        """Toggle counter collection. Enabled by default; when disabled
        the per-op cost drops to a single relaxed atomic check."""
        _lib.lib.tc_metrics_enable(self._handle, 1 if on else 0)

    def metrics_enabled(self) -> bool:
        return bool(_lib.lib.tc_metrics_enabled(self._handle))

    def set_watchdog(self, threshold: Optional[float]) -> None:
        """Arm the straggler watchdog: any blocking wait (collective
        segment or p2p) that makes no progress for `threshold` seconds
        logs which peer/slot this rank is blocked on and records the
        stall in the metrics snapshot (metrics()["watchdog"]). None or 0
        disarms. Default comes from TPUCOLL_WATCHDOG_MS."""
        disarm = threshold is None or threshold <= 0
        ms_val = 0 if disarm else max(1, int(threshold * 1000))
        _lib.lib.tc_metrics_set_watchdog(self._handle, ms_val)

    def register(self, array: np.ndarray) -> UnboundBuffer:
        return UnboundBuffer(self, array)

    # ---- persistent collective plans (docs/design.md) ----

    def allreduce_plan(self, array: np.ndarray, op="sum",
                       algorithm: str = "auto", tag: int = 0,
                       timeout: Optional[float] = None,
                       wire: Optional[str] = None) -> CollectivePlan:
        """Build a persistent in-place allreduce over `array` (same
        semantics and arguments as :meth:`allreduce`, callable
        reductions excluded). ``plan()`` replays it: one foreign call,
        zero per-step allocations or registrations once warm — the
        hot path for training loops whose buffers are stable."""
        algorithm = self._resolve_wire(wire, algorithm)
        _check_array(array)
        if callable(op):
            raise Error("allreduce_plan does not support callable "
                        "reductions (build per-call instead)")
        args = (self._handle, _ptr(array), array.size, _dtype_code(array),
                ReduceOp.parse(op), self._ALGORITHMS[algorithm], tag,
                _timeout_ms(timeout))
        return CollectivePlan(self, _lib.lib.tc_allreduce_inplace, args,
                              (array,), array)

    def reduce_scatter_plan(self, array: np.ndarray,
                            recv_counts: Optional[Sequence[int]] = None,
                            op="sum", algorithm: str = "auto",
                            tag: int = 0,
                            timeout: Optional[float] = None,
                            wire: Optional[str] = None,
                            output: Optional[np.ndarray] = None
                            ) -> CollectivePlan:
        """Persistent reduce_scatter: like :meth:`reduce_scatter` but
        marshalled once; ``plan()`` reduces `array` and writes this
        rank's block into ``plan.result`` (the preallocated `output`
        when given)."""
        algorithm = self._resolve_rs_wire(wire, algorithm)
        _check_array(array)
        if callable(op):
            raise Error("reduce_scatter_plan does not support callable "
                        "reductions (build per-call instead)")
        recv_counts = _resolve_recv_counts(recv_counts, array, self.size)
        out = _resolve_output(output, array.dtype,
                              int(recv_counts[self.rank]),
                              "reduce_scatter")
        counts = _counts_arg(recv_counts)  # pinned by the plan
        args = (self._handle, _ptr(array), _ptr(out), counts,
                _dtype_code(array), ReduceOp.parse(op),
                self._RS_ALGORITHMS[algorithm], tag, _timeout_ms(timeout))
        return CollectivePlan(self, _lib.lib.tc_reduce_scatter, args,
                              (array, out, counts), out)

    def allgather_plan(self, array: np.ndarray, tag: int = 0,
                       timeout: Optional[float] = None,
                       output: Optional[np.ndarray] = None
                       ) -> CollectivePlan:
        """Persistent allgather: ``plan()`` gathers `array` from every
        rank into ``plan.result`` ((size, *shape), or the preallocated
        `output`)."""
        _check_array(array)
        out = _resolve_output(output, array.dtype, self.size * array.size,
                              "allgather")
        if output is None:
            out = out.reshape((self.size,) + array.shape)
        args = (self._handle, _ptr(array), _ptr(out), array.size,
                _dtype_code(array), self._HIER_ALGORITHMS["auto"], tag,
                _timeout_ms(timeout))
        return CollectivePlan(self, _lib.lib.tc_allgather, args,
                              (array, out), out)

    def plan_cache_size(self) -> int:
        """Entries currently in this context's persistent-plan LRU
        (TPUCOLL_PLAN_LRU capacity; TPUCOLL_PLAN_CACHE=0 disables). A
        cached plan pins the registered buffers + scratch of one
        repeated collective so its steady-state replay performs zero
        allocations and zero registrations — `metrics()` exposes
        plan_hits / plan_misses / plan_evictions / ubuf_creates."""
        return int(_lib.lib.tc_plan_cache_size(self._handle))

    def plan_cache_clear(self) -> None:
        """Drop every cached plan (A/B measurement; also happens
        automatically on close() and on tuning-table install). Safe
        whenever no collective is concurrently running here."""
        _lib.lib.tc_plan_cache_clear(self._handle)

    # ---- async collective engine (docs/async.md) ----

    def async_engine(self, lanes: Optional[int] = None,
                     tag_base: int = 0) -> AsyncEngine:
        """Create an :class:`AsyncEngine` over this context — a
        COLLECTIVE call (it forks lane sub-contexts over this one), so
        every rank must call it concurrently with the same `lanes`
        (default: TPUCOLL_ASYNC_LANES, else 2). The engine is shut down
        automatically by close()."""
        engine = AsyncEngine(self, lanes=lanes, tag_base=tag_base)
        self._engines = [r for r in self._engines if r() is not None]
        self._engines.append(weakref.ref(engine))
        return engine

    # ---- collectives ----

    # Schedules without an algorithm family of their own take "auto"
    # (flat) or "hier" (topology-aware composition over native splits;
    # degrades to flat on a flat topology — docs/topology.md).
    _HIER_ALGORITHMS = {"auto": 0, "hier": 1}

    def barrier(self, tag: int = 0, timeout: Optional[float] = None,
                algorithm: str = "auto") -> None:
        check(_lib.lib.tc_barrier(self._handle,
                                  self._HIER_ALGORITHMS[algorithm], tag,
                                  _timeout_ms(timeout)))

    def broadcast(self, array: np.ndarray, root: int = 0, tag: int = 0,
                  timeout: Optional[float] = None,
                  algorithm: str = "auto") -> np.ndarray:
        _check_array(array)
        check(_lib.lib.tc_broadcast(self._handle, _ptr(array), array.size,
                                    _dtype_code(array), root,
                                    self._HIER_ALGORITHMS[algorithm], tag,
                                    _timeout_ms(timeout)))
        return array

    _ALGORITHMS = {"auto": 0, "ring": 1, "halving_doubling": 2, "hd": 2,
                   "bcube": 3, "ring_bf16_wire": 4,
                   "recursive_doubling": 5, "rd": 5,
                   "hd_fold": 6, "hd_blocks": 7,
                   "ring_q8_wire": 8, "q8": 8,
                   "auto_lossy_wire": 9, "auto_lossy": 9,
                   "hier": 10,
                   "ring_q4_wire": 11, "q4": 11}
    _REDUCE_ALGORITHMS = {"auto": 0, "binomial": 1, "ring": 2}

    # wire= shorthand -> allreduce algorithm. The q8/bf16 codecs are
    # float32-sum-only opt-ins (docs/algorithms.md precision contract);
    # "lossy" keeps auto dispatch but allows the tuning table to elect a
    # wire codec (auto_lossy_wire).
    _WIRE_ALGORITHMS = {"q8": "ring_q8_wire", "q4": "ring_q4_wire",
                        "bf16": "ring_bf16_wire",
                        "lossy": "auto_lossy_wire"}

    @classmethod
    def _resolve_wire(cls, wire, algorithm):
        """Fold the allreduce wire= shorthand into the algorithm choice
        (conflicts compare RESOLVED algorithms, so aliases like "q8"
        agree with their canonical spelling)."""
        if wire is None:
            return algorithm
        mapped = cls._WIRE_ALGORITHMS.get(wire)
        if mapped is None:
            raise Error(f"wire= must be one of "
                        f"{sorted(cls._WIRE_ALGORITHMS)}, got {wire!r}")
        if (algorithm != "auto" and
                cls._ALGORITHMS.get(algorithm) != cls._ALGORITHMS[mapped]):
            raise Error(f"wire={wire!r} conflicts with "
                        f"algorithm={algorithm!r}")
        return mapped

    @classmethod
    def _resolve_rs_wire(cls, wire, algorithm):
        """reduce_scatter's wire= shorthand (q8 and q4 are its codecs) —
        the single validation both the blocking and async entries use."""
        if wire is None:
            return algorithm
        if wire not in ("q8", "q4"):
            raise Error(f"reduce_scatter wire= supports only 'q8' or "
                        f"'q4', got {wire!r}")
        mapped = f"ring_{wire}_wire"
        if (algorithm != "auto" and
                cls._RS_ALGORITHMS.get(algorithm) !=
                cls._RS_ALGORITHMS[mapped]):
            raise Error(f"wire={wire!r} conflicts with "
                        f"algorithm={algorithm!r}")
        return mapped

    def allreduce(self, array: np.ndarray, op="sum", algorithm: str = "auto",
                  tag: int = 0,
                  timeout: Optional[float] = None,
                  wire: Optional[str] = None) -> np.ndarray:
        """In-place allreduce of `array` across the group.

        algorithm: "auto" consults the installed tuning table first
        (gloo_tpu.tuning: measured per-deployment crossovers), falling
        back to the built-in thresholds (recursive doubling for tiny
        payloads, halving-doubling through ~1 MiB, ring beyond;
        crossovers TPUCOLL_ALLREDUCE_RD_MAX / TPUCOLL_ALLREDUCE_HD_MAX).
        Explicit choices: "ring", "halving_doubling" ("hd"),
        "recursive_doubling" ("rd"; non-power-of-2 groups take a
        pre/post fold), "hd_fold" / "hd_blocks" (the halving-doubling
        non-power-of-2 sub-variants), "bcube", "ring_bf16_wire",
        "ring_q8_wire" (int8 block-quantized wire, TPUCOLL_Q8_BLOCK), or
        "ring_q4_wire" (packed-nibble int4 wire, TPUCOLL_Q4_BLOCK —
        coarsest codec, tuner-elected only under auto dispatch).

        wire: opt-in wire compression shorthand — "q8" / "q4" / "bf16"
        force the matching codec (float32 sum only; all ranks still
        receive bit-identical results), "lossy" keeps auto dispatch but lets the
        installed tuning table elect a wire codec when one measures
        faster ("auto_lossy_wire"). See docs/algorithms.md for the
        precision contract (per-hop requantization error grows with the
        hop count).

        op may also be a callable `fn(acc, inp)` combining two numpy views
        in place into acc (see _wrap_reduce_fn for the contract).

        Error contract: the reduction runs IN PLACE, so if the call
        raises (timeout, peer failure, AEAD verification failure on an
        encrypted transport), the contents of `array` are UNDEFINED —
        arbitrary mixtures of local, partially-folded, and peer data.
        The context is poisoned; rebuild it and restore `array` from
        the application's own copy before retrying (docs/errors.md,
        "In-place collectives").
        """
        algorithm = self._resolve_wire(wire, algorithm)
        _check_array(array)
        if callable(op):
            cb, fnp, raise_pending = _wrap_reduce_fn(op, array.dtype)
            check(_lib.lib.tc_allreduce_fn(
                self._handle, _ptr(array), _ptr(array), array.size,
                _dtype_code(array), fnp, self._ALGORITHMS[algorithm], tag,
                _timeout_ms(timeout)))
            del cb
            raise_pending()
            return array
        # Zero-copy in-place entry: one stable pointer in, reduced in
        # place — repeated calls on the same array replay a cached plan
        # (zero allocations / registrations; see plan_cache_size()).
        check(_lib.lib.tc_allreduce_inplace(self._handle, _ptr(array),
                                            array.size, _dtype_code(array),
                                            ReduceOp.parse(op),
                                            self._ALGORITHMS[algorithm],
                                            tag, _timeout_ms(timeout)))
        return array

    def allreduce_multi(self, arrays, op="sum", algorithm: str = "auto",
                        tag: int = 0,
                        timeout: Optional[float] = None,
                        wire: Optional[str] = None):
        """Allreduce N local buffers together (the reference's multi-input
        form for one-process-per-host, N-accelerator setups: local
        reduction first, one network pass, result fanned to every
        buffer). In-place on all arrays; on error their contents are
        undefined, exactly as for allreduce(). wire: same opt-in wire
        compression shorthand as allreduce()."""
        algorithm = self._resolve_wire(wire, algorithm)
        arrays = [_check_array(a) for a in arrays]
        if not arrays:
            raise Error("allreduce_multi needs at least one array")
        if any(a.dtype != arrays[0].dtype or a.size != arrays[0].size
               for a in arrays):
            raise Error("allreduce_multi arrays must match in dtype and "
                        "size")
        ptrs = (ctypes.c_void_p * len(arrays))(
            *[a.ctypes.data for a in arrays])
        if callable(op):
            cb, fnp, raise_pending = _wrap_reduce_fn(op, arrays[0].dtype)
            check(_lib.lib.tc_allreduce_multi_fn(
                self._handle, ptrs, ptrs, len(arrays), arrays[0].size,
                _dtype_code(arrays[0]), fnp, self._ALGORITHMS[algorithm],
                tag, _timeout_ms(timeout)))
            del cb
            raise_pending()
            return arrays
        check(_lib.lib.tc_allreduce_multi(
            self._handle, ptrs, ptrs, len(arrays), arrays[0].size,
            _dtype_code(arrays[0]), ReduceOp.parse(op),
            self._ALGORITHMS[algorithm], tag, _timeout_ms(timeout)))
        return arrays

    def reduce(self, array: np.ndarray, root: int = 0, op="sum",
               output: Optional[np.ndarray] = None,
               algorithm: str = "auto", tag: int = 0,
               timeout: Optional[float] = None) -> Optional[np.ndarray]:
        """Reduce to `root`. Returns the result array on root, else None.

        algorithm: "auto" (the installed tuning table when present, else
        binomial tree for small payloads, pipelined ring reduce-scatter
        + chunk gather for large; fallback crossover via
        TPUCOLL_REDUCE_BINOMIAL_MAX), "binomial", or "ring".

        Error contract: if the call raises, the contents of `output` (on
        root) are undefined — the schedules fold partner contributions
        into it in place, including transport-fused receive-reduce that
        may have partially folded when an encrypted frame fails AEAD
        verification. Rebuild the context and retry from application
        state (docs/errors.md, "In-place collectives").
        """
        _check_array(array)
        algo = self._REDUCE_ALGORITHMS[algorithm]
        if self.rank == root:
            out = output if output is not None else np.empty_like(array)
            _check_array(out, "output")
        else:
            out = None
        if callable(op):
            cb, fnp, raise_pending = _wrap_reduce_fn(op, array.dtype)
            check(_lib.lib.tc_reduce_fn(
                self._handle, _ptr(array),
                _ptr(out) if out is not None else None, array.size,
                _dtype_code(array), fnp, root, algo, tag,
                _timeout_ms(timeout)))
            del cb
            raise_pending()
            return out
        check(_lib.lib.tc_reduce(self._handle, _ptr(array),
                                 _ptr(out) if out is not None else None,
                                 array.size, _dtype_code(array),
                                 ReduceOp.parse(op), root, algo, tag,
                                 _timeout_ms(timeout)))
        return out

    def gather(self, array: np.ndarray, root: int = 0, tag: int = 0,
               timeout: Optional[float] = None) -> Optional[np.ndarray]:
        """Gather equal-size arrays to root; returns (size, *shape) on root."""
        _check_array(array)
        if self.rank == root:
            out = np.empty((self.size,) + array.shape, dtype=array.dtype)
        else:
            out = None
        check(_lib.lib.tc_gather(self._handle, _ptr(array),
                                 _ptr(out) if out is not None else None,
                                 array.size, _dtype_code(array), root, tag,
                                 _timeout_ms(timeout)))
        return out

    def gatherv(self, array: np.ndarray, counts: Sequence[int],
                root: int = 0, tag: int = 0,
                timeout: Optional[float] = None) -> Optional[np.ndarray]:
        _check_array(array)
        assert array.size == counts[self.rank], "input size != counts[rank]"
        if self.rank == root:
            out = np.empty(int(sum(counts)), dtype=array.dtype)
        else:
            out = None
        check(_lib.lib.tc_gatherv(self._handle, _ptr(array),
                                  _ptr(out) if out is not None else None,
                                  _counts_arg(counts), _dtype_code(array),
                                  root, tag, _timeout_ms(timeout)))
        return out

    def scatter(self, array: Optional[np.ndarray], root: int = 0,
                output: Optional[np.ndarray] = None, tag: int = 0,
                timeout: Optional[float] = None) -> np.ndarray:
        """Scatter rows of `array` (on root, shape (size, ...)) to all ranks."""
        if self.rank == root:
            _check_array(array)
            assert array.shape[0] == self.size, "scatter input rows != size"
            chunk_shape = array.shape[1:]
            chunk = np.empty(chunk_shape, dtype=array.dtype) \
                if output is None else output
        else:
            assert output is not None, "non-root scatter needs output array"
            chunk = output
        _check_array(chunk, "output")
        check(_lib.lib.tc_scatter(
            self._handle, _ptr(array) if array is not None else None,
            _ptr(chunk), chunk.size, _dtype_code(chunk), root, tag,
            _timeout_ms(timeout)))
        return chunk

    def allgather(self, array: np.ndarray, tag: int = 0,
                  timeout: Optional[float] = None,
                  output: Optional[np.ndarray] = None,
                  algorithm: str = "auto") -> np.ndarray:
        """Allgather into a (size, *shape) array. Passing a preallocated
        `output` (same dtype, size * array.size elements) avoids the
        per-call allocation AND keeps the output pointer stable across
        steps, which is what lets the native plan cache replay the
        schedule with zero registrations (docs/design.md).
        algorithm="hier" composes intra-host allgather + leader-only
        exchange on a non-flat topology (docs/topology.md)."""
        _check_array(array)
        out = _resolve_output(output, array.dtype, self.size * array.size,
                              "allgather")
        if output is None:
            out = out.reshape((self.size,) + array.shape)
        check(_lib.lib.tc_allgather(self._handle, _ptr(array), _ptr(out),
                                    array.size, _dtype_code(array),
                                    self._HIER_ALGORITHMS[algorithm], tag,
                                    _timeout_ms(timeout)))
        return out

    def allgatherv(self, array: np.ndarray, counts: Sequence[int],
                   tag: int = 0,
                   timeout: Optional[float] = None) -> np.ndarray:
        _check_array(array)
        assert array.size == counts[self.rank], "input size != counts[rank]"
        out = np.empty(int(sum(counts)), dtype=array.dtype)
        check(_lib.lib.tc_allgatherv(self._handle, _ptr(array), _ptr(out),
                                     _counts_arg(counts),
                                     _dtype_code(array), tag,
                                     _timeout_ms(timeout)))
        return out

    def alltoall(self, array: np.ndarray, tag: int = 0,
                 timeout: Optional[float] = None) -> np.ndarray:
        """First axis of `array` must equal group size; returns same shape."""
        _check_array(array)
        assert array.shape[0] == self.size, "alltoall input rows != size"
        out = np.empty_like(array)
        check(_lib.lib.tc_alltoall(self._handle, _ptr(array), _ptr(out),
                                   array.size // self.size,
                                   _dtype_code(array), tag,
                                   _timeout_ms(timeout)))
        return out

    def alltoallv(self, array: np.ndarray, in_counts: Sequence[int],
                  out_counts: Sequence[int], tag: int = 0,
                  timeout: Optional[float] = None) -> np.ndarray:
        _check_array(array)
        assert array.size == sum(in_counts), "input size != sum(in_counts)"
        out = np.empty(int(sum(out_counts)), dtype=array.dtype)
        check(_lib.lib.tc_alltoallv(self._handle, _ptr(array),
                                    _counts_arg(in_counts), _ptr(out),
                                    _counts_arg(out_counts),
                                    _dtype_code(array), tag,
                                    _timeout_ms(timeout)))
        return out

    _RS_ALGORITHMS = {"auto": 0, "ring": 1, "halving_doubling": 2,
                      "hd": 2, "direct": 3, "ring_q8_wire": 4, "q8": 4,
                      "hier": 5,
                      "ring_q4_wire": 6, "q4": 6}

    def reduce_scatter(self, array: np.ndarray,
                       recv_counts: Optional[Sequence[int]] = None,
                       op="sum", algorithm: str = "auto", tag: int = 0,
                       timeout: Optional[float] = None,
                       wire: Optional[str] = None,
                       output: Optional[np.ndarray] = None) -> np.ndarray:
        """Reduce then scatter per-rank blocks.

        algorithm: "auto" (the installed tuning table when present, else
        recursive halving for small payloads, ring for bulk; fallback
        crossover via TPUCOLL_RS_HD_MAX=256K), "direct" (one network
        round, P-1 concurrent transfers — the untuned fallback only
        picks it when TPUCOLL_RS_DIRECT_MAX is raised from its default
        0; meant for real DCN, it loses on shared-core loopback, and a
        tuned table elects it from measurement), "halving_doubling"/
        "hd", "ring", "ring_q8_wire", or "ring_q4_wire" (block-quantized
        wire, float32 sum only — wire="q8" / wire="q4" are the
        shorthands; only the hops are quantized, each rank's result
        block is the float32 accumulator). On error the returned
        array's contents are undefined (in-place folds; docs/errors.md).

        output: optional preallocated result array (dtype of `array`,
        recv_counts[rank] elements) — avoids the per-call allocation and
        keeps the output pointer stable across steps so the native plan
        cache replays the schedule with zero registrations.
        """
        algorithm = self._resolve_rs_wire(wire, algorithm)
        _check_array(array)
        algo = self._RS_ALGORITHMS[algorithm]
        recv_counts = _resolve_recv_counts(recv_counts, array, self.size)
        out = _resolve_output(output, array.dtype,
                              int(recv_counts[self.rank]),
                              "reduce_scatter")
        if callable(op):
            cb, fnp, raise_pending = _wrap_reduce_fn(op, array.dtype)
            check(_lib.lib.tc_reduce_scatter_fn(
                self._handle, _ptr(array), _ptr(out),
                _counts_arg(recv_counts), _dtype_code(array), fnp, algo,
                tag, _timeout_ms(timeout)))
            del cb
            raise_pending()
            return out
        check(_lib.lib.tc_reduce_scatter(self._handle, _ptr(array),
                                         _ptr(out),
                                         _counts_arg(recv_counts),
                                         _dtype_code(array),
                                         ReduceOp.parse(op), algo, tag,
                                         _timeout_ms(timeout)))
        return out

    def reduce_scatter_inplace(self, array: np.ndarray,
                               recv_counts: Optional[Sequence[int]] = None,
                               op="sum", algorithm: str = "auto",
                               tag: int = 0,
                               timeout: Optional[float] = None,
                               wire: Optional[str] = None) -> np.ndarray:
        """Zero-copy reduce_scatter: this rank's reduced block
        (recv_counts[rank] elements) lands at the FRONT of `array` and
        the returned value is that view — no output allocation at all.
        The rest of `array` is unspecified afterwards. Same algorithm /
        wire / error contracts as :meth:`reduce_scatter`."""
        algorithm = self._resolve_rs_wire(wire, algorithm)
        _check_array(array)
        if callable(op):
            raise Error("reduce_scatter_inplace does not support callable "
                        "reductions (use reduce_scatter)")
        recv_counts = _resolve_recv_counts(recv_counts, array, self.size)
        check(_lib.lib.tc_reduce_scatter_inplace(
            self._handle, _ptr(array), _counts_arg(recv_counts),
            _dtype_code(array), ReduceOp.parse(op),
            self._RS_ALGORITHMS[algorithm], tag, _timeout_ms(timeout)))
        return array[:int(recv_counts[self.rank])]

    # ---- blocking p2p conveniences ----

    def send(self, array: np.ndarray, dst: int, slot: int,
             timeout: Optional[float] = None) -> None:
        buf = self.register(array)
        buf.send(dst, slot)
        buf.wait_send(timeout)

    def recv(self, array: np.ndarray, src, slot: int,
             timeout: Optional[float] = None) -> int:
        buf = self.register(array)
        buf.recv(src, slot)
        rank = buf.wait_recv(timeout)
        assert rank is not None
        return rank
