"""Demo model family used to validate the framework end-to-end.

The reference is a communications library, not a model zoo — these models
exist for the same reason gloo's examples and benchmark workloads do: to
prove the collective layer under a real training loop (DDP gradient sync,
tensor-parallel matmuls, pipeline-ish shifts)."""

from gloo_tpu.models.mlp import MLP
from gloo_tpu.models.transformer import Transformer, TransformerConfig

__all__ = ["MLP", "Transformer", "TransformerConfig"]
