"""Demo model family used to validate the framework end-to-end.

The reference is a communications library, not a model zoo — these models
exist for the same reason gloo's examples and benchmark workloads do: to
prove the collective layer under a real training loop (DDP gradient sync,
tensor-parallel matmuls, pipeline-ish shifts)."""

# Backfill renamed jax APIs (jax.shard_map, lax.axis_size, lax.pcast, ...)
# on old jax releases before any device-plane module touches them;
# no-op on modern jax. Kept out of the top-level gloo_tpu __init__ so
# host-plane-only processes never pay the jax import.
from gloo_tpu import _jaxcompat  # noqa: F401


from gloo_tpu.models.mlp import MLP
from gloo_tpu.models.transformer import Transformer, TransformerConfig

__all__ = ["MLP", "Transformer", "TransformerConfig"]
