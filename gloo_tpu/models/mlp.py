"""Minimal MLP — the SURVEY §7 M2 milestone model (data-parallel training
with gradient allreduce through the framework's own collectives)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


class MLP:
    def __init__(self, sizes):
        self.sizes = tuple(sizes)

    def init(self, key):
        params = []
        for i, (fan_in, fan_out) in enumerate(zip(self.sizes, self.sizes[1:])):
            key, sub = jax.random.split(key)
            scale = jnp.sqrt(2.0 / fan_in)
            params.append({
                "w": jax.random.normal(sub, (fan_in, fan_out),
                                       jnp.float32) * scale,
                "b": jnp.zeros((fan_out,), jnp.float32),
            })
        return params

    def apply(self, params, x):
        for i, layer in enumerate(params):
            x = x @ layer["w"] + layer["b"]
            if i + 1 < len(params):
                x = jax.nn.relu(x)
        return x

    def loss(self, params, batch):
        x, y = batch
        pred = self.apply(params, x)
        return jnp.mean((pred - y) ** 2)
