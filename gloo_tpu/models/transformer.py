"""Flagship demo model: a causal-LM transformer written TPU-first.

Design notes (why it looks the way it does):
- bfloat16 activations with float32 parameters/logits: keeps the MXU fed
  at its native precision while preserving loss accuracy;
- shapes are static and multiples of (8, 128)-friendly sizes so XLA tiles
  matmuls onto the MXU without padding;
- pure functions over a params pytree — trivially composable with
  shard_map/pjit shardings (dp/tp splits live in gloo_tpu.parallel, not in
  the model).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 512
    d_model: int = 256
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 1024
    max_seq_len: int = 128
    dtype: Any = jnp.bfloat16
    # Use the Pallas flash-attention kernel (gloo_tpu.ops) instead of the
    # materialized-scores path; requires seq divisible by its block sizes.
    use_flash_attention: bool = False
    # Grouped-query attention: number of shared k/v heads (None = n_heads,
    # i.e. classic multi-head; 1 = multi-query).
    n_kv_heads: int | None = None
    # Rotary position embeddings on q/k instead of the learned absolute
    # table (the long-context default: positions travel with the math,
    # so sequence-parallel shards rotate by their global offsets).
    use_rope: bool = False


class Transformer:
    def __init__(self, config: TransformerConfig):
        self.cfg = config

    # ---- init ----

    def init(self, key) -> Dict:
        cfg = self.cfg
        keys = jax.random.split(key, 2 + cfg.n_layers)

        def dense(k, fan_in, fan_out):
            scale = jnp.sqrt(1.0 / fan_in)
            return jax.random.normal(k, (fan_in, fan_out),
                                     jnp.float32) * scale

        h_kv = (cfg.n_kv_heads if cfg.n_kv_heads is not None
                else cfg.n_heads)
        if h_kv < 1 or cfg.n_heads % h_kv != 0:
            raise ValueError(
                f"n_heads {cfg.n_heads} must be a positive multiple of "
                f"n_kv_heads {h_kv}")
        kv_dim = (cfg.d_model // cfg.n_heads) * h_kv
        layers = []
        for i in range(cfg.n_layers):
            lk = jax.random.split(keys[2 + i], 6)
            layers.append({
                "ln1": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
                "ln2": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
                "wqkv": dense(lk[0], cfg.d_model,
                              cfg.d_model + 2 * kv_dim),
                "wo": dense(lk[1], cfg.d_model, cfg.d_model),
                "w_up": dense(lk[2], cfg.d_model, cfg.d_ff),
                "w_down": dense(lk[3], cfg.d_ff, cfg.d_model),
            })
        params = {
            "embed": jax.random.normal(
                keys[0], (cfg.vocab_size, cfg.d_model), jnp.float32) * 0.02,
            "ln_f": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
            "layers": layers,
        }
        if not cfg.use_rope:
            # Learned absolute table only when it is actually consumed —
            # a dead entry would still ride checkpoints/optimizer state.
            params["pos"] = jax.random.normal(
                keys[1], (cfg.max_seq_len, cfg.d_model), jnp.float32) * 0.02
        return params

    # ---- forward ----

    @staticmethod
    def _rmsnorm(x, scale):
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale

    def _project_qkv(self, layer, x, positions):
        """Single definition of the fused projection layout: slice offsets,
        head reshapes, GQA kv width, and RoPE — used by BOTH the full
        forward and the cached decode step so the two cannot drift (the
        incremental-vs-full parity test guards exactly this)."""
        cfg = self.cfg
        b, t, d = x.shape
        h = cfg.n_heads
        hd = d // h
        h_kv = cfg.n_kv_heads if cfg.n_kv_heads is not None else h
        kv_dim = hd * h_kv
        qkv = x @ layer["wqkv"].astype(x.dtype)
        q = qkv[..., :d].reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        k = qkv[..., d:d + kv_dim].reshape(b, t, h_kv, hd)
        k = k.transpose(0, 2, 1, 3)
        v = qkv[..., d + kv_dim:].reshape(b, t, h_kv, hd)
        v = v.transpose(0, 2, 1, 3)
        if cfg.use_rope:
            from gloo_tpu.ops.rope import apply_rope

            q = apply_rope(q, positions)
            k = apply_rope(k, positions)
        return q, k, v

    def _attention(self, layer, x):
        cfg = self.cfg
        b, t, d = x.shape
        h = cfg.n_heads
        hd = d // h
        h_kv = cfg.n_kv_heads if cfg.n_kv_heads is not None else h
        from gloo_tpu.ops.rope import rope_positions

        q, k, v = self._project_qkv(layer, x, rope_positions(t))
        if cfg.use_flash_attention and t % 8 == 0:
            from gloo_tpu.ops.attention import flash_attention

            # Adaptive tile defaults (BASELINE.md block sweep); CPU
            # backends only run Pallas through the interpreter.
            out = flash_attention(
                q, k, v, causal=True,
                interpret=jax.default_backend() == "cpu")
        else:
            if h_kv != h:
                k = jnp.repeat(k, h // h_kv, axis=1)
                v = jnp.repeat(v, h // h_kv, axis=1)
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32)
            scores = scores / jnp.sqrt(jnp.float32(hd))
            mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            out = jnp.einsum("bhqk,bhkd->bhqd", probs, v,
                             preferred_element_type=jnp.float32)
        out = out.transpose(0, 2, 1, 3).reshape(b, t, d).astype(x.dtype)
        return out @ layer["wo"].astype(x.dtype)

    def _mlp(self, layer, x):
        up = x @ layer["w_up"].astype(x.dtype)
        return jax.nn.gelu(up) @ layer["w_down"].astype(x.dtype)

    def apply(self, params, tokens):
        """tokens: (batch, seq) int32 -> logits (batch, seq, vocab) f32."""
        cfg = self.cfg
        t = tokens.shape[1]
        x = params["embed"][tokens]
        if not cfg.use_rope:
            x = x + params["pos"][:t]
        x = x.astype(cfg.dtype)
        for layer in params["layers"]:
            x = x + self._attention(layer, self._rmsnorm(
                x, layer["ln1"]["scale"].astype(x.dtype)))
            x = x + self._mlp(layer, self._rmsnorm(
                x, layer["ln2"]["scale"].astype(x.dtype)))
        x = self._rmsnorm(x, params["ln_f"]["scale"].astype(x.dtype))
        return (x.astype(jnp.float32) @ params["embed"].T)

    def loss(self, params, batch):
        """batch: (tokens, targets), each (batch, seq) int32."""
        tokens, targets = batch
        logits = self.apply(params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1).squeeze(-1)
        return jnp.mean(nll)

    # ---- incremental decoding (KV cache) ----

    def init_cache(self, batch: int, max_len: int | None = None):
        """Per-layer key/value cache for incremental decoding. GQA models
        cache only n_kv_heads — the cache shrinks by the group factor,
        which is the production reason to use GQA."""
        cfg = self.cfg
        max_len = max_len or cfg.max_seq_len
        if not cfg.use_rope and max_len > cfg.max_seq_len:
            # The learned positional table has max_seq_len rows; beyond it
            # dynamic_slice would silently clamp to the last row.
            raise ValueError(
                f"cache length {max_len} exceeds max_seq_len "
                f"{cfg.max_seq_len} (learned positions)")
        hd = cfg.d_model // cfg.n_heads
        h_kv = cfg.n_kv_heads if cfg.n_kv_heads is not None else cfg.n_heads
        zeros = jnp.zeros((batch, h_kv, max_len, hd), cfg.dtype)
        return {"k": [zeros] * cfg.n_layers, "v": [zeros] * cfg.n_layers,
                "len": jnp.zeros((), jnp.int32)}

    def _decode_attention(self, layer, x, k_cache, v_cache, pos):
        """One-token attention against the cache. x: (b, 1, d); pos: ()
        current position. Returns (out, new_k_cache, new_v_cache)."""
        cfg = self.cfg
        b, _, d = x.shape
        h = cfg.n_heads
        hd = d // h
        h_kv = cfg.n_kv_heads if cfg.n_kv_heads is not None else h
        max_len = k_cache.shape[2]

        q, k, v = self._project_qkv(layer, x, pos[None])
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, pos, 0))

        kx, vx = k_cache, v_cache
        if h_kv != h:
            kx = jnp.repeat(kx, h // h_kv, axis=1)
            vx = jnp.repeat(vx, h // h_kv, axis=1)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kx,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        valid = jax.lax.broadcasted_iota(jnp.int32, (1, max_len), 1) <= pos
        scores = jnp.where(valid[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vx,
                         preferred_element_type=jnp.float32)
        out = out.transpose(0, 2, 1, 3).reshape(b, 1, d).astype(x.dtype)
        return out @ layer["wo"].astype(x.dtype), k_cache, v_cache

    def _step_hidden(self, params, cache, token):
        """One cached step WITHOUT the unembedding: returns the final
        hidden row (b, 1, d) and the updated cache. Prefill uses this so
        prompt tokens never pay the O(vocab) output matmul."""
        cfg = self.cfg
        pos = cache["len"]
        x = params["embed"][token][:, None, :]
        if not cfg.use_rope:
            x = x + jax.lax.dynamic_slice_in_dim(params["pos"], pos, 1)
        x = x.astype(cfg.dtype)
        new_k, new_v = [], []
        for i, layer in enumerate(params["layers"]):
            attn, kc, vc = self._decode_attention(
                layer, self._rmsnorm(x, layer["ln1"]["scale"].astype(
                    x.dtype)), cache["k"][i], cache["v"][i], pos)
            new_k.append(kc)
            new_v.append(vc)
            x = x + attn
            x = x + self._mlp(layer, self._rmsnorm(
                x, layer["ln2"]["scale"].astype(x.dtype)))
        x = self._rmsnorm(x, params["ln_f"]["scale"].astype(x.dtype))
        return x, {"k": new_k, "v": new_v, "len": pos + 1}

    def decode_step(self, params, cache, token):
        """Feed one token (b,) int32 at cache['len']; returns (logits
        (b, vocab) f32, updated cache)."""
        x, cache = self._step_hidden(params, cache, token)
        return (x.astype(jnp.float32) @ params["embed"].T)[:, 0], cache

    def generate(self, params, prompt, max_new: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 key=None):
        """Decoding: prompt (b, t_p) int32 -> (b, t_p + max_new).
        temperature == 0 (default) is greedy; > 0 samples from the
        softmax at that temperature, optionally truncated to the top_k
        logits, using `key` (required when sampling). Prefill streams
        prompt tokens through the cached step (exactly the path new
        tokens use, minus the unembedding); generation runs under
        lax.scan, so the whole loop compiles to one program."""
        if max_new == 0:
            return prompt
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k is not None and top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if temperature > 0.0 and key is None:
            raise ValueError("sampling (temperature > 0) requires `key`")
        if key is None:
            key = jax.random.PRNGKey(0)  # unused on the greedy path
        b, t_p = prompt.shape
        cache = self.init_cache(b, t_p + max_new)

        def pick(logits, key):
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1)
            logits = logits / temperature
            if top_k is not None:
                kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1)

        def prefill(cache, tok):
            _, cache = self._step_hidden(params, cache, tok)
            return cache, None

        # All but the last prompt token only warm the cache; the last one
        # produces the first generated token.
        cache, _ = jax.lax.scan(prefill, cache, prompt[:, :-1].T)
        logits, cache = self.decode_step(params, cache, prompt[:, -1])
        key, sub = jax.random.split(key)
        next_tok = pick(logits, sub)

        def step(carry, _):
            cache, tok, key = carry
            logits, cache = self.decode_step(params, cache, tok)
            key, sub = jax.random.split(key)
            new = pick(logits, sub)
            return (cache, new, key), new

        (_, _, _), later = jax.lax.scan(step, (cache, next_tok, key), None,
                                        length=max_new - 1)
        toks = jnp.concatenate([next_tok[:, None], later.T], axis=1)
        return jnp.concatenate([prompt, toks], axis=1)
