"""TpuProcessGroup: array-level device-plane process group.

Mirrors the host `gloo_tpu.Context` surface (one "rank" per device along a
mesh axis, same collective names and semantics) but every call is a jitted
XLA program over sharded jax arrays. This is the device-plane counterpart
of the reference's CUDA algorithm classes (gloo/cuda_allreduce_*.cc):
their ctor-time setup ≙ XLA compilation (cached per shape/dtype/op), their
run() ≙ executing the compiled program over ICI.

Array convention: the leading axis of every operand is the rank axis — a
global array of shape (P, ...) whose row i lives on mesh position i.
`shard(...)`/`unshard(...)` convert between host numpy and this layout.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gloo_tpu.tpu import spmd


class TpuProcessGroup:
    def __init__(self, mesh: Mesh, axis: Optional[str] = None):
        if axis is None:
            if len(mesh.axis_names) != 1:
                raise ValueError("axis required for multi-axis mesh")
            axis = mesh.axis_names[0]
        self.mesh = mesh
        self.axis = axis
        self.size = mesh.shape[axis]
        self._row_sharding = NamedSharding(mesh, P(self.axis))
        # Jitted shard_map callables keyed on (method, static args). Reusing
        # the same callable object across calls is what lets jax.jit's own
        # (shape, dtype) cache hit: a fresh lambda per call would re-trace
        # and re-compile every time. Bounded LRU so per-call-varying keys
        # (rotating send_recv perms, shifting roots) can't grow it forever.
        self._compiled = OrderedDict()
        self._compiled_max = 128

    # ---- data movement helpers ----

    def shard(self, array) -> jax.Array:
        """Place a (P, ...) host array so row i lives on device i."""
        array = jnp.asarray(array)
        if array.shape[0] != self.size:
            raise ValueError(
                f"leading axis {array.shape[0]} != group size {self.size}")
        return jax.device_put(array, self._row_sharding)

    def unshard(self, array) -> np.ndarray:
        return np.asarray(jax.device_get(array))

    def _smap(self, key, fn, *args):
        """Run the cached jitted shard_map program for `key`.

        On a cache hit `fn` is ignored and the stored jitted callable runs,
        so repeat calls with the same static args hit jax.jit's
        (shape, dtype) cache instead of re-tracing. `fn` must therefore be
        fully determined by `key`.
        """
        compiled = self._compiled.get(key)
        if compiled is None:
            in_specs = P(self.axis) if args else ()
            compiled = jax.jit(jax.shard_map(
                fn, mesh=self.mesh, in_specs=in_specs,
                out_specs=P(self.axis)))
            self._compiled[key] = compiled
            if len(self._compiled) > self._compiled_max:
                self._compiled.popitem(last=False)
        else:
            self._compiled.move_to_end(key)
        return compiled(*args)

    # ---- collectives (each rank's operand is its row) ----

    def allreduce(self, x, op: str = "sum"):
        return self._smap(
            ("allreduce", op),
            lambda s: spmd.allreduce(s, self.axis, op), x)

    def broadcast(self, x, root: int = 0):
        return self._smap(
            ("broadcast", root),
            lambda s: spmd.broadcast(s, self.axis, root), x)

    def reduce(self, x, root: int = 0, op: str = "sum"):
        return self._smap(
            ("reduce", root, op),
            lambda s: spmd.reduce(s, self.axis, root, op), x)

    def allgather(self, x):
        # Result is (P, P, ...): row i is rank i's copy of the gathered
        # buffer (identical rows, matching the host API where every rank's
        # output holds all inputs).
        return self._smap(
            ("allgather",),
            lambda s: spmd.allgather(s[0], self.axis, gather_axis=0,
                                     tiled=False)[None], x)

    def reduce_scatter(self, x, op: str = "sum"):
        """x rows are (P*k, ...); rank i keeps slice i of the sum."""
        return self._smap(
            ("reduce_scatter", op),
            lambda s: spmd.reduce_scatter(s[0], self.axis, op,
                                          scatter_axis=0)[None], x)

    def alltoall(self, x):
        """Row i holds P blocks along axis 1; block j goes to rank j."""
        return self._smap(
            ("alltoall",),
            lambda s: spmd.alltoall(s[0], self.axis, split_axis=0,
                                    concat_axis=0)[None], x)

    def scatter(self, x, root: int = 0):
        return self._smap(
            ("scatter", root),
            lambda s: spmd.scatter(s[0], self.axis, root,
                                   scatter_axis=0)[None], x)

    def send_recv(self, x, perm: Sequence[tuple]):
        # Materialize once: perm may be a generator, and the traced fn must
        # see exactly what the cache key was built from.
        perm_key = tuple((int(a), int(b)) for a, b in perm)
        return self._smap(
            ("send_recv", perm_key),
            lambda s: spmd.ppermute(s, self.axis, perm_key), x)

    def shift(self, x, offset: int = 1):
        return self._smap(
            ("shift", offset),
            lambda s: spmd.shift(s, self.axis, offset), x)

    def barrier(self):
        out = self._smap(
            ("barrier",),
            lambda: spmd.barrier(self.axis)[None])
        jax.block_until_ready(out)
