"""Hierarchical DCN x ICI collectives: the two planes composed.

A jax.distributed global mesh covers pods whose every host runs the same
XLA program. The reference also serves the OTHER deployment — independent
per-host processes whose accelerators cannot form one compiled program
(elastic groups, heterogeneous slices, DCN-only clusters) — by staging
device buffers through the host and running the CPU-side schedule across
machines (gloo/cuda_collectives_host.h CudaLocalHostReduce -> host ring ->
CudaLocalHostBroadcast; workspace split gloo/cuda_workspace.h:17-27).

HierarchicalGroup is that capability TPU-first: per-device partials are
reduced on-accelerator (one jitted tree-reduce; the adds never touch the
host), exactly one device->host transfer per collective crosses PCIe, the
cross-host hop rides the C++ host plane (TCP / encrypted / shm payload
rings — two processes on one machine exchange through shared memory
automatically), and the result returns to the local devices replicated.
Every host-plane property carries over: timeouts, abort, fast peer-death
detection, generation-based recovery (resilience.py), checkpoint stores.

Scale note (the scaling-book hierarchy argument): with L local chips and
H hosts, local reduction traffic stays on ICI/PCIe and DCN moves
2(H-1)/H of the payload once per HOST, independent of L — staging keeps
the slow fabric's traffic from multiplying with local chip count.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class HierarchicalGroup:
    """Cross-host collectives over (local devices) x (host Context).

    ctx: a connected host-plane `gloo_tpu.Context`, one rank per host
    process. devices: the process-local accelerators (default
    jax.local_devices()).

    Operand convention (mirrors the reference's CUDA algorithms, which
    take one pointer per local GPU): a collective accepts either
      - a list of per-device jax arrays (same shape/dtype) — the local
        partials, reduced on-accelerator first; or
      - a single array (numpy, single-device, or replicated) — one local
        contribution per host.
    Data-sharded single arrays are rejected: slices of one tensor are not
    partials, and silently summing them would corrupt data.
    """

    def __init__(self, ctx, devices: Optional[Sequence] = None,
                 tag: int = 0x51):
        import jax

        self.ctx = ctx
        self.devices = list(devices) if devices is not None \
            else jax.local_devices()
        self.tag = tag
        self._jit_cache = {}
        # Native topology plane: when the host context spans several
        # processes per machine (one per accelerator is the common
        # deployment), its collectives route through the native
        # hierarchical schedules — intra-host shm plane, leader-only
        # DCN exchange — built on Context.split sub-communicators. On a
        # flat topology (one process per host, or a single host) the
        # "hier" request degrades to the flat schedules natively, so
        # this is always safe to pass.
        try:
            self._hier_algo = ("hier" if ctx.topology().get("non_flat")
                               else "auto")
        except Exception:  # pragma: no cover - not connected / mock ctx
            self._hier_algo = "auto"
        self._local_ctx = None
        self._leader_ctx = None
        self._planes_built = False

    # ---- native split planes (no ad-hoc per-group store bootstrap) ----

    def _ensure_planes(self):
        """Build the intra-host / leader sub-communicators via native
        Context.split — a collective over the host context, so every
        process must reach the first accessor together. No side stores:
        the split's color exchange and subset bootstrap ride the
        context's own rendezvous namespace (docs/topology.md)."""
        if not self._planes_built:
            self._local_ctx = self.ctx.split_by_host(tag=0x51C0)
            topo = self.ctx.topology()
            self._leader_ctx = self.ctx.split(
                0 if topo["is_leader"] else -1, key=self.ctx.rank,
                tag=0x51C4)
            self._planes_built = True
        return self._local_ctx, self._leader_ctx

    def local_group(self):
        """Native intra-host communicator (co-hosted processes; shm
        plane). A collective on first use — call on every rank."""
        return self._ensure_planes()[0]

    def leader_group(self):
        """Native leader communicator (one process per host), or None on
        non-leader processes. A collective on first use."""
        return self._ensure_planes()[1]

    # ---- local (intra-host) stage ----

    def _reduce_list(self, xs, op: str) -> np.ndarray:
        """Jitted tree-reduce of per-device partials on device 0; one D2H
        transfer of the result."""
        import jax

        key = ("reduce", op, len(xs), xs[0].shape, str(xs[0].dtype))
        fn = self._jit_cache.get(key)
        if fn is None:
            import jax.numpy as jnp

            combine = {"sum": jnp.add, "prod": jnp.multiply,
                       "max": jnp.maximum, "min": jnp.minimum}[op]

            def reduce_parts(*parts):
                acc = parts[0]
                for p in parts[1:]:
                    acc = combine(acc, p)
                return acc

            fn = jax.jit(reduce_parts)
            self._jit_cache[key] = fn
        dev0 = self.devices[0]
        parts = [jax.device_put(x, dev0) for x in xs]
        # copy=True: on CPU backends np.asarray can alias the device
        # buffer, and the host collectives mutate their operand in place.
        return np.array(fn(*parts), copy=True)

    def _local_value(self, x, op: str = "sum") -> np.ndarray:
        """One host copy of this process's contribution."""
        import jax

        if isinstance(x, (list, tuple)):
            if len(x) == 0:
                raise ValueError("empty input list")
            return self._reduce_list(list(x), op)
        if isinstance(x, np.ndarray):
            # Copy like every other input kind: the host collectives
            # reduce in place, and the caller's array must not be
            # silently overwritten with intermediate values.
            return np.array(x, copy=True)
        if not isinstance(x, jax.Array):
            return np.array(np.asarray(x), copy=True)
        shards = x.addressable_shards
        if len(shards) > 1:
            first = shards[0].index
            if any(s.index != first for s in shards[1:]):
                raise ValueError(
                    "x is data-sharded over local devices; hierarchical "
                    "collectives expect per-device PARTIALS. Pass a list "
                    "of per-device arrays, or reduce locally first (e.g. "
                    "shard_map psum).")
        # copy=True: see _reduce_list — never hand the in-place host
        # collectives a view of device memory.
        return np.array(x, copy=True)

    def _put_back(self, host: np.ndarray, like):
        """numpy in -> numpy out; device in -> replicated over the local
        devices (every chip sees the reduced value, the reference's
        local-broadcast stage)."""
        import jax

        if isinstance(like, (list, tuple)):
            return [jax.device_put(host, d) for d in self.devices]
        if isinstance(like, np.ndarray) or not isinstance(like, jax.Array):
            return host
        if len(self.devices) == 1:
            return jax.device_put(host, self.devices[0])
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        mesh = Mesh(np.asarray(self.devices), ("local",))
        return jax.device_put(host, NamedSharding(mesh, PartitionSpec()))

    # ---- hierarchical collectives ----

    def allreduce(self, x, op: str = "sum"):
        """Local on-accelerator reduce -> host-plane allreduce over DCN ->
        replicate back to local devices. Returns x's structure: list in,
        per-device list out; array in, replicated array out. On a
        multi-process-per-host topology the host hop runs the native
        hierarchical schedule (shm plane intra-host, leaders-only DCN)."""
        host = self._local_value(x, op)
        flat = np.ascontiguousarray(host.reshape(-1))
        self.ctx.allreduce(flat, op=op, tag=self.tag,
                           algorithm=self._hier_algo)
        return self._put_back(flat.reshape(host.shape), x)

    def mean(self, x):
        """allreduce(sum) / total contribution count (hosts x local
        partials, allgathered so uneven local counts stay correct)."""
        nlocal = len(x) if isinstance(x, (list, tuple)) else 1
        counts = np.array([nlocal], dtype=np.int64)
        total = int(self.ctx.allgather(counts, tag=self.tag + 1).sum())
        out = self.allreduce(x, op="sum")
        scale = 1.0 / total

        def _scale(a):
            return (a * scale).astype(np.asarray(a).dtype) \
                if isinstance(a, np.ndarray) else a * scale
        if isinstance(out, list):
            return [_scale(a) for a in out]
        return _scale(out)

    def broadcast(self, x, root: int = 0):
        """Root host's value to every host's local devices."""
        host = self._local_value(x)
        flat = np.ascontiguousarray(host.reshape(-1))
        self.ctx.broadcast(flat, root=root, tag=self.tag,
                           algorithm=self._hier_algo)
        return self._put_back(flat.reshape(host.shape), x)

    def allgather(self, x) -> np.ndarray:
        """Stack each host's (locally reduced) contribution: (H, ...) on
        every host."""
        host = self._local_value(x)
        flat = np.ascontiguousarray(host.reshape(-1))
        out = self.ctx.allgather(flat, tag=self.tag,
                                 algorithm=self._hier_algo)
        return out.reshape((self.ctx.size,) + host.shape)

    def barrier(self) -> None:
        self.ctx.barrier(tag=self.tag, algorithm=self._hier_algo)


def make_hierarchical_ddp(loss_fn, optimizer, group: HierarchicalGroup,
                          mesh=None, axis: str = "local"):
    """Two-level DDP: the local device mesh averages gradients over ICI
    inside one jitted step; the host plane then averages the per-host
    means across machines (the reference's role as PyTorch's ProcessGroup
    backend, SURVEY.md §2.10). Returns step(params, opt_state, batch) ->
    (params, opt_state, loss); batch's leading axis shards over the local
    mesh when one exists.
    """
    import jax
    import optax

    if mesh is None and len(group.devices) > 1:
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(group.devices), (axis,))

    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from gloo_tpu.tpu import spmd
        local_axis = mesh.axis_names[0]

        def local_grads(params, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            # Replicated params => AD already psum'd grads across the
            # axis; divide for the mean (same reasoning as parallel/ddp).
            n = spmd.size(local_axis)
            grads = jax.tree.map(lambda g: g / n, grads)
            return spmd.mean(loss, local_axis), grads

        grads_fn = jax.jit(jax.shard_map(
            local_grads, mesh=mesh,
            in_specs=(P(), P(local_axis)), out_specs=(P(), P())))
    else:
        grads_fn = jax.jit(jax.value_and_grad(loss_fn))

    def _apply(params, opt_state, grads):
        updates, new_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    apply_fn = jax.jit(_apply)

    def step(params, opt_state, batch):
        loss, grads = grads_fn(params, batch)
        if group.ctx.size > 1:
            # Cross-host mean over DCN: one flat f32 buffer per step so
            # the transport sees a single large payload (shm/TCP
            # pipelining beats many small messages).
            leaves, treedef = jax.tree.flatten(grads)
            host_leaves = [np.asarray(l) for l in leaves]
            if host_leaves:
                flat = np.concatenate(
                    [l.reshape(-1).astype(np.float32)
                     for l in host_leaves])
                group.ctx.allreduce(flat, tag=group.tag,
                                    algorithm=group._hier_algo)
                flat /= group.ctx.size
                out, off = [], 0
                for l in host_leaves:
                    out.append(flat[off:off + l.size].reshape(l.shape)
                               .astype(l.dtype))
                    off += l.size
                grads = jax.tree.unflatten(treedef, out)
        params, opt_state = apply_fn(params, opt_state, grads)
        return params, opt_state, loss

    return step
