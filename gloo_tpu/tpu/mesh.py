"""Device mesh construction helpers.

The mesh is the device plane's "context": where the host plane bootstraps a
full mesh of TCP pairs (rendezvous/context.cc analog), the device plane
arranges chips into a named `jax.sharding.Mesh` whose axes carry the
parallelism meaning (dp/tp/pp/sp/ep). XLA then lowers collectives over an
axis to ICI transfers.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes: Optional[Mapping[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh over `devices` (default: all local devices).

    `axes` maps axis name -> size; one axis size may be -1 to absorb the
    remaining devices (like a reshape). Default: a single "data" axis over
    everything.
    """
    devs = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"data": len(devs)}
    names = list(axes.keys())
    sizes = list(axes.values())
    n_free = sizes.count(-1)
    if n_free > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if n_free == 1:
        if len(devs) % known != 0:
            raise ValueError(
                f"{len(devs)} devices not divisible by fixed axes {axes}")
        sizes[sizes.index(-1)] = len(devs) // known
    total = int(np.prod(sizes))
    if total != len(devs):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {len(devs)}")
    grid = np.asarray(devs, dtype=object).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))
