"""Collective primitives for use inside shard_map / pjit SPMD code.

These mirror the host collective suite (csrc/tpucoll/collectives/) but
operate on the per-device shard inside an SPMD region, compiling to XLA
collectives that ride ICI (reference analog: the NCCL op wrappers in
gloo/nccl/nccl.h — here the "wrapper" is XLA itself, which also fuses and
schedules them).

All functions take `axis`: the mesh axis name the collective runs over.
`op` accepts "sum" | "product" | "min" | "max".

Every collective runs under a `jax.named_scope("gloo_tpu.<op>")`: the
scope lands in XLA op metadata, so a jax profiler trace of the device
plane shows which gloo_tpu collective produced each ICI op — and lines
up with the host plane's tracer spans and metrics (same op names) in one
Perfetto investigation (docs/observability.md). Named scopes cost
nothing at runtime; they only annotate the HLO.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

Axis = Union[str, Sequence[str]]


def rank(axis: Axis):
    """Position of this shard along `axis` (the device-plane 'rank')."""
    return lax.axis_index(axis)


def size(axis: Axis) -> int:
    return lax.axis_size(axis)


def allreduce(x, axis: Axis, op: str = "sum"):
    with jax.named_scope("gloo_tpu.allreduce"):
        if op == "sum":
            return lax.psum(x, axis)
        if op == "max":
            return lax.pmax(x, axis)
        if op == "min":
            return lax.pmin(x, axis)
        if op in ("product", "prod"):
            # No pprod primitive: gather and reduce locally. XLA turns the
            # all_gather + reduce into an efficient fused loop.
            return jnp.prod(lax.all_gather(x, axis), axis=0)
    raise ValueError(f"unknown op: {op}")


def mean(x, axis: Axis):
    with jax.named_scope("gloo_tpu.allreduce"):
        return lax.pmean(x, axis)


def reduce_scatter(x, axis: Axis, op: str = "sum", scatter_axis: int = 0):
    """Reduce across `axis` and leave each shard with its 1/P slice."""
    with jax.named_scope("gloo_tpu.reduce_scatter"):
        if op != "sum":
            # psum_scatter is sum-only; emulate others via allreduce +
            # slice.
            full = allreduce(x, axis, op)
            p = size(axis)
            idx = rank(axis)
            chunk = x.shape[scatter_axis] // p
            return lax.dynamic_slice_in_dim(full, idx * chunk, chunk,
                                            axis=scatter_axis)
        return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                                tiled=True)


def allgather(x, axis: Axis, gather_axis: int = 0, tiled: bool = True):
    """Concatenate every shard's x along `gather_axis`."""
    with jax.named_scope("gloo_tpu.allgather"):
        return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def alltoall(x, axis: Axis, split_axis: int = 0, concat_axis: int = 0):
    """Scatter `split_axis` across the group and gather along `concat_axis`."""
    with jax.named_scope("gloo_tpu.alltoall"):
        return lax.all_to_all(x, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def broadcast(x, axis: Axis, root: int = 0):
    """Every shard receives the root shard's value."""
    with jax.named_scope("gloo_tpu.broadcast"):
        idx = rank(axis)
        zeros = jnp.zeros_like(x)
        return lax.psum(jnp.where(idx == root, x, zeros), axis)


def reduce(x, axis: Axis, root: int = 0, op: str = "sum"):
    """Full reduction; non-root shards receive zeros (XLA has no rooted
    reduce — the collective cost is the same on ICI, matching psum)."""
    full = allreduce(x, axis, op)
    idx = rank(axis)
    return jnp.where(idx == root, full, jnp.zeros_like(full))


def scatter(x, axis: Axis, root: int = 0, scatter_axis: int = 0):
    """Root's x is split into P slices; shard i receives slice i."""
    rooted = broadcast(x, axis, root)
    p = size(axis)
    idx = rank(axis)
    chunk = x.shape[scatter_axis] // p
    return lax.dynamic_slice_in_dim(rooted, idx * chunk, chunk,
                                    axis=scatter_axis)


def ppermute(x, axis: Axis, perm: Sequence[tuple]):
    """Point-to-point shift: pairs of (source_rank, dest_rank)."""
    with jax.named_scope("gloo_tpu.ppermute"):
        return lax.ppermute(x, axis, perm=perm)


def shift(x, axis: Axis, offset: int = 1, wrap: bool = True):
    """Send each shard to rank + offset (ring neighbor exchange); the
    building block for pipeline stages and ring attention."""
    p = size(axis)
    if wrap:
        perm = [(i, (i + offset) % p) for i in range(p)]
    else:
        perm = [(i, i + offset) for i in range(p)
                if 0 <= i + offset < p]
    with jax.named_scope("gloo_tpu.ppermute"):
        return lax.ppermute(x, axis, perm=perm)


def barrier(axis: Axis):
    """Synchronization point: returns a token-like scalar whose value
    depends on every participant (XLA cannot elide or reorder it past uses
    that consume the result)."""
    with jax.named_scope("gloo_tpu.barrier"):
        return lax.psum(jnp.ones((), dtype=jnp.int32), axis)
