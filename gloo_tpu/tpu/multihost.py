"""Multi-host device-plane bootstrap.

On a multi-host TPU pod the device plane needs jax.distributed so all
hosts' chips form one global mesh; the host plane needs a connected
Context for host-side collectives and control traffic. This module wires
both from one set of coordinates, with the TcpStore serving double duty as
the process-wide rendezvous:

    ctx, mesh = init_multihost(rank, size, "host0:29500",
                               mesh_axes={"data": -1})

After it returns: jax.devices() spans the pod, `mesh` is a global mesh,
and `ctx` is the host-plane process group (one rank per host process).
"""

from __future__ import annotations

from typing import Mapping, Optional, Tuple


def init_multihost(rank: int, size: int, store_address: str,
                   mesh_axes: Optional[Mapping[str, int]] = None,
                   timeout: float = 120.0,
                   device_hostname: Optional[str] = None):
    """Initialize both planes. `store_address` is host:port; rank 0 hosts
    the TcpStoreServer there. `device_hostname` is the DCN-reachable name
    this process advertises for host-plane traffic (default: the machine
    hostname)."""
    import socket

    import jax

    import gloo_tpu
    from gloo_tpu.tpu.mesh import make_mesh

    host, port_str = store_address.rsplit(":", 1)
    port = int(port_str)

    server = None
    if rank == 0:
        server = gloo_tpu.TcpStoreServer("0.0.0.0", port)
    store = gloo_tpu.TcpStore(host, port)

    # Host plane: full-mesh process group over DCN.
    if device_hostname is None:
        device_hostname = socket.gethostname()
    ctx = gloo_tpu.Context(rank, size, timeout=timeout)
    ctx.connect_full_mesh(store, gloo_tpu.Device(hostname=device_hostname))
    ctx._store_server = server  # pin the server to the context's lifetime

    # Device plane: jax.distributed makes every host's chips visible as one
    # global device set. The coordinator rides the same host as the store.
    if size > 1:
        coordinator = f"{host}:{port + 1}"
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=size, process_id=rank)

    mesh = make_mesh(mesh_axes)
    return ctx, mesh
