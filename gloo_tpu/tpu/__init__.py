"""Device data plane: collectives over jax arrays sharded across a TPU mesh.

This package is the TPU-native analog of the reference's accelerator layer
(/root/reference/gloo/cuda*.{h,cu}, gloo/nccl/): where gloo moves GPU buffers
with NCCL ops and CUDA-aware ring schedules, gloo_tpu moves sharded jax
arrays with XLA collectives compiled over the ICI mesh (`spmd` module —
psum/all_gather/ppermute lowered by XLA) and with hand-written Pallas ring
kernels (`gloo_tpu.ops.pallas_ring`) for custom schedules.

Two usage levels:
- `gloo_tpu.tpu.spmd`: collective primitives used *inside* your own
  shard_map/pjit code (the moral equivalent of calling nccl ops on a
  stream);
- `TpuProcessGroup`: an array-level process-group API mirroring the host
  `gloo_tpu.Context` surface, where "rank" = mesh position along one axis
  and every call is a compiled XLA program.
"""

# Backfill renamed jax APIs (jax.shard_map, lax.axis_size, lax.pcast, ...)
# on old jax releases before any device-plane module touches them;
# no-op on modern jax. Kept out of the top-level gloo_tpu __init__ so
# host-plane-only processes never pay the jax import.
from gloo_tpu import _jaxcompat  # noqa: F401


from gloo_tpu.tpu import spmd
from gloo_tpu.tpu.group import TpuProcessGroup
from gloo_tpu.tpu.hierarchical import (HierarchicalGroup,
                                       make_hierarchical_ddp)
from gloo_tpu.tpu.mesh import make_mesh
from gloo_tpu.tpu.multihost import init_multihost

__all__ = ["HierarchicalGroup", "TpuProcessGroup", "init_multihost",
           "make_hierarchical_ddp", "make_mesh", "spmd"]
