"""Pallas ring allreduce over ICI.

The same bandwidth-optimal schedule as the host ring (csrc/tpucoll/
collectives/collectives_ring.cc) and the reference's CUDA ring
(gloo/cuda_allreduce_ring.cc), but executed by the TPU's inter-chip DMA
engines: reduce-scatter phase ships chunks around the ring and accumulates
on the VPU, allgather phase writes finished chunks straight into each
neighbor's output buffer (one-sided, like the ibverbs RDMA_WRITE path in
the reference — gloo/transport/ibverbs/pair.cc:359-381).

Flow control: the reduce-scatter phase double-buffers its communication
slots, and a receiver acks slot consumption to its left neighbor with a
remote semaphore signal before the slot may be reused — without the ack, a
fast sender two steps ahead could overwrite an unconsumed slot. The
allgather phase needs no acks because every step writes a distinct chunk.

Two variants: `ring_allreduce` keeps everything VMEM-resident (lowest
latency, shard + 2 comm slots must fit in ~16 MB VMEM);
`ring_allreduce_hbm` keeps the ring buffers in HBM and streams the
reduction through VMEM tiles, scaling to arbitrarily large shards.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _peer_logical_id(axis_name, mesh_axes, r):
    """Flattened LOGICAL device id of ring-index r along axis_name.

    On a single-axis mesh the ring index IS the logical id. On a multi-axis
    mesh the logical id is the row-major flattened coordinate over
    `mesh_axes` (the mesh's full axis order), so a peer along one axis
    differs by that axis's stride.
    """
    my = lax.axis_index(axis_name)
    if mesh_axes is None or tuple(mesh_axes) == (axis_name,):
        return r
    axes = tuple(mesh_axes)
    my_flat = lax.axis_index(axes)
    idx = axes.index(axis_name)
    stride = 1
    for a in axes[idx + 1:]:
        stride = stride * lax.axis_size(a)
    return my_flat + (r - my) * stride


def _ring_neighbors(axis_name, mesh_axes):
    """(me, right, left) flattened LOGICAL ids — see _peer_logical_id."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    return (_peer_logical_id(axis_name, mesh_axes, my),
            _peer_logical_id(axis_name, mesh_axes, lax.rem(my + 1, n)),
            _peer_logical_id(axis_name, mesh_axes, lax.rem(my - 1 + n, n)))


def _ring_allreduce_kernel(x_ref, o_ref, comm_ref, rs_send, rs_recv,
                           ack_sem, ag_send, ag_recv, *, axis_name: str,
                           num_devices: int, chunk_rows: int):
    n = num_devices
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my - 1 + n, n)

    o_ref[...] = x_ref[...]

    def chunk_slice(idx):
        return pl.ds(idx * chunk_rows, chunk_rows)

    # Neighbors may enter the kernel at different times; do not let anyone
    # start writing into a peer that has not allocated its buffers yet.
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    # --- phase 1: reduce-scatter ---
    # Send/recv decoupled (see the HBM kernel): wait only the incoming
    # chunk before reducing — the outgoing transfer overlaps the VPU add —
    # and drain send completions two steps late at semaphore-slot reuse.
    def rs_rdma(s):
        send_chunk = lax.rem(my - s + n, n)
        slot = lax.rem(s, 2)
        return pltpu.make_async_remote_copy(
            src_ref=o_ref.at[chunk_slice(send_chunk)],
            dst_ref=comm_ref.at[slot],
            send_sem=rs_send.at[slot],
            recv_sem=rs_recv.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def rs_step(s, _):
        recv_chunk = lax.rem(my - s - 1 + n, n)
        slot = lax.rem(s, 2)

        # Reuse of a comm slot (step s >= 2) requires the right neighbor to
        # have consumed what we previously parked there, and our own s-2
        # send to have fully left (its send semaphore is reused now).
        @pl.when(s >= 2)
        def _():
            pltpu.semaphore_wait(ack_sem.at[slot], 1)
            rs_rdma(s - 2).wait_send()

        rdma = rs_rdma(s)
        rdma.start()
        rdma.wait_recv()

        o_ref[chunk_slice(recv_chunk), :] = (
            o_ref[chunk_slice(recv_chunk), :] + comm_ref[slot])
        # Tell the left neighbor its slot is free for step s + 2.
        pltpu.semaphore_signal(ack_sem.at[slot], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n - 1, rs_step, 0)

    # Drain outstanding acks and deferred send completions so every
    # semaphore ends the kernel at zero.
    @pl.when(n >= 3)
    def _():
        pltpu.semaphore_wait(ack_sem.at[lax.rem(n - 3, 2)], 1)
        rs_rdma(n - 3).wait_send()

    @pl.when(n >= 2)
    def _():
        pltpu.semaphore_wait(ack_sem.at[lax.rem(n - 2, 2)], 1)
        rs_rdma(n - 2).wait_send()

    # --- phase 2: allgather ---
    # After reduce-scatter, rank r owns fully-reduced chunk (r + 1). Each
    # step forwards the freshest chunk; the remote write lands it directly
    # in the neighbor's output (distinct chunk per step: no slot reuse).
    # Per-step semaphores: reusing a slot would let a neighbor running a
    # step ahead release this device's wait before the matching chunk
    # actually landed (each signal is indistinguishable on a shared slot),
    # and the next step would then forward stale data.
    def ag_rdma(s):
        send_chunk = lax.rem(my + 1 - s + n, n)
        return pltpu.make_async_remote_copy(
            src_ref=o_ref.at[chunk_slice(send_chunk)],
            dst_ref=o_ref.at[chunk_slice(send_chunk)],
            send_sem=ag_send.at[s],
            recv_sem=ag_recv.at[s],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def ag_step(s, _):
        rdma = ag_rdma(s)
        rdma.start()
        rdma.wait_recv()
        return 0

    lax.fori_loop(0, n - 1, ag_step, 0)

    def ag_drain(s, _):
        ag_rdma(s).wait_send()
        return 0

    lax.fori_loop(0, n - 1, ag_drain, 0)


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "collective_id",
                                    "interpret"))
def _ring_allreduce_shard(x, *, axis_name: str, collective_id: int,
                          interpret: bool):
    n = lax.axis_size(axis_name)
    rows, cols = x.shape
    assert rows % n == 0, f"rows {rows} not divisible by ring size {n}"
    chunk_rows = rows // n
    kernel = functools.partial(_ring_allreduce_kernel, axis_name=axis_name,
                               num_devices=n, chunk_rows=chunk_rows)
    return pl.pallas_call(
        kernel,
        # The distributed TPU interpreter validates the schedule (including
        # remote DMA and semaphore ordering) on a CPU mesh in CI.
        interpret=pltpu.InterpretParams() if interpret else False,
        # vma: the output varies across the ring axis (required by
        # shard_map's check_vma in recent jax).
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, chunk_rows, cols), x.dtype),  # comm slots
            pltpu.SemaphoreType.DMA((2,)),               # reduce-scatter send
            pltpu.SemaphoreType.DMA((2,)),               # reduce-scatter recv
            pltpu.SemaphoreType.REGULAR((2,)),           # comm slot acks
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),   # allgather send
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),   # allgather recv
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x)


def _differentiable(impl, x, axis_name, collective_id, interpret):
    """Sum-allreduce is linear: the VJP of y = sum_over_ranks(x) w.r.t.
    this rank's shard is the allreduce of the cotangent — the same kernel
    run on g (for the quantized ring this is the straight-through
    estimator). Makes the kernels drop-in for training loops."""

    @jax.custom_vjp
    def op(v):
        return impl(v, axis_name=axis_name, collective_id=collective_id,
                    interpret=interpret)

    def fwd(v):
        return op(v), None

    def bwd(_, g):
        return (impl(g, axis_name=axis_name, collective_id=collective_id,
                     interpret=interpret),)

    op.defvjp(fwd, bwd)
    return op(x)


def ring_allreduce(x, axis_name: str, collective_id: int = 7,
                   interpret: bool = False):
    """Sum-allreduce of `x` across `axis_name` via an ICI ring.

    Call inside shard_map. `x` is the local shard, shape (rows, cols) with
    rows divisible by the ring size and tiling-friendly dims (rows % 8 == 0,
    cols % 128 == 0 for float32 to map onto (8, 128) tiles).
    Differentiable (linear op: VJP = the same allreduce on the cotangent).
    """
    return _differentiable(_ring_allreduce_shard, x, axis_name,
                           collective_id, interpret)


# ---------------------------------------------------------------------------
# HBM-streaming variant: shards larger than VMEM.
# ---------------------------------------------------------------------------

def _ring_allreduce_hbm_kernel(x_ref, o_ref, comm_ref, acc_vmem, in_vmem,
                               copy_sem, rs_send, rs_recv, ack_sem, ag_send,
                               ag_recv, *, axis_name: str, num_devices: int,
                               chunk_rows: int, tile_rows: int):
    # comm_ref is a second kernel output (discarded by the wrapper): remote
    # DMA targets must be inputs/outputs for the distributed interpreter to
    # map them across devices; an ANY-space scratch is not.
    """Ring allreduce with all ring buffers resident in HBM.

    Remote DMA moves chunks HBM->HBM over ICI; the reduction streams each
    received chunk through VMEM in `tile_rows` slices (double-buffered DMA
    in, VPU add, DMA out). Same schedule and flow control as the
    VMEM-resident kernel.
    """
    n = num_devices
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my - 1 + n, n)
    tiles_per_chunk = chunk_rows // tile_rows

    # Seed the output: HBM -> HBM local copy.
    init = pltpu.make_async_copy(x_ref, o_ref, copy_sem.at[0])
    init.start()
    init.wait()

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    def chunk_slice(idx):
        return pl.ds(idx * chunk_rows, chunk_rows)

    # Send/receive are decoupled so the outgoing chunk's ICI transfer
    # flies while the received chunk streams through VMEM: each step
    # starts its send, then waits only for the INCOMING chunk before
    # reducing (a ring step's send reads the chunk reduced in the
    # previous step, so the send itself can never start earlier). Send
    # completions are drained two steps late, when their semaphore slot
    # is about to be reused — descriptors are reconstructed to wait; the
    # semaphores carry the state.
    def rs_rdma(s):
        send_chunk = lax.rem(my - s + n, n)
        slot = lax.rem(s, 2)
        return pltpu.make_async_remote_copy(
            src_ref=o_ref.at[chunk_slice(send_chunk)],
            dst_ref=comm_ref.at[slot],
            send_sem=rs_send.at[slot],
            recv_sem=rs_recv.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def rs_step(s, _):
        recv_chunk = lax.rem(my - s - 1 + n, n)
        slot = lax.rem(s, 2)

        @pl.when(s >= 2)
        def _():
            # Slot reuse gates: the receiver freed our comm slot, and the
            # send that last used send_sem[slot] has fully left the chip.
            pltpu.semaphore_wait(ack_sem.at[slot], 1)
            rs_rdma(s - 2).wait_send()

        rdma = rs_rdma(s)
        rdma.start()
        rdma.wait_recv()

        # Stream-reduce the received chunk: HBM tiles through VMEM,
        # double-buffered — tile t+1's loads overlap tile t's VPU add and
        # store, hiding most of the HBM round trip.
        def loads_for(t, buf):
            row0 = recv_chunk * chunk_rows + t * tile_rows
            la = pltpu.make_async_copy(
                o_ref.at[pl.ds(row0, tile_rows)], acc_vmem.at[buf],
                copy_sem.at[2 * buf])
            li = pltpu.make_async_copy(
                comm_ref.at[slot, pl.ds(t * tile_rows, tile_rows)],
                in_vmem.at[buf], copy_sem.at[2 * buf + 1])
            return la, li

        def store_for(t, buf):
            row0 = recv_chunk * chunk_rows + t * tile_rows
            return pltpu.make_async_copy(
                acc_vmem.at[buf], o_ref.at[pl.ds(row0, tile_rows)],
                copy_sem.at[4 + buf])

        la0, li0 = loads_for(0, 0)
        la0.start()
        li0.start()

        def tile_step(t, _):
            cur = lax.rem(t, 2)
            nxt = lax.rem(t + 1, 2)

            @pl.when(t + 1 < tiles_per_chunk)
            def _():
                # Slot `nxt` must be free: its previous store (tile t-1)
                # has to land before we overwrite acc_vmem[nxt].
                @pl.when(t >= 1)
                def _():
                    store_for(t - 1, nxt).wait()
                la, li = loads_for(t + 1, nxt)
                la.start()
                li.start()

            la, li = loads_for(t, cur)
            la.wait()
            li.wait()
            acc_vmem[cur] = acc_vmem[cur] + in_vmem[cur]
            store_for(t, cur).start()
            return 0

        lax.fori_loop(0, tiles_per_chunk, tile_step, 0)
        # Drain the last two stores before the chunk may be forwarded.
        @pl.when(tiles_per_chunk >= 2)
        def _():
            store_for(tiles_per_chunk - 2,
                      lax.rem(tiles_per_chunk - 2, 2)).wait()

        store_for(tiles_per_chunk - 1,
                  lax.rem(tiles_per_chunk - 1, 2)).wait()
        pltpu.semaphore_signal(ack_sem.at[slot], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n - 1, rs_step, 0)

    # Drain the deferred RS send completions and the final acks.
    @pl.when(n >= 3)
    def _():
        pltpu.semaphore_wait(ack_sem.at[lax.rem(n - 3, 2)], 1)
        rs_rdma(n - 3).wait_send()

    @pl.when(n >= 2)
    def _():
        pltpu.semaphore_wait(ack_sem.at[lax.rem(n - 2, 2)], 1)
        rs_rdma(n - 2).wait_send()

    def ag_rdma(s):
        send_chunk = lax.rem(my + 1 - s + n, n)
        return pltpu.make_async_remote_copy(
            src_ref=o_ref.at[chunk_slice(send_chunk)],
            dst_ref=o_ref.at[chunk_slice(send_chunk)],
            send_sem=ag_send.at[s],
            recv_sem=ag_recv.at[s],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def ag_step(s, _):
        # Wait only for the incoming chunk (the next send depends on it);
        # per-step semaphores let every send completion drain at the end.
        rdma = ag_rdma(s)
        rdma.start()
        rdma.wait_recv()
        return 0

    lax.fori_loop(0, n - 1, ag_step, 0)

    def ag_drain(s, _):
        ag_rdma(s).wait_send()
        return 0

    lax.fori_loop(0, n - 1, ag_drain, 0)


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "collective_id",
                                    "interpret"))
def _ring_allreduce_hbm_shard(x, *, axis_name: str, collective_id: int,
                              interpret: bool):
    n = lax.axis_size(axis_name)
    rows, cols = x.shape
    assert rows % n == 0, f"rows {rows} not divisible by ring size {n}"
    chunk_rows = rows // n
    # Stream tile: the largest divisor of the chunk that is a multiple of
    # 8 (sublane granularity) and at most 256 rows per VMEM buffer. Any
    # multiple-of-8 chunk therefore streams (odd tile counts included);
    # only chunks that are not multiples of 8 fall back to a single tile.
    tile_rows = chunk_rows
    if chunk_rows > 256 and chunk_rows % 8 == 0:
        for cand in range(256, 7, -8):
            if chunk_rows % cand == 0:
                tile_rows = cand
                break
    kernel = functools.partial(_ring_allreduce_hbm_kernel,
                               axis_name=axis_name, num_devices=n,
                               chunk_rows=chunk_rows, tile_rows=tile_rows)
    def reordered(x_ref, o_ref, comm_ref, *scratch):
        return kernel(x_ref, o_ref, comm_ref, *scratch)

    out, _comm = pl.pallas_call(
        reordered,
        interpret=pltpu.InterpretParams() if interpret else False,
        out_shape=(
            jax.ShapeDtypeStruct(x.shape, x.dtype,
                                 vma=frozenset({axis_name})),
            jax.ShapeDtypeStruct((2, chunk_rows, cols), x.dtype,
                                 vma=frozenset({axis_name})),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],   # stays in HBM
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[
            pltpu.VMEM((2, tile_rows, cols), x.dtype),     # acc tiles (x2)
            pltpu.VMEM((2, tile_rows, cols), x.dtype),     # in tiles (x2)
            pltpu.SemaphoreType.DMA((6,)),                 # local copies
            pltpu.SemaphoreType.DMA((2,)),                 # rs send
            pltpu.SemaphoreType.DMA((2,)),                 # rs recv
            pltpu.SemaphoreType.REGULAR((2,)),             # slot acks
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),     # ag send
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),     # ag recv
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x)
    return out


def ring_allreduce_hbm(x, axis_name: str, collective_id: int = 8,
                       interpret: bool = False):
    """Sum-allreduce for shards too large for VMEM: ring buffers live in
    HBM, remote DMA moves chunks chip-to-chip, and the reduction streams
    through VMEM in tiles of up to 256 rows while the NEXT chunk's ICI
    transfer is already in flight (chunk-level double buffering).
    Requirements: rows % ring_size == 0; per-chunk rows that are a
    multiple of 8 stream tiled (any tile count), others fall back to a
    single whole-chunk tile."""
    return _differentiable(_ring_allreduce_hbm_shard, x, axis_name,
                            collective_id, interpret)


# ---------------------------------------------------------------------------
# Quantized variant: int8 wire with per-chunk scales (EQuARX-style).
# ---------------------------------------------------------------------------

def _ring_allreduce_q8_kernel(x_ref, o_ref, qcomm_ref, scomm_ref, rs_send,
                              rs_recv, ack_sem, ag_send, ag_recv, *,
                              axis_name: str, num_devices: int,
                              chunk_rows: int):
    """Ring allreduce sending int8 + a per-chunk float32 scale over ICI.

    Accumulation stays float32 in o_ref; every hop quantizes the outgoing
    chunk symmetrically (scale = max|chunk| / 127) and the receiver
    dequantize-accumulates. The allgather phase quantizes each final block
    once and forwards the int8 stream verbatim, so every rank decodes
    identical values. Wire volume: ~1/4 of float32 plus one (8, 128)
    scale tile per chunk hop.
    """
    n = num_devices
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my - 1 + n, n)

    o_ref[...] = x_ref[...]

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    def chunk_slice(idx):
        return pl.ds(idx * chunk_rows, chunk_rows)

    def quantize(chunk):
        scale = jnp.max(jnp.abs(chunk)) / 127.0
        safe = jnp.maximum(scale, 1e-30)
        q = jnp.clip(jnp.round(chunk / safe), -127, 127).astype(jnp.int8)
        return q, scale

    # Same send/recv decoupling as the HBM kernel: start the outgoing
    # DMAs, wait only for the INCOMING pair before dequant-accumulating,
    # and drain send completions two steps late when their staging slot
    # and semaphore are about to be reused.
    def rs_dmas(s):
        slot = lax.rem(s, 2)
        qdma = pltpu.make_async_remote_copy(
            src_ref=qcomm_ref.at[2 + slot], dst_ref=qcomm_ref.at[slot],
            send_sem=rs_send.at[slot], recv_sem=rs_recv.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        sdma = pltpu.make_async_remote_copy(
            src_ref=scomm_ref.at[2 + slot], dst_ref=scomm_ref.at[slot],
            send_sem=rs_send.at[slot], recv_sem=rs_recv.at[slot],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        return qdma, sdma

    def rs_step(s, _):
        send_chunk = lax.rem(my - s + n, n)
        recv_chunk = lax.rem(my - s - 1 + n, n)
        slot = lax.rem(s, 2)

        @pl.when(s >= 2)
        def _():
            # Receiver freed the wire slot AND our s-2 send left the
            # chip (its staging slot is overwritten just below).
            pltpu.semaphore_wait(ack_sem.at[slot], 2)
            oq, os_ = rs_dmas(s - 2)
            oq.wait_send()
            os_.wait_send()

        q, scale = quantize(o_ref[chunk_slice(send_chunk), :])
        qcomm_ref[2 + slot] = q  # local staging slots 2/3; wire slots 0/1
        scomm_ref[2 + slot] = jnp.full((8, 128), scale, jnp.float32)
        qdma, sdma = rs_dmas(s)
        qdma.start()
        sdma.start()
        qdma.wait_recv()
        sdma.wait_recv()

        inc = (qcomm_ref[slot].astype(jnp.float32) *
               scomm_ref[slot, 0, 0])
        o_ref[chunk_slice(recv_chunk), :] = (
            o_ref[chunk_slice(recv_chunk), :] + inc)
        pltpu.semaphore_signal(ack_sem.at[slot], inc=2, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n - 1, rs_step, 0)

    @pl.when(n >= 3)
    def _():
        pltpu.semaphore_wait(ack_sem.at[lax.rem(n - 3, 2)], 2)
        oq, os_ = rs_dmas(n - 3)
        oq.wait_send()
        os_.wait_send()

    @pl.when(n >= 2)
    def _():
        pltpu.semaphore_wait(ack_sem.at[lax.rem(n - 2, 2)], 2)
        oq, os_ = rs_dmas(n - 2)
        oq.wait_send()
        os_.wait_send()

    # Allgather: quantize the owned block once, adopt its decoded values
    # locally, then forward the received int8 stream verbatim. Wire slots
    # are PER STEP (no reuse): unlike the base kernel, payloads route
    # through shared comm memory rather than distinct o_ref chunks, and a
    # reused slot could be overwritten by a fast left neighbor two steps
    # ahead before this device consumed or forwarded it.
    own = lax.rem(my + 1, n)
    q0, scale0 = quantize(o_ref[chunk_slice(own), :])
    stage = n - 1  # slot index used to stage the initial send
    qcomm_ref[4 + stage] = q0
    scomm_ref[4 + stage] = jnp.full((8, 128), scale0, jnp.float32)
    o_ref[chunk_slice(own), :] = q0.astype(jnp.float32) * scale0

    def ag_dmas(s):
        src_slot = jax.lax.select(s == 0, stage, s - 1)
        dst_slot = s
        qdma = pltpu.make_async_remote_copy(
            src_ref=qcomm_ref.at[4 + src_slot],
            dst_ref=qcomm_ref.at[4 + dst_slot],
            send_sem=ag_send.at[2 * s], recv_sem=ag_recv.at[2 * s],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        sdma = pltpu.make_async_remote_copy(
            src_ref=scomm_ref.at[4 + src_slot],
            dst_ref=scomm_ref.at[4 + dst_slot],
            send_sem=ag_send.at[2 * s + 1], recv_sem=ag_recv.at[2 * s + 1],
            device_id=right, device_id_type=pltpu.DeviceIdType.LOGICAL)
        return qdma, sdma

    def ag_step(s, _):
        # Wait only the incoming stream before decoding; per-step
        # semaphores let every send completion drain after the loop.
        recv_chunk = lax.rem(my - s + n, n)
        qdma, sdma = ag_dmas(s)
        qdma.start()
        sdma.start()
        qdma.wait_recv()
        sdma.wait_recv()
        o_ref[chunk_slice(recv_chunk), :] = (
            qcomm_ref[4 + s].astype(jnp.float32) *
            scomm_ref[4 + s, 0, 0])
        return 0

    lax.fori_loop(0, n - 1, ag_step, 0)

    def ag_drain(s, _):
        qdma, sdma = ag_dmas(s)
        qdma.wait_send()
        sdma.wait_send()
        return 0

    lax.fori_loop(0, n - 1, ag_drain, 0)


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "collective_id",
                                    "interpret"))
def _ring_allreduce_q8_shard(x, *, axis_name: str, collective_id: int,
                             interpret: bool):
    n = lax.axis_size(axis_name)
    rows, cols = x.shape
    assert x.dtype == jnp.float32, "q8 ring quantizes float32 payloads"
    if n == 1:
        return x  # identity: never quantize when nothing moves
    assert rows % n == 0, f"rows {rows} not divisible by ring size {n}"
    chunk_rows = rows // n
    assert chunk_rows % 32 == 0, \
        "int8 tiling needs chunk rows divisible by 32"
    kernel = functools.partial(_ring_allreduce_q8_kernel,
                               axis_name=axis_name, num_devices=n,
                               chunk_rows=chunk_rows)
    return pl.pallas_call(
        kernel,
        interpret=pltpu.InterpretParams() if interpret else False,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            # 0/1: RS wire slots; 2/3: RS staging; 4..4+n-1: per-step AG
            # wire slots (last doubles as the AG staging slot).
            pltpu.VMEM((4 + n, chunk_rows, cols), jnp.int8),
            pltpu.VMEM((4 + n, 8, 128), jnp.float32),  # per-chunk scales
            pltpu.SemaphoreType.DMA((2,)),             # rs send
            pltpu.SemaphoreType.DMA((2,)),             # rs recv
            pltpu.SemaphoreType.REGULAR((2,)),         # slot acks
            pltpu.SemaphoreType.DMA((max(2 * (n - 1), 1),)),  # ag send
            pltpu.SemaphoreType.DMA((max(2 * (n - 1), 1),)),  # ag recv
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x)


def ring_allreduce_q8(x, axis_name: str, collective_id: int = 9,
                      interpret: bool = False):
    """Quantized (int8 wire, per-chunk scale) sum-allreduce over the ICI
    ring: ~4x less inter-chip traffic than float32 at ~2.4 decimal digits
    of precision; all ranks receive identical values. float32 shards,
    rows divisible by ring size, chunk rows divisible by 32."""
    return _differentiable(_ring_allreduce_q8_shard, x, axis_name,
                            collective_id, interpret)


# ---------------------------------------------------------------------------
# Bidirectional variant: both ICI directions at once.
# ---------------------------------------------------------------------------

def _ring_allreduce_bidir_kernel(x_ref, o_ref, comm_ref, rs_send, rs_recv,
                                 ack_sem, ag_send, ag_recv, *,
                                 axis_name: str, num_devices: int,
                                 chunk_rows: int, half_cols: int):
    """Two counter-rotating rings over one shard: columns [0, half) ride
    the rightward ring, columns [half, 2*half) the leftward ring, so both
    ICI directions of the torus axis carry traffic concurrently (2x link
    bandwidth versus the unidirectional ring). Schedule and flow control
    per direction are identical to the base kernel; direction d gets its
    own comm slots, semaphores, and ack lane.
    """
    n = num_devices
    my = lax.axis_index(axis_name)
    right = lax.rem(my + 1, n)
    left = lax.rem(my - 1 + n, n)

    o_ref[...] = x_ref[...]

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    # Direction helpers: d = 0 sends right (chunks walk down), d = 1 sends
    # left (chunk indices mirrored). Both directions' DMAs are issued
    # before either is waited, so the two rings genuinely overlap on the
    # torus axis's two links.
    def neighbors(d):
        to = jax.lax.select(d == 0, right, left)
        frm = jax.lax.select(d == 0, left, right)
        return to, frm

    def rs_send_chunk(d, s):
        return jax.lax.select(d == 0, lax.rem(my - s + n, n),
                              lax.rem(my + s + n, n))

    def rs_recv_chunk(d, s):
        return jax.lax.select(d == 0, lax.rem(my - s - 1 + n, n),
                              lax.rem(my + s + 1, n))

    def rs_rdma(d, s):
        to, _ = neighbors(d)
        slot = lax.rem(s, 2)
        return pltpu.make_async_remote_copy(
            src_ref=o_ref.at[pl.ds(rs_send_chunk(d, s) * chunk_rows,
                                   chunk_rows),
                             pl.ds(d * half_cols, half_cols)],
            dst_ref=comm_ref.at[d, slot],
            send_sem=rs_send.at[d, slot],
            recv_sem=rs_recv.at[d, slot],
            device_id=to,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def rs_step(s, _):
        slot = lax.rem(s, 2)

        @pl.when(s >= 2)
        def _():
            pltpu.semaphore_wait(ack_sem.at[0, slot], 1)
            pltpu.semaphore_wait(ack_sem.at[1, slot], 1)
            rs_rdma(0, s - 2).wait_send()
            rs_rdma(1, s - 2).wait_send()

        dma0 = rs_rdma(0, s)
        dma1 = rs_rdma(1, s)
        dma0.start()
        dma1.start()
        # Wait only the incoming halves (send/recv decoupled as in the
        # unidirectional kernels); send completions drain at slot reuse.
        dma0.wait_recv()
        dma1.wait_recv()
        for d in (0, 1):
            rc = rs_recv_chunk(d, s)
            col0 = d * half_cols
            o_ref[pl.ds(rc * chunk_rows, chunk_rows),
                  pl.ds(col0, half_cols)] = (
                o_ref[pl.ds(rc * chunk_rows, chunk_rows),
                      pl.ds(col0, half_cols)] + comm_ref[d, slot])
            _, frm = neighbors(d)
            pltpu.semaphore_signal(ack_sem.at[d, slot], inc=1,
                                   device_id=frm,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n - 1, rs_step, 0)

    for d in (0, 1):
        @pl.when(n >= 3)
        def _():
            pltpu.semaphore_wait(ack_sem.at[d, lax.rem(n - 3, 2)], 1)
            rs_rdma(d, n - 3).wait_send()

        @pl.when(n >= 2)
        def _():
            pltpu.semaphore_wait(ack_sem.at[d, lax.rem(n - 2, 2)], 1)
            rs_rdma(d, n - 2).wait_send()

    def ag_send_chunk(d, s):
        return jax.lax.select(d == 0, lax.rem(my + 1 - s + n, n),
                              lax.rem(my - 1 + s + n, n))

    def ag_rdma(d, s):
        to, _ = neighbors(d)
        sc = ag_send_chunk(d, s)
        ref = o_ref.at[pl.ds(sc * chunk_rows, chunk_rows),
                       pl.ds(d * half_cols, half_cols)]
        return pltpu.make_async_remote_copy(
            src_ref=ref, dst_ref=ref,
            send_sem=ag_send.at[d, s], recv_sem=ag_recv.at[d, s],
            device_id=to,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def ag_step(s, _):
        dma0 = ag_rdma(0, s)
        dma1 = ag_rdma(1, s)
        dma0.start()
        dma1.start()
        dma0.wait_recv()
        dma1.wait_recv()
        return 0

    lax.fori_loop(0, n - 1, ag_step, 0)

    def ag_drain(s, _):
        ag_rdma(0, s).wait_send()
        ag_rdma(1, s).wait_send()
        return 0

    lax.fori_loop(0, n - 1, ag_drain, 0)


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "collective_id",
                                    "interpret"))
def _ring_allreduce_bidir_shard(x, *, axis_name: str, collective_id: int,
                                interpret: bool):
    n = lax.axis_size(axis_name)
    rows, cols = x.shape
    if n == 1:
        return x
    assert rows % n == 0, f"rows {rows} not divisible by ring size {n}"
    assert cols % 256 == 0, "bidirectional split needs cols % 256 == 0"
    chunk_rows = rows // n
    half_cols = cols // 2
    kernel = functools.partial(_ring_allreduce_bidir_kernel,
                               axis_name=axis_name, num_devices=n,
                               chunk_rows=chunk_rows, half_cols=half_cols)
    return pl.pallas_call(
        kernel,
        interpret=pltpu.InterpretParams() if interpret else False,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, 2, chunk_rows, half_cols), x.dtype),  # comm[d]
            pltpu.SemaphoreType.DMA((2, 2)),                 # rs send[d]
            pltpu.SemaphoreType.DMA((2, 2)),                 # rs recv[d]
            pltpu.SemaphoreType.REGULAR((2, 2)),             # acks[d]
            pltpu.SemaphoreType.DMA((2, max(n - 1, 1))),     # ag send[d]
            pltpu.SemaphoreType.DMA((2, max(n - 1, 1))),     # ag recv[d]
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x)


def ring_allreduce_bidir(x, axis_name: str, collective_id: int = 10,
                         interpret: bool = False):
    """Bidirectional sum-allreduce: the shard's column halves ride
    counter-rotating rings so both ICI directions carry traffic. cols must
    be divisible by 256 (two tiling-aligned halves). Differentiable."""
    return _differentiable(_ring_allreduce_bidir_shard, x, axis_name,
                           collective_id, interpret)


# ---------------------------------------------------------------------------
# Standalone phases: reduce-scatter and allgather kernels, and their
# dimension-ordered composition for multi-axis (torus) meshes.
# ---------------------------------------------------------------------------

def _ring_reduce_scatter_kernel(x_ref, o_ref, work_ref, comm_ref, rs_send,
                                rs_recv, ack_sem, *, axis_name: str,
                                mesh_axes, num_devices: int,
                                chunk_rows: int):
    """Ring reduce-scatter: o_ref (one chunk) = sum over ranks of this
    rank's chunk. Start shift -1 lands chunk r on rank r directly (same
    bookkeeping as the host ring, collectives_ring.cc). mesh_axes names
    the full mesh order so neighbor LOGICAL ids are correct on multi-axis
    (torus) meshes."""
    n = num_devices
    my = lax.axis_index(axis_name)
    _, right, left = _ring_neighbors(axis_name, mesh_axes)

    work_ref[...] = x_ref[...]

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    def chunk_slice(idx):
        return pl.ds(idx * chunk_rows, chunk_rows)

    # Send/recv decoupled like the allreduce kernels: the outgoing chunk
    # flies while the received one reduces; send waits drain at slot
    # reuse and in the epilogue.
    def rs_rdma(s):
        send_chunk = lax.rem(my - 1 - s + 2 * n, n)
        slot = lax.rem(s, 2)
        return pltpu.make_async_remote_copy(
            src_ref=work_ref.at[chunk_slice(send_chunk)],
            dst_ref=comm_ref.at[slot],
            send_sem=rs_send.at[slot],
            recv_sem=rs_recv.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def rs_step(s, _):
        recv_chunk = lax.rem(my - 2 - s + 2 * n, n)
        slot = lax.rem(s, 2)

        @pl.when(s >= 2)
        def _():
            pltpu.semaphore_wait(ack_sem.at[slot], 1)
            rs_rdma(s - 2).wait_send()

        rdma = rs_rdma(s)
        rdma.start()
        rdma.wait_recv()
        work_ref[chunk_slice(recv_chunk), :] = (
            work_ref[chunk_slice(recv_chunk), :] + comm_ref[slot])
        pltpu.semaphore_signal(ack_sem.at[slot], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(0, n - 1, rs_step, 0)

    @pl.when(n >= 3)
    def _():
        pltpu.semaphore_wait(ack_sem.at[lax.rem(n - 3, 2)], 1)
        rs_rdma(n - 3).wait_send()

    @pl.when(n >= 2)
    def _():
        pltpu.semaphore_wait(ack_sem.at[lax.rem(n - 2, 2)], 1)
        rs_rdma(n - 2).wait_send()

    o_ref[...] = work_ref[chunk_slice(my), :]


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "mesh_axes",
                                    "collective_id", "interpret"))
def _ring_reduce_scatter_shard(x, *, axis_name: str, mesh_axes,
                               collective_id: int, interpret: bool):
    n = lax.axis_size(axis_name)
    rows, cols = x.shape
    if n == 1:
        return x
    assert rows % n == 0, f"rows {rows} not divisible by ring size {n}"
    chunk_rows = rows // n
    kernel = functools.partial(_ring_reduce_scatter_kernel,
                               axis_name=axis_name, mesh_axes=mesh_axes,
                               num_devices=n, chunk_rows=chunk_rows)
    return pl.pallas_call(
        kernel,
        interpret=pltpu.InterpretParams() if interpret else False,
        out_shape=jax.ShapeDtypeStruct((chunk_rows, cols), x.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((rows, cols), x.dtype),           # working copy
            pltpu.VMEM((2, chunk_rows, cols), x.dtype),  # comm slots
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x)


def ring_reduce_scatter(x, axis_name: str, collective_id: int = 11,
                        interpret: bool = False, mesh_axes=None):
    """Ring reduce-scatter: returns this rank's 1/P slice of the sum.
    x: (rows, cols), rows divisible by the ring size. On a multi-axis
    mesh, mesh_axes = the Mesh's axis order is REQUIRED (flattened device
    ids follow mesh layout; omitting it there silently misroutes RDMA —
    the default is only valid on single-axis meshes)."""
    return _ring_reduce_scatter_shard(
        x, axis_name=axis_name,
        mesh_axes=None if mesh_axes is None else tuple(mesh_axes),
        collective_id=collective_id, interpret=interpret)


def _ring_allgather_kernel(x_ref, o_ref, ag_send, ag_recv, *,
                           axis_name: str, mesh_axes, num_devices: int,
                           chunk_rows: int):
    """Ring allgather: o_ref = all ranks' x chunks concatenated; chunk
    forwarding rides per-step semaphores like the allreduce phase 2."""
    n = num_devices
    my = lax.axis_index(axis_name)
    _, right, left = _ring_neighbors(axis_name, mesh_axes)

    o_ref[pl.ds(my * chunk_rows, chunk_rows), :] = x_ref[...]

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    def ag_rdma(s):
        send_chunk = lax.rem(my - s + n, n)
        ref = o_ref.at[pl.ds(send_chunk * chunk_rows, chunk_rows), :]
        return pltpu.make_async_remote_copy(
            src_ref=ref, dst_ref=ref,
            send_sem=ag_send.at[s], recv_sem=ag_recv.at[s],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def ag_step(s, _):
        rdma = ag_rdma(s)
        rdma.start()
        rdma.wait_recv()
        return 0

    lax.fori_loop(0, n - 1, ag_step, 0)

    def ag_drain(s, _):
        ag_rdma(s).wait_send()
        return 0

    lax.fori_loop(0, n - 1, ag_drain, 0)


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "mesh_axes",
                                    "collective_id", "interpret"))
def _ring_allgather_shard(x, *, axis_name: str, mesh_axes,
                          collective_id: int, interpret: bool):
    n = lax.axis_size(axis_name)
    rows, cols = x.shape
    if n == 1:
        return x
    kernel = functools.partial(_ring_allgather_kernel, axis_name=axis_name,
                               mesh_axes=mesh_axes, num_devices=n,
                               chunk_rows=rows)
    return pl.pallas_call(
        kernel,
        interpret=pltpu.InterpretParams() if interpret else False,
        out_shape=jax.ShapeDtypeStruct((n * rows, cols), x.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x)


def ring_allgather(x, axis_name: str, collective_id: int = 12,
                   interpret: bool = False, mesh_axes=None):
    """Ring allgather: returns (P * rows, cols) — every rank's x stacked
    in rank order. On a multi-axis mesh, mesh_axes (the Mesh's axis
    order) is REQUIRED — see ring_reduce_scatter."""
    return _ring_allgather_shard(
        x, axis_name=axis_name,
        mesh_axes=None if mesh_axes is None else tuple(mesh_axes),
        collective_id=collective_id, interpret=interpret)


def ring_allreduce_torus(x, axis_names, mesh_axes,
                         collective_id_base: int = 13,
                         interpret: bool = False):
    """Dimension-ordered allreduce over a multi-axis (torus) mesh:
    reduce-scatter along each axis in order (payload shrinking P_axis-fold
    per hop), then allgather in reverse order. Bandwidth-optimal for tori:
    each axis moves only the already-reduced fraction, unlike composing
    full allreduces per axis. rows must be divisible by prod(P_axis).

    mesh_axes is REQUIRED and must be the Mesh's axis_names in mesh order
    (not the reduction order): flattened LOGICAL device ids follow the
    mesh's row-major layout, and a mismatched order silently routes RDMA
    to the wrong chips. There is no way to introspect the mesh from
    inside shard_map, so the caller must state it.
    """
    axes = list(axis_names)
    if mesh_axes is None:
        raise ValueError(
            "ring_allreduce_torus requires mesh_axes (the Mesh's axis "
            "order); a wrong guess silently corrupts results")
    mesh_axes = tuple(mesh_axes)
    for i, ax in enumerate(axes):
        x = ring_reduce_scatter(x, ax, collective_id=collective_id_base + i,
                                interpret=interpret, mesh_axes=mesh_axes)
    for i, ax in enumerate(reversed(axes)):
        x = ring_allgather(
            x, ax,
            collective_id=collective_id_base + len(axes) + i,
            interpret=interpret, mesh_axes=mesh_axes)
    return x


def _alltoall_kernel(x_ref, o_ref, send_sems, recv_sems, *, axis_name: str,
                     mesh_axes, num_devices: int, chunk_rows: int):
    """Rotated-pairwise all-to-all (the on-device mirror of the host
    schedule, reference: gloo/alltoall.cc:39-50): at step s every device
    sends block (my+s) to peer (my+s) and receives block my from peer
    (my-s) — a permutation per step. The per-step semaphore slots work
    because each device gets exactly ONE incoming copy per step index
    (from (my-s), which uses slot s on my side), not because sender and
    receiver are the same pair; collapsing the slots or weakening the
    full-peer entry barrier WOULD race. The copies are independent (each
    reads a distinct x block and lands in a distinct remote slot), so all
    n-1 start before any wait."""
    n = num_devices
    my = lax.axis_index(axis_name)

    def blk(idx):
        return pl.ds(idx * chunk_rows, chunk_rows)

    o_ref[blk(my), :] = x_ref[blk(my), :]

    # Every peer will be written to; none may be touched before it has
    # entered the kernel and allocated its buffers.
    barrier = pltpu.get_barrier_semaphore()

    def signal_peer(s, _):
        peer = _peer_logical_id(axis_name, mesh_axes, lax.rem(my + s, n))
        pltpu.semaphore_signal(barrier, inc=1, device_id=peer,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)
        return 0

    lax.fori_loop(1, n, signal_peer, 0)
    pltpu.semaphore_wait(barrier, n - 1)

    def make_copy(s):
        dst = lax.rem(my + s, n)
        return pltpu.make_async_remote_copy(
            src_ref=x_ref.at[blk(dst), :],
            dst_ref=o_ref.at[blk(my), :],
            send_sem=send_sems.at[s - 1], recv_sem=recv_sems.at[s - 1],
            device_id=_peer_logical_id(axis_name, mesh_axes, dst),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    def start(s, _):
        make_copy(s).start()
        return 0

    def wait(s, _):
        make_copy(s).wait()
        return 0

    lax.fori_loop(1, n, start, 0)
    lax.fori_loop(1, n, wait, 0)


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "mesh_axes",
                                    "collective_id", "interpret"))
def _alltoall_shard(x, *, axis_name: str, mesh_axes, collective_id: int,
                    interpret: bool):
    n = lax.axis_size(axis_name)
    rows, cols = x.shape
    if n == 1:
        return x
    if rows % n != 0:
        raise ValueError(f"rows {rows} not divisible by ring size {n}")
    kernel = functools.partial(_alltoall_kernel, axis_name=axis_name,
                               mesh_axes=mesh_axes, num_devices=n,
                               chunk_rows=rows // n)
    return pl.pallas_call(
        kernel,
        interpret=pltpu.InterpretParams() if interpret else False,
        out_shape=jax.ShapeDtypeStruct((rows, cols), x.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x)


def pallas_alltoall(x, axis_name: str, collective_id: int = 19,
                    interpret: bool = False, mesh_axes=None):
    """All-to-all over the inter-chip DMA engines: x is (P * chunk_rows,
    cols); output block r is peer r's block for this rank (the EP/MoE
    dispatch hot path). On a multi-axis mesh, mesh_axes (the Mesh's axis
    order) is REQUIRED — see ring_reduce_scatter. Differentiable: the
    global block swap (i, j) -> (j, i) is an involution, so its adjoint
    is the same all-to-all run on the cotangent."""
    ma = None if mesh_axes is None else tuple(mesh_axes)

    @jax.custom_vjp
    def op(v):
        return _alltoall_shard(v, axis_name=axis_name, mesh_axes=ma,
                               collective_id=collective_id,
                               interpret=interpret)

    def fwd(v):
        return op(v), None

    def bwd(_, g):
        return (op(g),)

    op.defvjp(fwd, bwd)
    return op(x)
