"""Pallas TPU kernels: custom collective schedules over ICI.

Device-plane analog of the reference's hand-written CUDA ring algorithms
(gloo/cuda_allreduce_ring*.cc): where XLA's built-in collectives (see
gloo_tpu.tpu.spmd) are the "NCCL path", these kernels drive the inter-chip
DMA engines directly for schedules XLA does not emit.
"""

# Backfill renamed jax APIs (jax.shard_map, lax.axis_size, lax.pcast, ...)
# on old jax releases before any device-plane module touches them;
# no-op on modern jax. Kept out of the top-level gloo_tpu __init__ so
# host-plane-only processes never pay the jax import.
from gloo_tpu import _jaxcompat  # noqa: F401


from gloo_tpu.ops.attention import (flash_attention, flash_attention_step,
                                    flash_attention_bwd_step,
                                     largest_block)
from gloo_tpu.ops.overlap import allgather_matmul, matmul_reduce_scatter
from gloo_tpu.ops.rope import apply_rope, rope_positions
from gloo_tpu.ops.pallas_ring import (pallas_alltoall, ring_allgather,
                                       ring_allreduce,
                                       ring_allreduce_bidir,
                                       ring_allreduce_hbm,
                                       ring_allreduce_q8,
                                       ring_allreduce_torus,
                                       ring_reduce_scatter)

__all__ = ["allgather_matmul", "apply_rope", "matmul_reduce_scatter",
           "rope_positions",
           "flash_attention", "flash_attention_step",
           "flash_attention_bwd_step", "pallas_alltoall", "ring_allgather",
           "ring_allreduce",
           "ring_allreduce_bidir",
           "ring_allreduce_hbm", "ring_allreduce_q8",
           "ring_allreduce_torus", "ring_reduce_scatter"]
