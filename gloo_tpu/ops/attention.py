"""Pallas flash attention (single device).

The MXU-side companion to the collective kernels: attention computed
without materializing the (T, T) score matrix. The grid walks
(batch*heads, query-block, key-block) with the key-block dimension
innermost; the online-softmax state (accumulator, running max, running
denominator) lives in VMEM scratch that persists across the sequential
grid steps, so only ONE (block_q, d) query tile and ONE (block_k, d)
key/value tile are resident at a time — sequence length is bounded by
HBM, not VMEM. Same math as the cross-chip ring attention in
gloo_tpu.parallel.sp, applied at the tile level.

Causal masking: key blocks entirely above the diagonal skip their
compute, and a clamped kv index map repeats the last valid tile on dead
grid steps so the pipeline elides their fetches; tiles straddling the
diagonal pay the mask, fully-valid interior tiles run mask-free.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _score_tile_global(q_ref, k_ref, q_base, k_base, block_q, block_k,
                       causal, scale):
    """THE tile computation: scaled scores with the causal mask applied,
    with the tile's rows at q_base.. and columns at k_base.. in the full
    sequence (bases may be dynamic SMEM scalars for ring-rotated blocks).
    Every kernel — forward, backward, step — must go through this single
    definition: the backward kernels recompute softmax from the forward's
    saved logsumexp, so any drift silently skews gradients.

    The dot runs in the inputs' native dtype (bf16 inputs hit the MXU at
    its native rate) with f32 accumulation. `scale` is folded into the q
    tile — a (block_q, d) multiply — rather than the (block_q, block_k)
    scores: the kernels are VPU-bound, so every per-score-element op
    counts. Returns the scaled q tile (backward kernels contract against
    it, so dK inherits the scale for free)."""
    q = q_ref[0] * scale
    k = k_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        q_pos = q_base + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = k_base + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
    return q, s


def _score_tile(q_ref, k_ref, qi, kb, block_q, block_k, causal, scale):
    """Local-sequence view of _score_tile_global (block indices, not
    positions)."""
    return _score_tile_global(q_ref, k_ref, qi * block_q, kb * block_k,
                              block_q, block_k, causal, scale)


def _softmax_tile(s, lse):
    # Masked entries hold -inf and lse is finite (every query row sees at
    # least its diagonal key globally), so exp(-inf - lse) underflows to
    # exactly 0 — no explicit guard needed on the VPU-bound hot path.
    return jnp.exp(s - lse)


def _online_step(s, v, m, l, acc):
    """One online-softmax update shared by the forward and step kernels.

    Handles m == -inf (initial state / fully masked rows so far) via the
    m_safe/corr guards; masked score entries are -inf and their exp
    underflows to 0 against the finite m_safe, so no per-element guard is
    spent on them."""
    m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * corr + p.sum(axis=1, keepdims=True)
    # p contracts on the MXU in v's dtype (matches the reference oracle,
    # which also casts softmax weights to the input dtype).
    acc_new = acc * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                  l_ref, *, block_q: int, block_k: int, causal: bool,
                  scale: float):
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    num_k_blocks = pl.num_programs(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    def update(masked):
        _, s = _score_tile(q_ref, k_ref, qi, kb, block_q, block_k, masked,
                           scale)
        acc_ref[...], m_ref[...], l_ref[...] = _online_step(
            s, v_ref[0], m_ref[...], l_ref[...], acc_ref[...])

    if causal:
        # Split by tile kind: only tiles straddling the diagonal pay the
        # iota/compare/select mask; interior (fully valid) tiles — the
        # vast majority — run mask-free, and fully-masked tiles are
        # skipped outright (their fetches are elided by the clamped kv
        # index map in flash_attention).
        active = kb * block_k <= qi * block_q + block_q - 1
        interior = (kb + 1) * block_k - 1 <= qi * block_q

        @pl.when(active & jnp.logical_not(interior))
        def _():
            update(True)

        @pl.when(interior)
        def _():
            update(False)
    else:
        update(False)

    @pl.when(kb == num_k_blocks - 1)
    def _():
        o_ref[0, ...] = (acc_ref[...] /
                         jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)
        # logsumexp per query row (the backward pass's softmax residual).
        lse_ref[0, ...] = (m_ref[...] +
                           jnp.log(jnp.maximum(l_ref[...], 1e-30)))


def _reference_attention(q, k, v, causal: bool):
    """Materialized-scores attention — the test parity oracle only (the
    VJP runs the dedicated Pallas backward kernels)."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s / jnp.sqrt(jnp.float32(d))
    if causal:
        t = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((t, t), jnp.bool_)), s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "vma_axes"))
def flash_attention(q, k, v, causal: bool = True, block_q: int = None,
                    block_k: int = None, interpret: bool = False,
                    vma_axes=()):
    """Attention over (batch, heads, seq, head_dim) without materializing
    the score matrix. seq must be divisible by the block sizes; head_dim
    should be a multiple of 128 for full MXU tiles.

    block_q/block_k default to the largest divisors of seq up to
    1024/1024: the kernel's cost is dominated by per-grid-step overhead,
    not the matmuls, so big tiles win — the stable-timing v5e block sweep
    (BASELINE.md) has 1024x1024 at 77-131 TFLOP/s across t=1k..16k vs
    ~15 for the round-1 128x128 tiles.

    Supports grouped-query attention: k/v may carry h_kv heads with
    h % h_kv == 0. Both directions map each query head to its shared kv
    head in the BlockSpec index maps — kv tiles are NEVER replicated in
    memory; the backward's per-query-head dK/dV partials are group-summed
    in f32 before the single downcast.

    Differentiable with flash-memory in BOTH directions: the custom VJP
    runs dedicated backward kernels (dQ; dK/dV) that recompute the
    softmax tiles from the saved logsumexp rows — no (T, T)
    materialization anywhere in training."""
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    if v.shape[1] != h_kv:
        raise ValueError(
            f"k has {h_kv} heads but v has {v.shape[1]}")
    if h % h_kv != 0:
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {h_kv}")
    group = h // h_kv
    if block_q is None:
        block_q = largest_block(t, 1024)
    if block_k is None:
        block_k = largest_block(t, 1024)
    if t % block_q != 0 or t % block_k != 0:
        raise ValueError(
            f"seq {t} must be divisible by block sizes {block_q}/{block_k}")
    scale = 1.0 / (d ** 0.5)

    bh = b * h
    qf = q.reshape(bh, t, d)
    kf = k.reshape(b * h_kv, t, d)
    vf = v.reshape(b * h_kv, t, d)

    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale)

    @jax.custom_vjp
    def op(qf, kf, vf):
        return run_kernel(qf, kf, vf)[0]

    def fwd(qf, kf, vf):
        out, lse = run_kernel(qf, kf, vf)
        return out, (qf, kf, vf, out, lse)

    def bwd(residuals, g):
        qf, kf, vf, out, lse = residuals
        return _flash_backward(qf, kf, vf, out, lse, g.astype(qf.dtype),
                               causal=causal, block_q=block_q,
                               block_k=block_k, interpret=interpret,
                               kv_group=group, vma_axes=vma_axes)

    op.defvjp(fwd, bwd)

    if causal:
        # Key blocks fully above the diagonal are masked out; clamping
        # their block index to the last in-range block makes consecutive
        # dead steps request the SAME tile, so the pipeline elides the
        # fetch — without this the HBM traffic for a causal forward is 2x
        # what the math needs.
        def kv_index(i, j, kb):
            last = ((j + 1) * block_q - 1) // block_k
            return (i // group, jnp.minimum(kb, last), 0)
    else:
        def kv_index(i, j, kb):
            return (i // group, kb, 0)

    def run_kernel(qf, kf, vf):
        return pl.pallas_call(
            kernel,
            interpret=interpret,
            grid=(bh, t // block_q, t // block_k),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, d), kv_index,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, d), kv_index,
                             memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0),
                             memory_space=pltpu.VMEM),
            ),
            out_shape=(
                jax.ShapeDtypeStruct((bh, t, d), q.dtype,
                                     vma=frozenset(vma_axes)),
                jax.ShapeDtypeStruct((bh, t, 1), jnp.float32,
                                     vma=frozenset(vma_axes)),
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),  # accumulator
                pltpu.VMEM((block_q, 1), jnp.float32),  # running max
                pltpu.VMEM((block_q, 1), jnp.float32),  # running denom
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
                # Large tiles (the measured optimum) exceed the default
                # 16 MB scoped-vmem budget; v5e/v5p have 128 MB VMEM.
                vmem_limit_bytes=100 * 1024 * 1024),
        )(qf, kf, vf)

    return op(qf, kf, vf).reshape(b, h, t, d)


def largest_block(t: int, cap: int = 128) -> int:
    """Largest divisor of t that is a multiple of 8 and at most `cap`
    (block-size helper for arbitrary multiple-of-8 sequence lengths)."""
    best = 8
    for candidate in range(8, cap + 1, 8):
        if t % candidate == 0:
            best = candidate
    return best


# ---------------------------------------------------------------------------
# Backward kernels: dQ (query-block major) and dK/dV (key-block major).
# ---------------------------------------------------------------------------

def _flash_backward(qf, kf, vf, out, lse, g, *, causal: bool, block_q: int,
                    block_k: int, interpret: bool, kv_group: int = 1,
                    vma_axes=()):
    """Local (single-block) backward via the FUSED one-pass kernel: scores
    and dp are computed once per tile pair and feed dQ, dK, and dV
    together (5 matmuls per tile instead of the two-pass split's 7 — dQ
    accumulates in a resident f32 output block while the grid walks
    key-major). kf/vf may carry bh // kv_group heads (GQA); the
    per-query-head dK/dV partials come back in f32 and are group-summed
    BEFORE the single downcast, matching the f32 accumulation of the
    ungrouped path."""
    # delta[i] = rowsum(dO * O): cheap elementwise pass outside pallas.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)
    dq, dk, dv = flash_attention_bwd_fused(
        qf, kf, vf, g, delta, lse, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interpret, kv_group=kv_group,
        vma_axes=vma_axes)
    dk = group_sum_kv(dk, kv_group)
    dv = group_sum_kv(dv, kv_group)
    return dq.astype(qf.dtype), dk.astype(kf.dtype), dv.astype(vf.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, delta_ref, lse_ref,
                            dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                            block_q: int, block_k: int, causal: bool,
                            scale: float):
    """One-pass backward (local sequence, static offsets): grid
    (bh, key-block, query-block), both inner dims sequential. Each tile
    pair computes s / p / dp / ds ONCE and feeds all three gradients:
    dV/dK accumulate in per-key-block scratch, dQ accumulates into the
    full (t_q, d) f32 output block, which stays resident in VMEM for the
    whole batch-head group and is scaled once at the end."""
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    num_k_blocks = pl.num_programs(1)
    num_q_blocks = pl.num_programs(2)

    @pl.when((kb == 0) & (qi == 0))
    def _():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    @pl.when(qi == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def update(masked):
        q, s = _score_tile(q_ref, k_ref, qi, kb, block_q, block_k, masked,
                           scale)
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p = _softmax_tile(s, lse_ref[0])
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        row = qi * block_q
        dq_ref[0, pl.dslice(row, block_q), :] = (
            dq_ref[0, pl.dslice(row, block_q), :] +
            jax.lax.dot_general(ds.astype(k.dtype), k,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32))

    if causal:
        active = qi * block_q + block_q - 1 >= kb * block_k
        interior = (kb + 1) * block_k - 1 <= qi * block_q

        @pl.when(active & jnp.logical_not(interior))
        def _():
            update(True)

        @pl.when(interior)
        def _():
            update(False)
    else:
        update(False)

    @pl.when(qi == num_q_blocks - 1)
    def _():
        # q comes back from _score_tile already scaled, so ds^T q is dK
        # directly; dQ accumulated against UNscaled k and takes the scale
        # once at the very end.
        dk_ref[0, ...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_acc[...].astype(dv_ref.dtype)

    @pl.when((kb == num_k_blocks - 1) & (qi == num_q_blocks - 1))
    def _():
        dq_ref[...] = dq_ref[...] * scale


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "vma_axes", "kv_group"))
def flash_attention_bwd_fused(q, k, v, do, delta, lse, causal: bool = True,
                              block_q: int = None, block_k: int = None,
                              interpret: bool = False, vma_axes=(),
                              kv_group: int = 1):
    """Fused one-pass flash backward over the local sequence (the
    jax.grad path; ring steps keep flash_attention_bwd_step, whose dQ and
    dK/dV separate cleanly across rotation hops).

    q, do: (bh, t, d); k, v: (bh // kv_group, t, d); delta/lse:
    (bh, t, 1) f32. Returns (dq, dk, dv) f32, dk/dv per-QUERY-head
    partials when kv_group > 1 (caller group-sums). Causal dead tiles
    skip compute with their q-side fetches elided by clamped index maps;
    interior tiles run mask-free.

    VMEM note: the full (t, d) f32 dQ block stays resident (t=16k, d=128
    -> 8 MB), which the 100 MB scoped budget comfortably holds to
    ~100k-token sequences."""
    bh, t, d = q.shape
    if bh % kv_group != 0 or k.shape[0] != bh // kv_group:
        raise ValueError(
            f"k head count {k.shape[0]} != bh {bh} / kv_group {kv_group}")
    if block_q is None:
        block_q = largest_block(t, 1024)
    if block_k is None:
        block_k = largest_block(t, 1024)
    if t % block_q != 0 or t % block_k != 0:
        raise ValueError("tile sizes must divide the sequence length")
    scale = 1.0 / (d ** 0.5)
    vma = frozenset(vma_axes)

    if causal:
        def q_index(i, kb, j):
            first = (kb * block_k) // block_q
            return (i, jnp.maximum(j, first), 0)
    else:
        def q_index(i, kb, j):
            return (i, j, 0)

    kernel = functools.partial(_flash_bwd_fused_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale)
    return pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=(bh, t // block_k, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda i, kb, j: (i // kv_group, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda i, kb, j: (i // kv_group, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), q_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), q_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), q_index,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, t, d), lambda i, kb, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, t, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, t, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, t, d), jnp.float32, vma=vma),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(q, k, v, do, delta, lse)


# ---------------------------------------------------------------------------
# Flash step with carried state: the inner kernel for ring attention.
# ---------------------------------------------------------------------------

def _flash_step_kernel(q_ref, k_ref, v_ref, acc_in, m_in, l_in, q_off_ref,
                       k_off_ref, acc_out, m_out, l_out, *, block_q: int,
                       block_k: int, causal: bool, scale: float):
    """One flash update: fold a (t_kv, d) key/value block into carried
    online-softmax state. Offsets place the local tiles in the GLOBAL
    sequence so causal masking works across ring-rotated blocks."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    num_k_blocks = pl.num_programs(2)

    @pl.when(kb == 0)
    def _():
        acc_out[0, ...] = acc_in[0]
        m_out[0, ...] = m_in[0]
        l_out[0, ...] = l_in[0]

    _, s = _score_tile_global(q_ref, k_ref, q_off_ref[0] + qi * block_q,
                              k_off_ref[0] + kb * block_k, block_q, block_k,
                              causal, scale)
    acc_out[0, ...], m_out[0, ...], l_out[0, ...] = _online_step(
        s, v_ref[0], m_out[0], l_out[0], acc_out[0])
    del num_k_blocks


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "vma_axes", "kv_group"))
def flash_attention_step(q, k, v, acc, m, l, q_offset, k_offset,
                         causal: bool = True, block_q: int = None,
                         block_k: int = None, interpret: bool = False,
                         vma_axes=(), kv_group: int = 1):
    """Fold one key/value block into carried flash state.

    q: (bh, t_q, d); k, v: (bh, t_kv, d); acc: (bh, t_q, d) float32;
    m, l: (bh, t_q, 1) float32; q_offset/k_offset: () int32 global
    positions of the tiles. Returns updated (acc, m, l). Used by
    gloo_tpu.parallel.sp.ring_flash_attention, where the ring rotation
    supplies a different k/v block (and k_offset) per step. Inside
    shard_map with vma checking, pass vma_axes=(axis,). kv_group > 1
    (GQA): k/v carry bh // kv_group heads, shared via the index map.
    """
    bh, tq, d = q.shape
    tkv = k.shape[1]
    if bh % kv_group != 0 or k.shape[0] != bh // kv_group:
        raise ValueError(
            f"k head count {k.shape[0]} != bh {bh} / kv_group {kv_group}")
    if block_q is None:
        block_q = largest_block(tq, 512)
    if block_k is None:
        block_k = largest_block(tkv, 1024)
    if tq % block_q != 0 or tkv % block_k != 0:
        raise ValueError("tile sizes must divide the block shapes")
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(_flash_step_kernel, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale)
    q_off = jnp.reshape(q_offset.astype(jnp.int32), (1,))
    k_off = jnp.reshape(k_offset.astype(jnp.int32), (1,))
    return pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=(bh, tq // block_q, tkv // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda i, j, kb: (i // kv_group, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda i, j, kb: (i // kv_group, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, tq, d), jnp.float32,
                                 vma=frozenset(vma_axes)),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32,
                                 vma=frozenset(vma_axes)),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32,
                                 vma=frozenset(vma_axes)),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(q, k, v, acc, m, l, q_off, k_off)


def group_sum_kv(partials, kv_group: int):
    """Fold per-query-head f32 dK/dV partials down to kv heads: flat query
    head bi*h + hi pairs with kv head bi*h_kv + hi//group, so consecutive
    runs of kv_group rows share one kv head."""
    if kv_group == 1:
        return partials
    bh, tkv, d = partials.shape
    return partials.reshape(bh // kv_group, kv_group, tkv, d).sum(1)


def _flash_bwd_dq_step_kernel(q_ref, k_ref, v_ref, do_ref, delta_ref,
                              lse_ref, q_off_ref, k_off_ref, dq_ref, acc_ref,
                              *, block_q: int, block_k: int, causal: bool,
                              scale: float):
    """dQ contribution of ONE key/value block (global offsets), for the
    ring backward: softmax is recomputed from the forward's global
    logsumexp, so each block's dQ piece is independently correct and the
    ring loop just sums them. (The local jax.grad path uses the fused
    one-pass kernel below, where the static causal tile split lives.)"""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    num_k_blocks = pl.num_programs(2)

    @pl.when(kb == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def update(masked):
        _, s = _score_tile_global(q_ref, k_ref, q_off_ref[0] + qi * block_q,
                                  k_off_ref[0] + kb * block_k, block_q,
                                  block_k, masked, scale)
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p = _softmax_tile(s, lse_ref[0])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        active = (k_off_ref[0] + kb * block_k <=
                  q_off_ref[0] + qi * block_q + block_q - 1)

        @pl.when(active)
        def _():
            update(True)
    else:
        update(False)

    @pl.when(kb == num_k_blocks - 1)
    def _():
        dq_ref[0, ...] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_step_kernel(q_ref, k_ref, v_ref, do_ref, delta_ref,
                               lse_ref, q_off_ref, k_off_ref, dk_ref, dv_ref,
                               dk_acc, dv_acc, *, block_q: int, block_k: int,
                               causal: bool, scale: float):
    """dK/dV of the currently-held key/value block w.r.t. the LOCAL
    queries only (global offsets). In the ring backward these partials
    ride the rotation with their block and sum to the full gradient once
    the block returns home."""
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    num_q_blocks = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def update(masked):
        q, s = _score_tile_global(q_ref, k_ref, q_off_ref[0] + qi * block_q,
                                  k_off_ref[0] + kb * block_k, block_q,
                                  block_k, masked, scale)
        v = v_ref[0]
        do = do_ref[0]
        p = _softmax_tile(s, lse_ref[0])
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        active = (q_off_ref[0] + qi * block_q + block_q - 1 >=
                  k_off_ref[0] + kb * block_k)

        @pl.when(active)
        def _():
            update(True)
    else:
        update(False)

    @pl.when(qi == num_q_blocks - 1)
    def _():
        # q comes back from _score_tile_global already scaled, so the
        # ds^T q contraction yields dK directly; dV needs no scale.
        dk_ref[0, ...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_acc[...].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret", "vma_axes", "kv_group"))
def flash_attention_bwd_step(q, k, v, do, delta, lse, q_offset, k_offset,
                             causal: bool = True, block_q: int = None,
                             block_k: int = None, interpret: bool = False,
                             vma_axes=(), kv_group: int = 1):
    """Backward mirror of flash_attention_step: gradients through one
    key/value block at a global position.

    q, do: (bh, t_q, d); k, v: (bh, t_kv, d); delta = rowsum(dO * O) and
    lse = m + log(l), both (bh, t_q, 1) float32 from the completed
    forward. Returns (dq_partial, dk, dv): dq_partial sums across blocks
    to the full dQ; dk/dv are this block's gradients w.r.t. the local
    queries only. Used by gloo_tpu.parallel.sp.ring_flash_attention's
    VJP (reference backward split: gloo has no device plane; torch ring
    attention recipes shard this the same way).

    kv_group > 1 (GQA): k/v carry bh // kv_group heads, read through the
    i // kv_group index map (never replicated in memory); dk/dv are still
    per-QUERY-head f32 partials — the caller group-sums them.
    """
    bh, tq, d = q.shape
    tkv = k.shape[1]
    if bh % kv_group != 0 or k.shape[0] != bh // kv_group:
        raise ValueError(
            f"k head count {k.shape[0]} != bh {bh} / kv_group {kv_group}")
    if block_q is None:
        block_q = largest_block(tq, 512)
    if block_k is None:
        block_k = largest_block(tkv, 1024)
    if tq % block_q != 0 or tkv % block_k != 0:
        raise ValueError("tile sizes must divide the block shapes")
    scale = 1.0 / (d ** 0.5)
    q_off = jnp.reshape(q_offset.astype(jnp.int32), (1,))
    k_off = jnp.reshape(k_offset.astype(jnp.int32), (1,))
    vma = frozenset(vma_axes)

    def dq_kv_index(i, j, kb):
        return (i // kv_group, kb, 0)

    dq_kernel = functools.partial(_flash_bwd_dq_step_kernel, block_q=block_q,
                                  block_k=block_k, causal=causal, scale=scale)
    dq = pl.pallas_call(
        dq_kernel,
        interpret=interpret,
        grid=(bh, tq // block_q, tkv // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), dq_kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), dq_kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda i, j, kb: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), jnp.float32, vma=vma),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(q, k, v, do, delta, lse, q_off, k_off)

    def dkv_q_index(i, kb, j):
        return (i, j, 0)

    dkv_kernel = functools.partial(_flash_bwd_dkv_step_kernel,
                                   block_q=block_q, block_k=block_k,
                                   causal=causal, scale=scale)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        interpret=interpret,
        grid=(bh, tkv // block_k, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), dkv_q_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda i, kb, j: (i // kv_group, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda i, kb, j: (i // kv_group, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), dkv_q_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), dkv_q_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), dkv_q_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda i, kb, j: (i, kb, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, tkv, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((bh, tkv, d), jnp.float32, vma=vma),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024),
    )(q, k, v, do, delta, lse, q_off, k_off)
    return dq, dk, dv
