"""Rotary position embeddings (RoPE) with explicit positions.

Positions are an argument, not an assumption: under sequence parallelism
each device holds t_local rows of a longer sequence, so the correct
rotation uses GLOBAL positions (rank * t_local + row). Pairing this with
gloo_tpu.parallel.sp: apply_rope(q, my * t_local + iota) on the queries
and the SAME global positions on each k block BEFORE it enters the ring,
and the rotated blocks stay correctly embedded as they travel (RoPE is
applied to values, not indices, so rotation does not disturb it).

TPU notes: pure elementwise ops — XLA fuses the rotation into the
surrounding matmul prologue; no kernel needed. The half-split layout
(rotate_half) is used, matching the convention of most open models.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rope_angles(positions, head_dim: int, theta: float = 10000.0):
    """(..., t) int positions -> (..., t, head_dim // 2) angles."""
    if head_dim % 2 != 0:
        raise ValueError(f"head_dim {head_dim} must be even for RoPE")
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    return positions.astype(jnp.float32)[..., None] * inv_freq


def apply_rope(x, positions, theta: float = 10000.0):
    """Rotate x: (..., t, head_dim) by its positions: (t,) or broadcastable
    to x's leading dims + (t,). Returns x's dtype."""
    d = x.shape[-1]
    ang = rope_angles(positions, d, theta)          # (..., t, d//2)
    cos = jnp.cos(ang).astype(jnp.float32)
    sin = jnp.sin(ang).astype(jnp.float32)
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                          axis=-1)
    return out.astype(x.dtype)


def rope_positions(t: int, offset=0):
    """Global positions for a local block of length t starting at offset
    (e.g. offset = rank * t_local under sequence parallelism)."""
    return offset + lax.iota(jnp.int32, t)
