"""Pallas flash attention (single device).

The MXU-side companion to the collective kernels: the flagship
transformer's hot op computed without materializing the (T, T) score
matrix. Classic two-level structure — the grid walks (batch*heads,
query-block), and each program streams key/value blocks through an
online-softmax accumulator in VMEM (same math as the cross-chip ring
attention in gloo_tpu.parallel.sp, applied at the block level).

Block sizes honor float32 (8, 128) tiling; causal masking skips key
blocks entirely above the diagonal (their contribution is fully masked).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  seq_len: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # block shapes carry a
    # leading singleton (batch*head) dim

    num_k_blocks = seq_len // block_k
    if causal:
        # Key blocks strictly above the diagonal contribute nothing.
        last = lax.div((qi + 1) * block_q - 1, block_k) + 1
    else:
        last = num_k_blocks

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + p.sum(axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    head_dim = q.shape[1]
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = lax.fori_loop(0, last, body, (acc0, m0, l0))
    o_ref[0, ...] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret"))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Attention over (batch, heads, seq, head_dim) without materializing
    the score matrix. seq must be divisible by the block sizes; head_dim
    should be a multiple of 128 for full MXU tiles (smaller works via
    padding by the compiler at reduced efficiency)."""
    b, h, t, d = q.shape
    assert t % block_q == 0 and t % block_k == 0, (
        f"seq {t} must be divisible by block sizes {block_q}/{block_k}")
    scale = 1.0 / (d ** 0.5)

    bh = b * h
    qf = q.reshape(bh, t, d)
    kf = k.reshape(bh, t, d)
    vf = v.reshape(bh, t, d)

    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, seq_len=t, causal=causal,
                               scale=scale)
    out = pl.pallas_call(
        kernel,
        interpret=interpret,
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
    )(qf, kf, vf)
    return out.reshape(b, h, t, d)
