"""Compute/communication overlap kernels: matmul fused with its collective.

The tensor-parallel hot path pays one collective per matmul (row-parallel:
Y = sum_d X_d @ W_d then scatter rows; column-parallel backward: gather
then matmul). Issued separately, the MXU idles during the collective and
the ICI idles during the matmul. These kernels interleave them at ring-
chunk granularity — each ICI hop's transfer flies while the MXU computes
the NEXT chunk's partial product — the "collective matmul" the TPU's
compiler applies to XLA-level sharded dots, here available as an explicit
Pallas primitive for custom schedules (reference framework has no device
compute at all; this is the TPU-native frontier beyond it).

Both ops are differentiable and exactly dual under transposition:
  matmul_reduce_scatter bwd -> allgather (+ dots)
  allgather_matmul bwd      -> matmul_reduce_scatter
Call inside shard_map; shapes per shard. Validated against reference
einsums on the distributed-interpreter CPU mesh (tests/test_overlap.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gloo_tpu.ops.pallas_ring import _ring_neighbors, ring_allgather

# Ring walks up to this size are statically unrolled inside the kernels
# (Mosaic pipelines chunk dots across step boundaries only then; worth
# ~15-20% whole-kernel throughput on v5e). Larger (pod-size) axes fall
# back to fori_loop: O(n) unrolled step bodies risk extreme compile
# times and Mosaic program-size limits.
_kMaxUnrollRing = 16


def _matmul_rs_kernel(x_ref, w_ref, o_ref, send_stage, comm, send_sem,
                      recv_sem, ack_sem, *, axis_name: str, mesh_axes,
                      num_devices: int, chunk_rows: int):
    """Ring reduce-scatter of Y = sum_d X_d @ W_d, with each rank's partial
    for a block computed WHILE the running sum for that block is in flight.

    Schedule (ringReduceScatter convention, startShift=-1: block b lands
    on rank b after P-1 hops): at step s this rank sends the running sum
    for block (r-1-s) and receives block (r-2-s), adding its just-computed
    partial. Double-buffered staging on both sides; comm-slot reuse is
    ack-gated exactly like the pallas ring allreduce.
    """
    n = num_devices
    my = lax.axis_index(axis_name)
    _, right, left = _ring_neighbors(axis_name, mesh_axes)

    def partial_block(b):
        rows = x_ref[pl.ds(b * chunk_rows, chunk_rows), :]
        return jnp.dot(rows, w_ref[...],
                       preferred_element_type=jnp.float32).astype(
                           o_ref.dtype)

    send_stage[0] = partial_block(lax.rem(my - 1 + n, n))

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    def rdma(s):
        slot = lax.rem(s, 2)
        return pltpu.make_async_remote_copy(
            src_ref=send_stage.at[slot],
            dst_ref=comm.at[slot],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    # The ring walk is STATICALLY UNROLLED for rings up to
    # _kMaxUnrollRing (n is a compile-time kernel parameter): Mosaic does
    # not software-pipeline across fori_loop iterations, and the
    # resulting MXU drain at every step boundary measured ~15-20% of
    # whole-kernel throughput on v5e at the 256-row chunk; the unrolled
    # form pipelines chunk dots back-to-back and the per-step
    # conditionals resolve at trace time. Beyond the threshold (pod-size
    # axes) the O(n) code growth risks multi-hour compiles, so the
    # fori_loop form with pl.when predication is kept as the fallback.
    def step(s, static):
        slot = s % 2 if static else lax.rem(s, 2)

        def wait_ack():
            # Slot reuse: the right neighbor must have consumed what we
            # parked in its comm[slot] two steps ago.
            pltpu.semaphore_wait(ack_sem.at[slot], 1)

        if static:
            if s >= 2:
                wait_ack()
        else:
            pl.when(s >= 2)(wait_ack)

        tx = rdma(s)
        tx.start()
        # THE overlap: this block's local partial streams through the MXU
        # while the running sum for it rides the ICI.
        br = lax.rem(my - 2 - s + 2 * n, n)
        p = partial_block(br)
        tx.wait_recv()
        tot = comm[slot] + p

        def wait_prev_send():
            # Next hop's payload. Its staging buffer was the src of send
            # s-1; that transfer must have fully left before we
            # overwrite it.
            rdma(s - 1).wait_send()

        def stage_next():
            send_stage[(s + 1) % 2 if static else lax.rem(s + 1, 2)] = tot

        def emit():
            o_ref[...] = tot  # br == my at the last step

        if static:
            if 1 <= s < n - 2:
                wait_prev_send()
            if s < n - 2:
                stage_next()
            if s == n - 2:
                emit()
        else:
            pl.when(jnp.logical_and(s >= 1, s < n - 2))(wait_prev_send)
            pl.when(s < n - 2)(stage_next)
            pl.when(s == n - 2)(emit)

        pltpu.semaphore_signal(ack_sem.at[slot], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)

    if n <= _kMaxUnrollRing:
        for s in range(n - 1):
            step(s, static=True)
    else:
        def loop_body(s, _):
            step(s, static=False)
            return 0
        lax.fori_loop(0, n - 1, loop_body, 0)

    # Drain: two outstanding acks/sends for n >= 3, one for n == 2, so
    # every semaphore ends the kernel at zero.
    if n >= 3:
        pltpu.semaphore_wait(ack_sem.at[(n - 3) % 2], 1)
        rdma(n - 3).wait_send()

    pltpu.semaphore_wait(ack_sem.at[(n - 2) % 2], 1)
    rdma(n - 2).wait_send()


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "mesh_axes",
                                    "collective_id", "interpret",
                                    "virtual_ranks"))
def _matmul_rs_shard(x, w, *, axis_name: str, mesh_axes, collective_id: int,
                     interpret: bool, virtual_ranks: int | None = None):
    # virtual_ranks: BENCH-ONLY. On a 1-device axis, run the kernel's full
    # P-step schedule with self-loop neighbors (every RDMA lands in the
    # local comm slot) so the compute pipeline can be timed on one chip
    # without ICI. Data semantics degenerate; timing semantics don't.
    # A >1-device axis would route the RDMAs to real neighbors while the
    # chunk indexing walks the virtual ring — nonsense data AND timing.
    if virtual_ranks:
        assert lax.axis_size(axis_name) == 1, \
            "virtual_ranks requires a 1-device axis (self-loop bench mode)"
    n = virtual_ranks or lax.axis_size(axis_name)
    m, k = x.shape
    k2, cols = w.shape
    assert k == k2, f"matmul_reduce_scatter: inner dims {k} vs {k2}"
    assert m % n == 0, f"rows {m} not divisible by ring size {n}"
    chunk_rows = m // n
    if n == 1:
        return jnp.dot(x, w, preferred_element_type=jnp.float32).astype(
            x.dtype)
    kernel = functools.partial(_matmul_rs_kernel, axis_name=axis_name,
                               mesh_axes=mesh_axes, num_devices=n,
                               chunk_rows=chunk_rows)
    return pl.pallas_call(
        kernel,
        interpret=pltpu.InterpretParams() if interpret else False,
        out_shape=jax.ShapeDtypeStruct((chunk_rows, cols), x.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, chunk_rows, cols), x.dtype),  # send staging
            pltpu.VMEM((2, chunk_rows, cols), x.dtype),  # comm slots
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x, w)


def _ag_matmul_kernel(x_ref, w_ref, y_ref, gx_ref, ag_send, ag_recv, *,
                      axis_name: str, mesh_axes, num_devices: int,
                      chunk_rows: int):
    """Ring allgather of X with the per-chunk matmul interleaved: chunk
    (my - s) is forwarded right at step s while its product with W streams
    through the MXU. gx_ref accumulates the gathered X (written once per
    chunk, like the plain ring allgather) and doubles as the DMA target."""
    n = num_devices
    my = lax.axis_index(axis_name)
    _, right, left = _ring_neighbors(axis_name, mesh_axes)

    gx_ref[pl.ds(my * chunk_rows, chunk_rows), :] = x_ref[...]

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    def dot_chunk(c):
        rows = gx_ref[pl.ds(c * chunk_rows, chunk_rows), :]
        y_ref[pl.ds(c * chunk_rows, chunk_rows), :] = jnp.dot(
            rows, w_ref[...],
            preferred_element_type=jnp.float32).astype(y_ref.dtype)

    def ag_rdma(s):
        send_chunk = lax.rem(my - s + n, n)
        ref = gx_ref.at[pl.ds(send_chunk * chunk_rows, chunk_rows), :]
        return pltpu.make_async_remote_copy(
            src_ref=ref, dst_ref=ref,
            send_sem=ag_send.at[s], recv_sem=ag_recv.at[s],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    # Statically unrolled ring walk for rings up to _kMaxUnrollRing —
    # same rationale (and same pod-size fallback) as the matmul_rs
    # kernel: Mosaic pipelines the chunk dots back-to-back only when
    # the step loop is unrolled at trace time (~15-20% whole-kernel
    # throughput on v5e).
    def ag_step(s):
        tx = ag_rdma(s)
        tx.start()
        # Chunk (my - s) is already local (own chunk at s=0, received at
        # step s-1 otherwise): its matmul overlaps the in-flight forward.
        dot_chunk(lax.rem(my - s + n, n))
        tx.wait_recv()

    if n <= _kMaxUnrollRing:
        for s in range(n - 1):
            ag_step(s)
        dot_chunk(lax.rem(my - (n - 1) + n, n))
        for s in range(n - 1):
            ag_rdma(s).wait_send()
    else:
        def loop_body(s, _):
            ag_step(s)
            return 0
        lax.fori_loop(0, n - 1, loop_body, 0)
        # Last received chunk was never forwarded; compute its product.
        dot_chunk(lax.rem(my - (n - 1) + n, n))

        def drain(s, _):
            ag_rdma(s).wait_send()
            return 0
        lax.fori_loop(0, n - 1, drain, 0)


@functools.partial(jax.jit,
                   static_argnames=("axis_name", "mesh_axes",
                                    "collective_id", "interpret",
                                    "virtual_ranks"))
def _ag_matmul_shard(x, w, *, axis_name: str, mesh_axes, collective_id: int,
                     interpret: bool, virtual_ranks: int | None = None):
    # virtual_ranks: BENCH-ONLY self-loop mode, see _matmul_rs_shard.
    if virtual_ranks:
        assert lax.axis_size(axis_name) == 1, \
            "virtual_ranks requires a 1-device axis (self-loop bench mode)"
    n = virtual_ranks or lax.axis_size(axis_name)
    rows, k = x.shape
    k2, cols = w.shape
    assert k == k2, f"allgather_matmul: inner dims {k} vs {k2}"
    if n == 1:
        y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)
        return y, x
    kernel = functools.partial(_ag_matmul_kernel, axis_name=axis_name,
                               mesh_axes=mesh_axes, num_devices=n,
                               chunk_rows=rows)
    return pl.pallas_call(
        kernel,
        interpret=pltpu.InterpretParams() if interpret else False,
        out_shape=(
            jax.ShapeDtypeStruct((n * rows, cols), x.dtype,
                                 vma=frozenset({axis_name})),
            jax.ShapeDtypeStruct((n * rows, k), x.dtype,
                                 vma=frozenset({axis_name})),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
    )(x, w)


# --------------------------------------------------------------------------
# Public, differentiable ops (exactly dual under transposition).
# --------------------------------------------------------------------------


def matmul_reduce_scatter(x, w, axis_name: str, collective_id: int = 21,
                          interpret: bool = False, mesh_axes=None):
    """Rows [r*m/P, (r+1)*m/P) of sum_d X_d @ W_d, computed with the ring
    reduce-scatter overlapped against the per-block matmuls.

    Per shard: x [m, k_local], w [k_local, cols] -> [m/P, cols]. The
    row-parallel TP forward (k sharded over `axis_name`) with its output
    scattered over rows; m % P == 0 and tiling-friendly dims required.
    On a multi-axis mesh, mesh_axes (the Mesh's full axis order) is
    REQUIRED so the ring RDMA routes by flattened logical device id —
    see ring_reduce_scatter. VJP: dx = gather(g) @ w^T,
    dw = x^T @ gather(g) — one allgather.
    """
    axes = None if mesh_axes is None else tuple(mesh_axes)

    @jax.custom_vjp
    def op(xv, wv):
        return _matmul_rs_shard(xv, wv, axis_name=axis_name, mesh_axes=axes,
                                collective_id=collective_id,
                                interpret=interpret)

    def fwd(xv, wv):
        return op(xv, wv), (xv, wv)

    def bwd(res, g):
        xv, wv = res
        gfull = ring_allgather(g, axis_name, collective_id=collective_id + 1,
                               interpret=interpret, mesh_axes=axes)
        dx = jnp.dot(gfull, wv.T,
                     preferred_element_type=jnp.float32).astype(xv.dtype)
        dw = jnp.dot(xv.T, gfull,
                     preferred_element_type=jnp.float32).astype(wv.dtype)
        return dx, dw

    op.defvjp(fwd, bwd)
    return op(x, w)


def allgather_matmul(x, w, axis_name: str, collective_id: int = 23,
                     interpret: bool = False, mesh_axes=None):
    """gather_rows(X over `axis_name`) @ W, the ring allgather overlapped
    against per-chunk matmuls.

    Per shard: x [m_local, k], w [k, cols] -> [P*m_local, cols]. The
    column-parallel TP pattern (w may be a per-device column shard).
    On a multi-axis mesh, mesh_axes is REQUIRED (see
    matmul_reduce_scatter). VJP: dx = matmul_reduce_scatter(g, w^T)
    (the dual fused kernel), dw = gather(x)^T @ g (gathered X is saved
    from the forward).
    """
    axes = None if mesh_axes is None else tuple(mesh_axes)

    @jax.custom_vjp
    def op(xv, wv):
        y, _ = _ag_matmul_shard(xv, wv, axis_name=axis_name, mesh_axes=axes,
                                collective_id=collective_id,
                                interpret=interpret)
        return y

    def fwd(xv, wv):
        y, gx = _ag_matmul_shard(xv, wv, axis_name=axis_name, mesh_axes=axes,
                                 collective_id=collective_id,
                                 interpret=interpret)
        return y, (gx, wv)

    def bwd(res, g):
        gx, wv = res
        dx = matmul_reduce_scatter(g, wv.T, axis_name,
                                   collective_id=collective_id + 1,
                                   interpret=interpret, mesh_axes=axes)
        dw = jnp.dot(gx.T, g,
                     preferred_element_type=jnp.float32).astype(wv.dtype)
        return dx, dw

    op.defvjp(fwd, bwd)
    return op(x, w)
