"""gloo_tpu: a TPU-native collective communications framework.

Two data planes, mirroring the reference's tcp-vs-ibverbs/CUDA split
(/root/reference/gloo, see SURVEY.md):

- **Host plane** (`gloo_tpu.core`, C++ core in `csrc/`): store-based
  rendezvous into a full-mesh process group, slot-tagged async send/recv over
  an epoll TCP transport, and the full collective suite (barrier, broadcast,
  allreduce, reduce, gather(v), scatter, allgather(v), alltoall(v),
  reduce_scatter) with timeouts and abortable waits.
- **Device plane** (`gloo_tpu.tpu`): the same collective surface over jax
  arrays sharded across a `jax.sharding.Mesh` — XLA collectives compiled over
  ICI, plus Pallas ring kernels for custom schedules.
"""

# NOTE: gloo_tpu._jaxcompat (the old-jax API backfill) is deliberately
# NOT imported here — it would drag the multi-second jax import into
# every host-plane-only process. The device-plane packages
# (gloo_tpu.tpu / .ops / .parallel / .models) import it themselves.
from gloo_tpu import elastic, fault, schedule, tuning
from gloo_tpu.bootstrap import detect_launch_env, init_from_env
from gloo_tpu.bucketer import GradientBucketer
from gloo_tpu.core import (
    Aborted,
    AsyncEngine,
    CollectivePlan,
    Context,
    Device,
    Error,
    FileStore,
    HashStore,
    IoError,
    PrefixStore,
    ReduceOp,
    Store,
    TcpStore,
    TcpStoreServer,
    set_connect_debug_logger,
    TimeoutError,
    UnboundBuffer,
    Work,
    codec_pipeline,
    codec_threads,
    crypto_isa_tier,
    derive_keyring,
    q4_block,
    q4_decode,
    q4_encode,
    q4_wire_bytes,
    q8_block,
    q8_decode,
    q8_encode,
    q8_wire_bytes,
    uring_available,
)

__version__ = "0.1.0"

__all__ = [
    "Aborted",
    "AsyncEngine",
    "Context",
    "GradientBucketer",
    "Work",
    "Device",
    "Error",
    "FileStore",
    "HashStore",
    "IoError",
    "PrefixStore",
    "ReduceOp",
    "Store",
    "TcpStore",
    "TcpStoreServer",
    "TimeoutError",
    "UnboundBuffer",
    "__version__",
    "crypto_isa_tier",
    "detect_launch_env",
    "init_from_env",
    "derive_keyring",
    "elastic",
    "fault",
    "codec_pipeline",
    "codec_threads",
    "q4_block",
    "q4_decode",
    "q4_encode",
    "q4_wire_bytes",
    "q8_block",
    "q8_decode",
    "q8_encode",
    "q8_wire_bytes",
    "schedule",
    "tuning",
    "uring_available",
]
