"""Deterministic fault injection for the transport stack (docs/faults.md).

The native fault plane (csrc/tpucoll/fault/) interposes on every outbound
wire message and connection attempt and fires scripted faults — delay,
stall, dup, truncate, corrupt, kill, connect_refuse — matched on
(rank, peer, opcode, slot, payload size, nth). This module is the Python
face of that plane: install a schedule, run the workload, read back the
deterministic firing report.

The table is **process-global** (one schedule per process, like the
connect debug logger): rules pin the injecting ``rank`` so in-process
multi-rank tests share it safely, and multiprocess jobs install the same
schedule in every worker (or set ``TPUCOLL_FAULT_FILE``, loaded at
context connect). With nothing installed, the transport hot path pays a
single predictable pointer check per message — production binaries carry
the plane for free.

Determinism contract: same seed + same schedule + same per-rank workload
=> each rank's firing subsequence in :func:`report` is byte-identical
across runs (entries carry no timestamps; probabilistic rules draw from
a per-(rule, rank) PRNG seeded from the schedule seed).

Example::

    from gloo_tpu import fault
    fault.install({"seed": 42, "faults": [
        {"when": {"rank": 1, "peer": 0, "opcode": "data", "nth": 3},
         "action": "delay", "ms": 200},
        {"when": {"rank": 2}, "action": "kill", "count": 1},
    ]})
    ...   # run collectives; rank 2's first matched send kills its pair
    fired = fault.report()
    fault.clear()

Every fired fault is also counted in the owning context's metrics
registry (``ctx.metrics()["faults"]``) and stamped into the span tracer
(``fault.delay`` etc.), so tests can assert exactly which fault fired
from either side.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Union

from gloo_tpu import _lib
from gloo_tpu._lib import check

__all__ = ["install", "clear", "report", "fired_count"]


def install(schedule: Union[dict, str]) -> None:
    """Install a fault schedule for this process, replacing any previous
    one and resetting the firing report.

    ``schedule`` is a dict (serialized here) or a pre-serialized JSON
    string::

        {"seed": <int, optional>,
         "faults": [{"when": {"rank", "peer", "opcode", "slot",
                              "min_bytes", "max_bytes", "nth"},
                     "action": "delay|stall|dup|truncate|corrupt|kill|"
                               "connect_refuse",
                     "ms": ..., "bytes": ..., "count": ...,
                     "prob": ..., "seed": ...}, ...]}

    All ``when`` fields are optional (match-any); see docs/faults.md for
    the full semantics. Malformed schedules raise ``gloo_tpu.Error``.
    """
    if not isinstance(schedule, str):
        schedule = json.dumps(schedule)
    check(_lib.lib.tc_fault_install(schedule.encode()))


def clear() -> None:
    """Remove the installed schedule and firing report; the transport
    returns to its zero-cost (single pointer check) hot path."""
    _lib.lib.tc_fault_clear()


def report(rank: Optional[int] = None) -> List[Dict]:
    """The deterministic firing log, in firing order.

    Each entry is ``{"rank", "n", "rule", "action", "peer", "opcode",
    "slot", "nbytes", "channel", "domain"}`` where ``n`` indexes fires
    per (injecting rank, fault domain) — domain 0 is the root context,
    async-engine lanes carry lane + 1. With several in-process ranks
    (or async lanes) the global interleaving is scheduling-dependent,
    but each (rank, domain) subsequence is deterministic — pass ``rank``
    for that rank's slice, and sort by ``(domain, n)`` to canonicalize a
    run with concurrent lanes (docs/faults.md, "Determinism").
    """
    entries = json.loads(_lib.copy_out(_lib.lib.tc_fault_report))
    if rank is not None:
        entries = [e for e in entries if e["rank"] == rank]
    return entries


def fired_count(action: Optional[str] = None,
                rank: Optional[int] = None) -> int:
    """Convenience: how many faults have fired (optionally filtered by
    action name and/or injecting rank)."""
    return sum(1 for e in report(rank)
               if action is None or e["action"] == action)
