"""Data-parallel training on both gloo_tpu planes.

Device plane: `make_ddp_train_step` compiles one XLA program where the
batch is sharded over the mesh's data axis, gradients are psum-averaged
over ICI inside shard_map, and the optimizer runs replicated — the
standard TPU DDP recipe.

Host plane: `HostGradSync` averages numpy gradient pytrees across OS
processes with the C++ allreduce — exactly the role the reference plays as
PyTorch's ProcessGroup backend for DDP (SURVEY.md §2.10: "allreduce → DP
gradient sync").
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gloo_tpu.tpu import spmd


def make_ddp_train_step(loss_fn: Callable, optimizer, mesh,
                        axis: str = "data"):
    """Build a jitted (params, opt_state, batch) -> (params, opt_state,
    loss) step with gradient averaging over `axis`.

    `loss_fn(params, batch)` consumes the per-device micro-batch; `batch`
    leaves must have a leading axis divisible by the axis size.
    """

    def local_grads(params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        # Params enter the manual region replicated, so AD's transpose has
        # already psum'd the per-device gradients across `axis`; dividing by
        # the axis size yields the mean (adding a pmean here would be a
        # no-op on the already-replicated value, not a division).
        with jax.named_scope("gloo_tpu.ddp.grad_sync"):
            n = spmd.size(axis)
            grads = jax.tree.map(lambda g: g / n, grads)
            return spmd.mean(loss, axis), grads

    import optax

    sharded_grads = jax.shard_map(
        local_grads, mesh=mesh,
        in_specs=(P(), P(axis)),
        out_specs=(P(), P()))

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = sharded_grads(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


class HostGradSync:
    """Average gradient pytrees across processes via the host data plane.

    Usage: each training process builds a connected `gloo_tpu.Context`,
    computes local gradients (any jax backend), then calls
    `average_(grads)` before the optimizer step. Matches the reference's
    DDP contract: allreduce(SUM) then divide by world size.

    bucketed=True switches to the async engine + gradient bucketer
    (docs/async.md): leaves are flattened into ~25 MiB per-dtype buckets
    issued asynchronously, so bucket k+1's pack overlaps bucket k's wire
    time — the fast path for the many-small-tensors shape of real
    models. Construction is then a COLLECTIVE (it forks lane
    sub-contexts), as is every average() call — same contract as the
    sequential path.
    """

    def __init__(self, context, bucketed: bool = False,
                 bucket_bytes=None, lanes=None, wire=None):
        """wire: opt-in wire compression for float32 gradients — "q8" /
        "bf16" / "lossy" (the Context.allreduce shorthand; precision
        contract in docs/algorithms.md). Gradient averaging is the
        canonical tolerant workload for lossy wire (EQuARX line of
        work); non-float32 leaves always ride the lossless path."""
        self.context = context
        self._tag = 1 << 20  # leave low tags to the application
        self._bucketer = None
        self._wire = wire
        if bucketed:
            from gloo_tpu.bucketer import GradientBucketer

            engine = context.async_engine(lanes=lanes)
            self._bucketer = GradientBucketer(
                engine, bucket_bytes=bucket_bytes, average=True,
                wire=wire)

    def average(self, grads):
        from gloo_tpu.utils.tracing import annotate

        size = self.context.size
        leaves, treedef = jax.tree.flatten(grads)
        out = []
        # The annotation puts the host-plane allreduce on the jax
        # profiler timeline next to device activity (the C++ tracer's
        # own span covers the native side; see docs/observability.md).
        with annotate("gloo_tpu.ddp.host_grad_sync"):
            if self._bucketer is not None:
                arrs = [np.ascontiguousarray(np.asarray(leaf))
                        for leaf in leaves]
                for arr in arrs:
                    self._bucketer.add(arr)
                self._bucketer.finish()  # arrs now hold the means
                out = [jnp.asarray(arr, dtype=leaf.dtype)
                       if hasattr(leaf, "dtype") else arr
                       for leaf, arr in zip(leaves, arrs)]
                return jax.tree.unflatten(treedef, out)
            for i, leaf in enumerate(leaves):
                arr = np.ascontiguousarray(np.asarray(leaf))
                wire = self._wire if arr.dtype == np.float32 else None
                self.context.allreduce(arr, op="sum", tag=self._tag + i,
                                       wire=wire)
                out.append(jnp.asarray(arr / size, dtype=leaf.dtype)
                           if hasattr(leaf, "dtype") else arr / size)
        return jax.tree.unflatten(treedef, out)
