"""Parallelism strategies built on the gloo_tpu collective layers.

The reference sits one layer below these (SURVEY.md §2.10): it supplies the
collectives that DP/TP/PP/SP are built from. This package closes the loop
by shipping the strategies themselves, each built on a gloo_tpu plane:

- `ddp`: data parallelism — device-plane gradient psum over the mesh, and
  host-plane gradient allreduce over the C++ TCP transport (the exact role
  the reference plays under PyTorch DDP);
- `tp`: Megatron-style tensor parallelism (column/row-parallel dense);
- `sp`: sequence/context parallelism — ring attention over ppermute,
  plus Ulysses-style all-to-all head/sequence exchange;
- `pp`: pipeline parallelism — the GPipe forward schedule plus the
  1F1B training schedule (activation stash bounded by stages, not
  microbatches), both static timetables under one lax.scan;
- `ep`: expert parallelism — fixed-capacity MoE dispatch/combine over
  all_to_all;
- `fsdp`: ZeRO-3-style fully-sharded data parallelism — just-in-time
  parameter allgather whose autodiff transpose is the gradient
  reduce-scatter.
"""

# Backfill renamed jax APIs (jax.shard_map, lax.axis_size, lax.pcast, ...)
# on old jax releases before any device-plane module touches them;
# no-op on modern jax. Kept out of the top-level gloo_tpu __init__ so
# host-plane-only processes never pay the jax import.
from gloo_tpu import _jaxcompat  # noqa: F401


from gloo_tpu.parallel.ddp import HostGradSync, make_ddp_train_step
from gloo_tpu.parallel.ep import dispatch_combine
from gloo_tpu.parallel.fsdp import (make_fsdp_train_step, shard_params,
                                    unshard_params)
from gloo_tpu.parallel.pp import pipeline_apply, pipeline_train_1f1b
from gloo_tpu.parallel.sp import (ring_attention, ring_flash_attention,
                                  ulysses_attention)
from gloo_tpu.parallel.tp import (allgather_matmul_dense_auto,
                                  column_parallel_dense,
                                  estimate_comm_share, fused_compute_ratio,
                                  measure_fused_ratio, row_parallel_dense,
                                  row_parallel_dense_scattered_auto,
                                  tp_mlp_block, use_fused_overlap)

__all__ = [
    "HostGradSync",
    "allgather_matmul_dense_auto",
    "column_parallel_dense",
    "dispatch_combine",
    "estimate_comm_share",
    "fused_compute_ratio",
    "measure_fused_ratio",
    "row_parallel_dense_scattered_auto",
    "use_fused_overlap",
    "make_ddp_train_step",
    "make_fsdp_train_step",
    "pipeline_apply",
    "pipeline_train_1f1b",
    "ring_attention",
    "ring_flash_attention",
    "row_parallel_dense",
    "shard_params",
    "ulysses_attention",
    "unshard_params",
    "tp_mlp_block",
]
