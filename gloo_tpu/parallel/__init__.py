"""Parallelism strategies built on the gloo_tpu collective layers.

The reference sits one layer below these (SURVEY.md §2.10): it supplies the
collectives that DP/TP/PP/SP are built from. This package closes the loop
by shipping the strategies themselves, each built on a gloo_tpu plane:

- `ddp`: data parallelism — device-plane gradient psum over the mesh, and
  host-plane gradient allreduce over the C++ TCP transport (the exact role
  the reference plays under PyTorch DDP);
- `tp`: Megatron-style tensor parallelism (column/row-parallel dense);
- `sp`: sequence/context parallelism — ring attention over ppermute.
"""

from gloo_tpu.parallel.ddp import HostGradSync, make_ddp_train_step
from gloo_tpu.parallel.sp import ring_attention
from gloo_tpu.parallel.tp import (column_parallel_dense, row_parallel_dense,
                                  tp_mlp_block)

__all__ = [
    "HostGradSync",
    "column_parallel_dense",
    "make_ddp_train_step",
    "ring_attention",
    "row_parallel_dense",
    "tp_mlp_block",
]
