"""Megatron-style tensor parallelism over a mesh axis.

Built purely from gloo_tpu device-plane collectives — demonstrating that
the collective layer is sufficient to express TP, the same way users build
TP on the reference's allreduce/allgather (SURVEY.md §2.10). All functions
run inside shard_map with the weight shards as per-device values.
"""

from __future__ import annotations

import jax.numpy as jnp

from gloo_tpu.tpu import spmd


def column_parallel_dense(x, w_shard, axis: str):
    """y_shard = x @ w_shard where w is split along its output dim.

    No forward communication; consumers either keep working on the output
    shard (paired with a following row-parallel layer) or allgather.
    """
    return x @ w_shard


def row_parallel_dense(x_shard, w_shard, axis: str):
    """y = sum_over_ranks(x_shard @ w_shard): w split along its input dim,
    x arriving already split (e.g. from a column-parallel layer). The psum
    is the TP allreduce on the ICI mesh."""
    partial = x_shard @ w_shard
    return spmd.allreduce(partial, axis, "sum")


def tp_mlp_block(x, w_up_shard, w_down_shard, axis: str, activation=None):
    """The canonical 2-layer TP block: column-parallel up-projection,
    nonlinearity on the shard, row-parallel down-projection (one psum per
    block, like Megatron's MLP)."""
    import jax

    act = activation if activation is not None else jax.nn.gelu
    h = column_parallel_dense(x, w_up_shard, axis)
    h = act(h)
    return row_parallel_dense(h, w_down_shard, axis)


def row_parallel_dense_scattered(x_shard, w_shard, axis: str,
                                 interpret: bool = False, mesh_axes=None):
    """Row-parallel dense with the output SCATTERED over rows (sequence
    dim) instead of replicated — and the reduce-scatter fused into the
    matmul at ring-chunk granularity (gloo_tpu.ops.matmul_reduce_scatter):
    each ICI hop flies while the MXU computes the next chunk's partial.
    The Megatron-sp pattern (row-parallel -> reduce-scatter) in one
    kernel; pair with allgather_matmul_dense for the gather side. On a
    multi-axis mesh pass mesh_axes (the Mesh's full axis order)."""
    from gloo_tpu.ops import matmul_reduce_scatter

    return matmul_reduce_scatter(x_shard, w_shard, axis,
                                 interpret=interpret, mesh_axes=mesh_axes)


def allgather_matmul_dense(x_rows_shard, w, axis: str,
                           interpret: bool = False, mesh_axes=None):
    """Column-parallel-style dense whose input rows are sequence-sharded:
    gather(x) @ w with the allgather overlapped against per-chunk matmuls
    (gloo_tpu.ops.allgather_matmul). The dual of
    row_parallel_dense_scattered — together they close the Megatron-sp
    loop with both collectives fused. On a multi-axis mesh pass
    mesh_axes (the Mesh's full axis order)."""
    from gloo_tpu.ops import allgather_matmul

    return allgather_matmul(x_rows_shard, w, axis, interpret=interpret,
                            mesh_axes=mesh_axes)
