"""Megatron-style tensor parallelism over a mesh axis.

Built purely from gloo_tpu device-plane collectives — demonstrating that
the collective layer is sufficient to express TP, the same way users build
TP on the reference's allreduce/allgather (SURVEY.md §2.10). All functions
run inside shard_map with the weight shards as per-device values.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from gloo_tpu.tpu import spmd


def column_parallel_dense(x, w_shard, axis: str):
    """y_shard = x @ w_shard where w is split along its output dim.

    No forward communication; consumers either keep working on the output
    shard (paired with a following row-parallel layer) or allgather.
    """
    return x @ w_shard


def row_parallel_dense(x_shard, w_shard, axis: str):
    """y = sum_over_ranks(x_shard @ w_shard): w split along its input dim,
    x arriving already split (e.g. from a column-parallel layer). The psum
    is the TP allreduce on the ICI mesh."""
    partial = x_shard @ w_shard
    with jax.named_scope("gloo_tpu.tp.row_sync"):
        return spmd.allreduce(partial, axis, "sum")


def tp_mlp_block(x, w_up_shard, w_down_shard, axis: str, activation=None):
    """The canonical 2-layer TP block: column-parallel up-projection,
    nonlinearity on the shard, row-parallel down-projection (one psum per
    block, like Megatron's MLP)."""
    import jax

    act = activation if activation is not None else jax.nn.gelu
    h = column_parallel_dense(x, w_up_shard, axis)
    h = act(h)
    return row_parallel_dense(h, w_down_shard, axis)


def row_parallel_dense_scattered(x_shard, w_shard, axis: str,
                                 interpret: bool = False, mesh_axes=None):
    """Row-parallel dense with the output SCATTERED over rows (sequence
    dim) instead of replicated — and the reduce-scatter fused into the
    matmul at ring-chunk granularity (gloo_tpu.ops.matmul_reduce_scatter):
    each ICI hop flies while the MXU computes the next chunk's partial.
    The Megatron-sp pattern (row-parallel -> reduce-scatter) in one
    kernel; pair with allgather_matmul_dense for the gather side. On a
    multi-axis mesh pass mesh_axes (the Mesh's full axis order)."""
    from gloo_tpu.ops import matmul_reduce_scatter

    return matmul_reduce_scatter(x_shard, w_shard, axis,
                                 interpret=interpret, mesh_axes=mesh_axes)


def allgather_matmul_dense(x_rows_shard, w, axis: str,
                           interpret: bool = False, mesh_axes=None):
    """Column-parallel-style dense whose input rows are sequence-sharded:
    gather(x) @ w with the allgather overlapped against per-chunk matmuls
    (gloo_tpu.ops.allgather_matmul). The dual of
    row_parallel_dense_scattered — together they close the Megatron-sp
    loop with both collectives fused. On a multi-axis mesh pass
    mesh_axes (the Mesh's full axis order)."""
    from gloo_tpu.ops import allgather_matmul

    return allgather_matmul(x_rows_shard, w, axis, interpret=interpret,
                            mesh_axes=mesh_axes)


# ---------------------------------------------------------------------------
# Shape-aware fused/unfused dispatch (r5).
#
# The fused overlap kernels hide the TP collective entirely but pay a
# chunking cost on the matmul itself; measured on a real v5e chip
# (BASELINE.md "End-to-end fused-TP" + the r4 overlap sweeps) the cost
# tracks the kernel's shape family: near-parity with >=512-row chunks
# and K<=2048, but down to 0.68x of the plain-dot step at 256-row
# chunks with K=4096. Whether fusing wins therefore depends on how much
# of the unfused step the collective would cost: with ratio = fused
# compute throughput / plain-dot throughput and share = collective time
# / unfused step time, fused wins iff share > 1 - ratio. Encoding that
# rule HERE keeps a user on a single ICI domain with K-heavy shards
# from silently losing a third of their step time to an
# unconditionally-fused pair.
# ---------------------------------------------------------------------------

#: Conservative single-chip throughput of the fused kernels relative to
#: a plain dot of the same FLOPs, by shape family. Calibrated against
#: the two measured end-to-end points (0.93 at M=4096/D=F=2048 ->
#: chunk 512/K=2048; 0.68 at M=2048/D=F=4096 -> chunk 256/K=4096) and
#: the per-kernel sweeps; the slow draw of the bimodal 2048x4096 cell
#: is the one encoded (conservatism favors unfused, whose cost is
#: bounded and stable).
_FUSED_BASE_RATIO = 0.95
_SMALL_CHUNK_PENALTY = 0.85   # chunk_rows < 512
_WIDE_K_PENALTY = 0.85        # K > 2048


def fused_compute_ratio(m: int, k: int, axis_size: int) -> float:
    """Estimated fused-kernel compute throughput as a fraction of the
    plain dot's, for a per-shard [m, k] matmul on a ring of axis_size
    (ring chunks are m // axis_size rows)."""
    chunk_rows = max(1, m // max(1, axis_size))
    ratio = _FUSED_BASE_RATIO
    if chunk_rows < 512:
        ratio *= _SMALL_CHUNK_PENALTY
    if k > 2048:
        ratio *= _WIDE_K_PENALTY
    return ratio


def estimate_comm_share(m: int, k: int, cols: int, axis_size: int,
                        dtype_bytes: int = 2,
                        ici_bytes_per_s: float | None = None,
                        flops_per_s: float | None = None,
                        wire_elems: int | None = None) -> float:
    """Estimated collective share of the UNFUSED step for a per-shard
    [m, k] @ [k, cols] matmul paired with its TP collective over
    `axis_size` devices. `wire_elems` is the element count the
    collective moves: default m*cols (the [m, cols] result riding a
    reduce-scatter); the allgather side must pass its INPUT size
    instead (m*k — the gathered X), which differs whenever k != cols.

    Defaults are v5e-ish and env-tunable — TPUCOLL_TP_ICI_GBPS
    (effective per-hop ring bandwidth, default 90 GB/s: two of the four
    45 GB/s ICI links active in a bidirectional ring) and
    TPUCOLL_TP_TFLOPS (sustained matmul throughput, default 170: the
    measured plain-dot rate on v5e, not the 197 nameplate). Estimates
    feed a one-bit decision with a wide gap between the families, so
    ~30% parameter error does not flip it; re-tune on other
    generations via the env knobs.
    """
    if axis_size <= 1:
        return 0.0
    if ici_bytes_per_s is None:
        ici_bytes_per_s = float(
            os.environ.get("TPUCOLL_TP_ICI_GBPS", "90")) * 1e9
    if flops_per_s is None:
        flops_per_s = float(
            os.environ.get("TPUCOLL_TP_TFLOPS", "170")) * 1e12
    if wire_elems is None:
        wire_elems = m * cols
    wire_bytes = (wire_elems * dtype_bytes) * (axis_size - 1) / axis_size
    t_comm = wire_bytes / ici_bytes_per_s
    t_mm = (2.0 * m * k * cols) / flops_per_s
    return t_comm / (t_comm + t_mm)


def use_fused_overlap(m: int, k: int, cols: int, axis_size: int,
                      comm_share: float | None = None,
                      dtype_bytes: int = 2,
                      wire_elems: int | None = None,
                      ratio: float | None = None) -> bool:
    """The dispatch decision: fuse iff the collective's share of the
    unfused step exceeds the fused kernels' compute penalty
    (share > 1 - ratio). Pass `comm_share` directly when measured;
    otherwise it is estimated from shape + hardware parameters. Pass
    `ratio` from measure_fused_ratio() to use THIS process's measured
    compile draw instead of the shape model (the fused kernels'
    throughput is bimodal across compiles on some shapes — BASELINE.md
    "Overlap kernels" — and a measured slow draw should fall back to
    unfused even where the model would fuse).
    TPUCOLL_TP_OVERLAP=fused|unfused forces either way (auto/unset =
    decide); anything else raises.

    CAUTION: the env var is read at TRACE time. A jitted caller bakes
    the decision into its compiled computation, so flipping
    TPUCOLL_TP_OVERLAP after the first call has NO effect on already-
    traced shapes — re-jit the function or call jax.clear_caches() to
    make a new setting take effect."""
    mode = os.environ.get("TPUCOLL_TP_OVERLAP", "auto")
    if mode == "fused":
        return True
    if mode == "unfused":
        return False
    if mode not in ("", "auto"):
        raise ValueError(
            f"TPUCOLL_TP_OVERLAP must be fused|unfused|auto, got: {mode}")
    if comm_share is None:
        comm_share = estimate_comm_share(m, k, cols, axis_size,
                                         dtype_bytes=dtype_bytes,
                                         wire_elems=wire_elems)
    if ratio is None:
        ratio = fused_compute_ratio(m, k, axis_size)
    return comm_share > 1.0 - ratio


_PROBE_CACHE: dict = {}


def measure_fused_ratio(m: int, k: int, axis_size: int,
                        dtype=None, chain: int = 64, reps: int = 3,
                        interpret: bool = False) -> float:
    """Measure THIS process's fused-kernel compute throughput relative
    to a plain dot of the same FLOPs, on one local device via the
    self-loop virtual ring (the kernel runs its full axis_size-step
    schedule with the ICI leg replaced by on-chip DMA — identical
    compute pipeline, no other participants needed).

    Why measure instead of model: the fused kernels' throughput is
    BIMODAL across process restarts on some shapes (fast ~0.88x of
    plain, slow ~0.79x at 2048x4096 — BASELINE.md); the shape model
    cannot know which draw this process got, a probe can. Feed the
    result to use_fused_overlap(ratio=...) — a slow draw then falls
    back to plain dots + explicit collectives.

    Caveat on what the probe proves: it times its OWN compile of the
    self-loop kernel, not the deployed step's compile. Under the
    r4 observation (the draw is process-correlated: stable within a
    process, bimodal across restarts) that is the same draw; if the
    nondeterminism turns out to be fully per-compile
    (tools/overlap_probe.py is the committed discrimination
    experiment), the probe bounds the distribution but cannot
    guarantee the deployed kernel's draw — time the real step when
    you need certainty.

    The probe runs the square [m, k] @ [k, k] member of the shape
    family — the measured penalty tracks (chunk rows, K), not the
    output width (BASELINE.md r4 sweeps), and the square output chains
    back into the timing loop. Cost: one extra compile of the
    self-loop kernel (minutes for unrolled rings on TPU — comparable
    to the training step's own compile) plus ~chain*reps kernel
    executions. Cached per (m, k, axis_size, dtype) for the process
    lifetime.
    """
    import time

    import jax
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from gloo_tpu.ops.overlap import _matmul_rs_shard

    if dtype is None:
        dtype = jnp.bfloat16
    key = (m, k, axis_size, str(dtype))
    if not interpret and key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    if m % axis_size:
        raise ValueError(f"rows {m} not divisible by ring size {axis_size}")
    if chain < 2:
        raise ValueError(f"chain must be >= 2, got {chain}")
    chunk = m // axis_size
    import numpy as np

    # local_devices: on a multi-host pod every process probes its OWN
    # chip (jax.devices()[0] is only addressable from host 0).
    mesh = Mesh(np.asarray(jax.local_devices()[:1], dtype=object),
                ("_probe",))
    w = jnp.full((k, k), 1.0 / k, dtype)
    x = jnp.ones((m, k), dtype)

    def fused_body(c):
        y = _matmul_rs_shard(c, w, axis_name="_probe", mesh_axes=None,
                             collective_id=29, interpret=interpret,
                             virtual_ranks=axis_size)
        return c.at[:chunk, :].set(y)

    def plain_body(c):
        return jnp.dot(c, w, preferred_element_type=jnp.float32
                       ).astype(c.dtype)

    def chained(body):
        # Trip count is a TRACED argument: one compiled executable
        # serves both chain lengths, so the probe pays the fused
        # kernel's multi-minute compile once (not twice) and the
        # differenced t1/tk time the SAME schedule draw by
        # construction.
        def outer(xv, n):
            return lax.fori_loop(0, n, lambda i, c: body(c), xv)
        return jax.jit(jax.shard_map(outer, mesh=mesh,
                                     in_specs=(P(), P()), out_specs=P(),
                                     check_vma=False))

    def run(f, n):
        jax.block_until_ready(f(x, jnp.int32(n)))

    def rate(body, name):
        f = chained(body)
        run(f, 1), run(f, chain)
        t1 = tk = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            run(f, 1)
            t1 = min(t1, time.perf_counter() - t0)
            t0 = time.perf_counter()
            run(f, chain)
            tk = min(tk, time.perf_counter() - t0)
        if tk <= t1 and not interpret:
            # Noise exceeded chain-1 iterations of kernel time: a
            # clamped value here would cache a garbage ratio and drive
            # dispatch with it. Caller should raise `chain`.
            raise RuntimeError(
                f"measure_fused_ratio: timing noise exceeded the "
                f"{name} kernel's chained time at chain={chain}; "
                f"retry with a longer chain")
        return max(tk - t1, 1e-9) / (chain - 1)

    ratio = rate(plain_body, "plain") / rate(fused_body, "fused")
    if not interpret:
        # Interpreter-mode timings are meaningless — never serve them
        # to a later real measurement of the same shape.
        _PROBE_CACHE[key] = ratio
    return ratio


def row_parallel_dense_scattered_auto(x_shard, w_shard, axis: str,
                                      comm_share: float | None = None,
                                      interpret: bool = False,
                                      mesh_axes=None,
                                      ratio: float | None = None):
    """row_parallel_dense_scattered with the fused/unfused choice made
    by use_fused_overlap: the fused matmul_reduce_scatter kernel when
    hiding the collective pays for the chunking cost, else the plain
    dot + explicit reduce-scatter (identical semantics: [m/P, cols]
    row-scattered output). Pass ratio from measure_fused_ratio() to
    dispatch on this process's measured compile draw.

    The dispatch (including its TPUCOLL_TP_OVERLAP override) happens at
    trace time: under jit, a traced shape keeps whichever branch it was
    compiled with until the caller re-jits or runs jax.clear_caches()."""
    m, k = x_shard.shape
    cols = w_shard.shape[1]
    p = spmd.size(axis)
    if use_fused_overlap(m, k, cols, p, comm_share=comm_share,
                         ratio=ratio,
                         dtype_bytes=x_shard.dtype.itemsize):
        return row_parallel_dense_scattered(x_shard, w_shard, axis,
                                            interpret=interpret,
                                            mesh_axes=mesh_axes)
    partial = jnp.dot(x_shard, w_shard,
                      preferred_element_type=jnp.float32).astype(
                          x_shard.dtype)
    with jax.named_scope("gloo_tpu.tp.row_scatter"):
        return spmd.reduce_scatter(partial, axis, "sum", scatter_axis=0)


def allgather_matmul_dense_auto(x_rows_shard, w, axis: str,
                                comm_share: float | None = None,
                                interpret: bool = False, mesh_axes=None,
                                ratio: float | None = None):
    """allgather_matmul_dense with the fused/unfused choice made by
    use_fused_overlap (same rule as the reduce-scatter side: the two
    kernels are duals with the same chunk geometry), falling back to an
    explicit allgather + plain dot. Pass ratio from
    measure_fused_ratio(rows * axis_size, k, axis_size) — the kernel
    gathers the FULL [rows*P, k] input, so the probe's m is the total
    rows, not this shard's (unlike the reduce-scatter dual, whose m is
    the local shard's rows).

    As with the reduce-scatter dual, the fused/unfused choice (and any
    TPUCOLL_TP_OVERLAP override) is captured at trace time — changing
    the env var needs a re-jit or jax.clear_caches() to take effect."""
    rows, k = x_rows_shard.shape
    cols = w.shape[1]
    p = spmd.size(axis)
    m_total = rows * p
    if use_fused_overlap(m_total, k, cols, p, comm_share=comm_share,
                         ratio=ratio,
                         dtype_bytes=x_rows_shard.dtype.itemsize,
                         wire_elems=m_total * k):
        return allgather_matmul_dense(x_rows_shard, w, axis,
                                      interpret=interpret,
                                      mesh_axes=mesh_axes)
    with jax.named_scope("gloo_tpu.tp.allgather_x"):
        x_full = spmd.allgather(x_rows_shard, axis, gather_axis=0)
    return jnp.dot(x_full, w,
                   preferred_element_type=jnp.float32).astype(
                       x_rows_shard.dtype)
