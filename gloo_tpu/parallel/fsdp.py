"""ZeRO-3 / FSDP-style fully-sharded data parallelism.

Every parameter lives flattened and sharded across the data axis; the
forward all-gathers each leaf just-in-time, and the backward produces
gradients that are ALREADY sharded — no separate reduce-scatter pass is
written anywhere. That is the TPU-native formulation of the ZeRO
recipe: `lax.all_gather`'s transpose IS `psum_scatter`, so jax.grad of
the gather-then-compute program emits exactly the reference-style
allgather(params) + reduce_scatter(grads) schedule (SURVEY.md §2.10:
gloo supplies those two collectives as the primitives FSDP/ZeRO are
built from; the schedule here is recovered by autodiff instead of
hand-written).

Memory: parameter and gradient state per device is 1/n of the model
(plus the transient gathered leaf); optimizer state (the SGD update
below, or any optax state threaded the same way) is sharded too.

Use inside shard_map with the batch sharded over `axis`:

    sharded = shard_params(params, axis)           # once, per device
    step = make_fsdp_train_step(loss_fn, params, axis, lr=0.1)
    sharded, loss = step(sharded, batch)           # repeat
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from gloo_tpu.tpu import spmd


def _pad_len(size: int, n: int) -> int:
    return (-size) % n


def shard_params(params, axis: str):
    """Flatten each leaf, zero-pad to a multiple of the axis size, and keep
    only this device's 1/n chunk. Call inside shard_map."""
    n = spmd.size(axis)
    my = spmd.rank(axis)

    def shard(p):
        flat = p.reshape(-1)
        flat = jnp.pad(flat, (0, _pad_len(flat.size, n)))
        chunk = flat.size // n
        # dynamic_slice at a rank-dependent offset is already varying
        # over `axis` — no pcast needed.
        return lax.dynamic_slice(flat, (my * chunk,), (chunk,))

    return jax.tree.map(shard, params)


def unshard_params(sharded, template, axis: str):
    """All-gather every leaf back to its full shape. `template` is any
    pytree with the original leaf shapes (e.g. jax.eval_shape output or
    the unsharded params)."""

    def gather(piece, ref):
        size = 1
        for s in ref.shape:
            size *= s
        with jax.named_scope("gloo_tpu.fsdp.unshard"):
            full = spmd.allgather(piece, axis)
        return full[:size].reshape(ref.shape).astype(ref.dtype)

    return jax.tree.map(gather, sharded, template)


def make_fsdp_train_step(loss_fn, template, axis: str, lr: float = 1e-2):
    """SGD train step over fully-sharded parameters.

    loss_fn(params, batch) -> scalar local loss, computed on the
    device's local batch shard. The step returns (new_sharded_params,
    global mean loss). Gradients w.r.t. the shards come out of jax.grad
    already reduce-scattered (all_gather transposes to psum_scatter),
    so the update touches only 1/n of the model per device.
    """
    # Keep only leaf metadata: closing over real arrays would bake the
    # whole unsharded model into the jitted executable as replicated
    # constants, defeating the 1/n memory point of sharding.
    template = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), template)

    def local_loss(sharded, batch):
        params = unshard_params(sharded, template, axis)
        return loss_fn(params, batch)

    def step(sharded, batch, step_lr=lr):
        # Differentiate the LOCAL loss only: the all_gather's transpose
        # (psum_scatter) already sums every device's contribution into
        # the shard, so dividing by n yields the global-mean gradient.
        # Keeping psum out of the differentiated function matters — its
        # transpose re-psums the cotangent, which would scale grads by n
        # (same pitfall as ddp.py's grads/n).
        loss, grads = jax.value_and_grad(local_loss)(sharded, batch)
        n = spmd.size(axis)
        new = jax.tree.map(lambda p, g: p - step_lr * (g / n), sharded,
                           grads)
        return new, spmd.allreduce(loss, axis) / n

    return step
