"""Expert parallelism: MoE token routing over all_to_all.

The canonical EP pattern the reference's alltoall exists to serve
(SURVEY.md §2.10: "alltoall → EP/MoE routing"), expressed on the device
plane: each device holds one expert shard; tokens are bucketed by
assigned expert with fixed capacity, dispatched with a single all_to_all
over ICI, processed by the local expert, and combined back by a second
all_to_all.

Fixed-capacity dispatch keeps shapes static for XLA: each device sends
exactly `capacity` token slots to every expert; overflow tokens are
dropped (their combine weight is zero), the standard MoE capacity-factor
discipline.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from gloo_tpu.tpu import spmd


def dispatch_combine(expert_fn: Callable, tokens, expert_idx, capacity: int,
                     axis: str):
    """Route tokens to experts and back. Call inside shard_map.

    Per-device arguments:
      tokens: (T, D) local tokens;
      expert_idx: (T,) int32 assigned expert (global expert e lives on
        mesh position e);
      capacity: slots this device reserves PER expert.
    Returns (T, D): expert outputs aligned with the input tokens (zeros
    for overflow tokens).
    """
    n_experts = spmd.size(axis)
    t_local, d = tokens.shape

    # Position of each token within its expert bucket. Out-of-range
    # assignments (router bug) are dropped like overflow — without the
    # explicit bound check they would silently alias another expert's slot
    # through the combine gather's index clipping.
    one_hot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)
    pos_in_bucket = jnp.cumsum(one_hot, axis=0) * one_hot - one_hot
    pos = pos_in_bucket.sum(axis=1)  # (T,)
    keep = jnp.logical_and(pos < capacity,
                           jnp.logical_and(expert_idx >= 0,
                                           expert_idx < n_experts))

    # Scatter tokens into the send buffer. Overflow tokens go to a dummy
    # expert row (sliced off below) so they can never clobber a kept
    # token's slot.
    send = jnp.zeros((n_experts + 1, capacity, d), tokens.dtype)
    send = send.at[jnp.where(keep, expert_idx, n_experts),
                   jnp.where(keep, pos, 0)].set(tokens)
    send = send[:n_experts]

    # Dispatch: slot (e, c) goes to expert e; gather every device's bucket.
    with jax.named_scope("gloo_tpu.ep.dispatch"):
        arrived = spmd.alltoall(send, axis, split_axis=0, concat_axis=0)
    arrived = arrived.reshape(n_experts * capacity, d)

    # Local expert processes all arrived tokens.
    processed = expert_fn(arrived).reshape(n_experts, capacity, d)

    # Combine: send results back to their source devices.
    with jax.named_scope("gloo_tpu.ep.combine"):
        returned = spmd.alltoall(processed, axis, split_axis=0,
                                 concat_axis=0)

    # Un-scatter back to token order.
    out = returned[expert_idx, jnp.where(keep, pos, 0)]
    return jnp.where(keep[:, None], out, jnp.zeros_like(out))
