"""Sequence/context parallelism: ring attention over the device mesh.

Long sequences are sharded along time; K/V blocks rotate around the ring
(ppermute over ICI) while each device accumulates attention for its local
queries with an online-softmax (flash-style) update. Communication volume
matches the reference's chunked-ring schedule shape (SURVEY.md §5: the
ring allreduce IS a ring sequence-parallel schedule over chunks) — here
expressed as a jit-compiled XLA program.

Call inside shard_map with the time axis sharded:
    q, k, v: (batch, heads, t_local, head_dim) per device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from gloo_tpu.tpu import spmd


def ring_attention(q, k, v, axis: str, causal: bool = True):
    n = spmd.size(axis)
    my = spmd.rank(axis)
    b, h, t_local, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(d))

    q32 = q.astype(jnp.float32)
    pos_q = my * t_local + lax.broadcasted_iota(jnp.int32, (t_local, 1), 0)

    def step(i, carry):
        k_blk, v_blk, out, m, l = carry
        src = lax.rem(my - i + n, n)  # which shard's K/V we hold now
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k_blk.astype(jnp.float32)) * scale
        if causal:
            pos_k = src * t_local + lax.broadcasted_iota(
                jnp.int32, (1, t_local), 1)
            mask = pos_k <= pos_q  # (t_local, t_local)
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        # Guard fully-masked rows (no attendable keys yet): keep m finite.
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - m_safe)
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        correction = jnp.where(jnp.isfinite(m), correction, 0.0)
        l_new = l * correction + p.sum(axis=-1, keepdims=True)
        out_new = out * correction + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        # Rotate K/V to the right neighbor for the next step.
        with jax.named_scope("gloo_tpu.sp.ring_shift"):
            k_next = spmd.shift(k_blk, axis, 1)
            v_next = spmd.shift(v_blk, axis, 1)
        return k_next, v_next, out_new, m_new, l_new

    out0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    # Fresh zeros are device-invariant; the loop carry becomes varying over
    # the ring axis after one step, so pre-mark them to keep carry types
    # stable under shard_map's vma checking.
    out0, m0, l0 = (lax.pcast(a, (axis,), to="varying")
                    for a in (out0, m0, l0))
    _, _, out, m, l = lax.fori_loop(0, n, step, (k, v, out0, m0, l0))
    out = out / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def _ring_flash_forward(q, k, v, axis, causal, block_q, block_k, interpret):
    """Forward ring loop; returns (out in q.dtype, logsumexp rows)."""
    from gloo_tpu.ops.attention import flash_attention_step

    n = spmd.size(axis)
    my = spmd.rank(axis)
    b, h, t_local, d = q.shape
    h_kv = k.shape[1]
    if h % h_kv != 0:
        raise ValueError(
            f"query heads {h} must be a multiple of kv heads {h_kv}")
    group = h // h_kv
    qf = q.reshape(b * h, t_local, d)

    def step(i, carry):
        k_blk, v_blk, acc, m, l = carry
        src = lax.rem(my - i + n, n)
        acc, m, l = flash_attention_step(
            qf, k_blk.reshape(b * h_kv, t_local, d),
            v_blk.reshape(b * h_kv, t_local, d), acc, m, l,
            q_offset=my * t_local, k_offset=src * t_local, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            vma_axes=(axis,), kv_group=group)
        with jax.named_scope("gloo_tpu.sp.ring_shift"):
            k_next = spmd.shift(k_blk, axis, 1)
            v_next = spmd.shift(v_blk, axis, 1)
        return k_next, v_next, acc, m, l

    def zeros(shape, fill=0.0):
        return lax.pcast(jnp.full(shape, fill, jnp.float32), (axis,),
                         to="varying")

    acc0 = zeros((b * h, t_local, d))
    m0 = zeros((b * h, t_local, 1), -jnp.inf)
    l0 = zeros((b * h, t_local, 1))
    _, _, acc, m, l = lax.fori_loop(0, n, step, (k, v, acc0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    out = (acc / l_safe).reshape(b, h, t_local, d).astype(q.dtype)
    return out, m + jnp.log(l_safe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis, causal, block_q, block_k, interpret):
    return _ring_flash_forward(q, k, v, axis, causal, block_q, block_k,
                               interpret)[0]


def _ring_flash_fwd(q, k, v, axis, causal, block_q, block_k, interpret):
    out, lse = _ring_flash_forward(q, k, v, axis, causal, block_q, block_k,
                                   interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis, causal, block_q, block_k, interpret, res, g):
    """Second ring pass. Softmax tiles are recomputed from the forward's
    global logsumexp, so each (queries, rotated block) pair yields an
    independently-correct gradient piece: dQ pieces sum locally; dK/dV
    pieces are accumulated into buffers that rotate WITH their key/value
    block, so each block's gradient arrives home exactly when the block
    does."""
    from gloo_tpu.ops.attention import flash_attention_bwd_step, group_sum_kv

    q, k, v, out, lse = res
    n = spmd.size(axis)
    my = spmd.rank(axis)
    b, h, t_local, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    bh = b * h
    bh_kv = b * h_kv
    qf = q.reshape(bh, t_local, d)
    gf = g.astype(jnp.float32).reshape(bh, t_local, d)
    delta = jnp.sum(gf * out.astype(jnp.float32).reshape(bh, t_local, d),
                    axis=-1, keepdims=True)

    def step(i, carry):
        k_blk, v_blk, dk_c, dv_c, dq = carry
        src = lax.rem(my - i + n, n)
        dq_p, dk_p, dv_p = flash_attention_bwd_step(
            qf, k_blk.reshape(bh_kv, t_local, d),
            v_blk.reshape(bh_kv, t_local, d), gf, delta, lse,
            q_offset=my * t_local, k_offset=src * t_local, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            vma_axes=(axis,), kv_group=group)
        dk_p = group_sum_kv(dk_p, group)
        dv_p = group_sum_kv(dv_p, group)
        return (spmd.shift(k_blk, axis, 1), spmd.shift(v_blk, axis, 1),
                spmd.shift(dk_c + dk_p, axis, 1),
                spmd.shift(dv_c + dv_p, axis, 1), dq + dq_p)

    def zeros(shape):
        return lax.pcast(jnp.zeros(shape, jnp.float32), (axis,),
                         to="varying")

    _, _, dk, dv, dq = lax.fori_loop(
        0, n, step,
        (k, v, zeros((bh_kv, t_local, d)), zeros((bh_kv, t_local, d)),
         zeros((bh, t_local, d))))
    return (dq.reshape(b, h, t_local, d).astype(q.dtype),
            dk.reshape(b, h_kv, t_local, d).astype(k.dtype),
            dv.reshape(b, h_kv, t_local, d).astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, axis: str, causal: bool = True,
                         block_q: int = 128, block_k: int = 128,
                         interpret: bool = False):
    """Ring attention with a Pallas flash inner kernel: K/V blocks rotate
    over ICI (ppermute) while each device folds the arriving block into
    carried online-softmax state tile-by-tile on the MXU — the standard
    long-context recipe (cross-chip ring x on-chip flash), with no
    (t_local, t_local) materialization either.

    Shapes as ring_attention: q, k, v are (batch, heads, t_local, d) per
    device inside shard_map; k/v may carry fewer heads (GQA — shared via
    index maps, never replicated; the smaller blocks also shrink the ICI
    rotation traffic by the group factor). Differentiable: the custom VJP
    runs a second ring pass with dedicated Pallas backward kernels (dQ
    local; per-block dK/dV partials group-summed in f32, riding the
    rotation home with their block).

    interpret=True requires check_vma=False on the enclosing shard_map:
    the Pallas HLO interpreter's block indexing mixes varying and
    invariant operands, which vma checking rejects (JAX limitation; the
    compiled TPU path works under the default check_vma=True)."""
    return _ring_flash(q, k, v, axis, causal, block_q, block_k, interpret)


def ulysses_attention(q, k, v, axis: str, causal: bool = True,
                      attn_fn=None, interpret: bool = False):
    """DeepSpeed-Ulysses-style sequence parallelism: two all-to-alls swap
    the sharded dimension from sequence to heads, so each device runs
    FULL-sequence attention for a subset of heads, then a final
    all-to-all restores sequence sharding. The complement to the ring
    recipes: all_to_all rides ICI once per direction instead of n-1
    ppermute steps, at the cost of requiring heads % group size == 0.
    (Reference positioning: SURVEY.md §2.10 — gloo supplies alltoall as
    the primitive these recipes are built from.)

    q, k, v: (batch, heads, t_local, d) per device inside shard_map.
    The attention over the gathered full sequence DEFAULTS to the Pallas
    flash kernel — the configuration long-context users actually run —
    with the shard_map varying-axis bookkeeping handled internally
    (vma_axes=(axis,) threads through the kernel's out_shapes, so the
    compiled TPU path works under the default check_vma=True).
    interpret=True forces the Pallas interpreter for the DEFAULT flash
    path (it is auto-enabled on CPU backends and ignored when attn_fn is
    supplied — a custom attn_fn owns its own interpret choice); that
    mode needs check_vma=False on the enclosing shard_map (HLO
    interpreter limitation, as for ring_flash_attention). Pass attn_fn
    (signature attn_fn(q, k, v, causal)) to substitute a different
    full-sequence attention, e.g. the materialized-scores oracle.
    """
    n = spmd.size(axis)
    b, h, t_local, d = q.shape
    if h % n != 0:
        raise ValueError(f"heads {h} not divisible by group size {n}")
    if attn_fn is None:
        import jax

        from gloo_tpu.ops.attention import flash_attention

        # CPU backends only run Pallas through the interpreter (the
        # 8-device test/dryrun meshes); real TPU backends compile.
        use_interpret = interpret or jax.default_backend() == "cpu"

        def attn_fn(qh, kh, vh, causal):
            return flash_attention(qh, kh, vh, causal=causal,
                                   interpret=use_interpret,
                                   vma_axes=(axis,))

    # (b, h, t_local, d) -> (b, h/n, t_global, d): scatter heads, gather
    # sequence. all_to_all splits/concats one axis; heads is axis 1,
    # sequence axis 2.
    with jax.named_scope("gloo_tpu.sp.ulysses_exchange"):
        qh, kh, vh = (spmd.alltoall(x, axis, split_axis=1, concat_axis=2)
                      for x in (q, k, v))
    out = attn_fn(qh, kh, vh, causal)
    # (b, h/n, t_global, d) -> (b, h, t_local, d): inverse exchange.
    with jax.named_scope("gloo_tpu.sp.ulysses_exchange"):
        return spmd.alltoall(out, axis, split_axis=2, concat_axis=1)
