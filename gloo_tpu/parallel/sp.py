"""Sequence/context parallelism: ring attention over the device mesh.

Long sequences are sharded along time; K/V blocks rotate around the ring
(ppermute over ICI) while each device accumulates attention for its local
queries with an online-softmax (flash-style) update. Communication volume
matches the reference's chunked-ring schedule shape (SURVEY.md §5: the
ring allreduce IS a ring sequence-parallel schedule over chunks) — here
expressed as a jit-compiled XLA program.

Call inside shard_map with the time axis sharded:
    q, k, v: (batch, heads, t_local, head_dim) per device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from gloo_tpu.tpu import spmd


def ring_attention(q, k, v, axis: str, causal: bool = True):
    n = spmd.size(axis)
    my = spmd.rank(axis)
    b, h, t_local, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(d))

    q32 = q.astype(jnp.float32)
    pos_q = my * t_local + lax.broadcasted_iota(jnp.int32, (t_local, 1), 0)

    def step(i, carry):
        k_blk, v_blk, out, m, l = carry
        src = lax.rem(my - i + n, n)  # which shard's K/V we hold now
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k_blk.astype(jnp.float32)) * scale
        if causal:
            pos_k = src * t_local + lax.broadcasted_iota(
                jnp.int32, (1, t_local), 1)
            mask = pos_k <= pos_q  # (t_local, t_local)
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        # Guard fully-masked rows (no attendable keys yet): keep m finite.
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - m_safe)
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        correction = jnp.where(jnp.isfinite(m), correction, 0.0)
        l_new = l * correction + p.sum(axis=-1, keepdims=True)
        out_new = out * correction + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        # Rotate K/V to the right neighbor for the next step.
        k_next = spmd.shift(k_blk, axis, 1)
        v_next = spmd.shift(v_blk, axis, 1)
        return k_next, v_next, out_new, m_new, l_new

    out0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    m0 = jnp.full((b, h, t_local, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), jnp.float32)
    # Fresh zeros are device-invariant; the loop carry becomes varying over
    # the ring axis after one step, so pre-mark them to keep carry types
    # stable under shard_map's vma checking.
    out0, m0, l0 = (lax.pcast(a, (axis,), to="varying")
                    for a in (out0, m0, l0))
    _, _, out, m, l = lax.fori_loop(0, n, step, (k, v, out0, m0, l0))
    out = out / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_flash_attention(q, k, v, axis: str, causal: bool = True,
                         block_q: int = 128, block_k: int = 128,
                         interpret: bool = False):
    """Ring attention with a Pallas flash inner kernel: K/V blocks rotate
    over ICI (ppermute) while each device folds the arriving block into
    carried online-softmax state tile-by-tile on the MXU — the standard
    long-context recipe (cross-chip ring x on-chip flash), with no
    (t_local, t_local) materialization either.

    Shapes as ring_attention: q, k, v are (batch, heads, t_local, d) per
    device inside shard_map. Forward-only (wrap with jax.checkpoint or
    use ring_attention for the differentiable path until the step kernel
    grows a VJP)."""
    from gloo_tpu.ops.attention import flash_attention_step

    n = spmd.size(axis)
    my = spmd.rank(axis)
    b, h, t_local, d = q.shape
    qf = q.reshape(b * h, t_local, d)

    def step(i, carry):
        k_blk, v_blk, acc, m, l = carry
        src = lax.rem(my - i + n, n)
        acc, m, l = flash_attention_step(
            qf, k_blk.reshape(b * h, t_local, d),
            v_blk.reshape(b * h, t_local, d), acc, m, l,
            q_offset=my * t_local, k_offset=src * t_local, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            vma_axes=(axis,))
        k_next = spmd.shift(k_blk, axis, 1)
        v_next = spmd.shift(v_blk, axis, 1)
        return k_next, v_next, acc, m, l

    acc0 = lax.pcast(jnp.zeros((b * h, t_local, d), jnp.float32), (axis,),
                     to="varying")
    m0 = lax.pcast(jnp.full((b * h, t_local, 1), -jnp.inf, jnp.float32),
                   (axis,), to="varying")
    l0 = lax.pcast(jnp.zeros((b * h, t_local, 1), jnp.float32), (axis,),
                   to="varying")
    _, _, acc, m, l = lax.fori_loop(0, n, step, (k, v, acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(b, h, t_local, d).astype(q.dtype)
