"""Pipeline parallelism over the device mesh.

GPipe-style schedule built from gloo_tpu collectives: stage weights live
on their pipe-axis position, microbatches march stage-to-stage with
`spmd.shift` (ppermute over ICI), and a `lax.scan` over ticks keeps the
whole schedule one compiled XLA program with static control flow.

The classic pipelining identity: with S stages and M microbatches the
schedule runs S + M - 1 ticks; at tick t, stage s computes microbatch
t - s (when 0 <= t - s < M). Each device applies only its own stage
function; activations rotate right one stage per tick.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax

from gloo_tpu.tpu import spmd


def pipeline_apply(stage_fn: Callable, stage_params, x_microbatches,
                   axis: str):
    """Run a pipeline of `stage_fn` across the mesh axis.

    Call inside shard_map. Per-device arguments:
      stage_params: this device's stage weights (stage s on position s);
      x_microbatches: (M, ...) microbatches, only meaningful on stage 0
        (other stages may pass zeros of the same shape).
    Returns (M, ...) outputs, meaningful on the LAST stage.

    stage_fn(params, x) -> y must be shape-preserving across stages (equal
    widths) so activations can rotate; pad stages to a common width
    otherwise.
    """
    stages = spmd.size(axis)
    my_stage = spmd.rank(axis)
    m = x_microbatches.shape[0]
    ticks = stages + m - 1

    def tick(carry, t):
        inflight, outputs = carry
        # Which microbatch does stage 0 inject this tick?
        feed_idx = jnp.clip(t, 0, m - 1)
        injected = x_microbatches[feed_idx]
        incoming = jnp.where(my_stage == 0, injected, inflight)

        computed = stage_fn(stage_params, incoming)
        # Stages outside their active window pass zeros along; harmless
        # because their results are never recorded.
        active = jnp.logical_and(t - my_stage >= 0, t - my_stage < m)
        computed = jnp.where(active, computed, jnp.zeros_like(computed))

        # Record finished microbatch t - (stages - 1) on the last stage.
        done_idx = jnp.clip(t - (stages - 1), 0, m - 1)
        record = jnp.logical_and(my_stage == stages - 1,
                                 jnp.logical_and(t >= stages - 1,
                                                 t - (stages - 1) < m))
        outputs = jnp.where(
            record,
            outputs.at[done_idx].set(computed),
            outputs)

        # Rotate activations to the next stage.
        nxt = spmd.shift(computed, axis, 1)
        return (nxt, outputs), None

    # pcast: the carry becomes device-varying after the first tick; fresh
    # zeros must be pre-marked to keep scan carry types stable under
    # shard_map's vma checking.
    inflight0 = lax.pcast(jnp.zeros_like(x_microbatches[0]), (axis,),
                          to="varying")
    outputs0 = lax.pcast(jnp.zeros_like(x_microbatches), (axis,),
                         to="varying")
    (_, outputs), _ = lax.scan(tick, (inflight0, outputs0),
                               jnp.arange(ticks))
    return outputs
