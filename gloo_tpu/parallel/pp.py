"""Pipeline parallelism over the device mesh.

Two schedules built from gloo_tpu collectives, both one compiled XLA
program with static control flow (`lax.scan` over ticks, `spmd.shift`
ppermutes over ICI):

- `pipeline_apply`: GPipe-style forward pipeline. S + M - 1 ticks; at
  tick t, stage s computes microbatch t - s.
- `pipeline_train_1f1b`: the 1F1B training schedule (one-forward-
  one-backward; the non-interleaved PipeDream-flush/Megatron schedule).
  Each stage runs min(S-1-s, M) warmup forwards, then strictly
  alternates forward/backward, then drains. The point of 1F1B over a
  GPipe-style all-forwards-then-all-backwards training schedule is the
  activation footprint: a stage stashes at most S in-flight microbatch
  inputs instead of all M — every buffer here has static leading
  dimension S, independent of M.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gloo_tpu.tpu import spmd


def pipeline_apply(stage_fn: Callable, stage_params, x_microbatches,
                   axis: str):
    """Run a pipeline of `stage_fn` across the mesh axis.

    Call inside shard_map. Per-device arguments:
      stage_params: this device's stage weights (stage s on position s);
      x_microbatches: (M, ...) microbatches, only meaningful on stage 0
        (other stages may pass zeros of the same shape).
    Returns (M, ...) outputs, meaningful on the LAST stage.

    stage_fn(params, x) -> y must be shape-preserving across stages (equal
    widths) so activations can rotate; pad stages to a common width
    otherwise.
    """
    stages = spmd.size(axis)
    my_stage = spmd.rank(axis)
    m = x_microbatches.shape[0]
    ticks = stages + m - 1

    def tick(carry, t):
        inflight, outputs = carry
        # Which microbatch does stage 0 inject this tick?
        feed_idx = jnp.clip(t, 0, m - 1)
        injected = x_microbatches[feed_idx]
        incoming = jnp.where(my_stage == 0, injected, inflight)

        computed = stage_fn(stage_params, incoming)
        # Stages outside their active window pass zeros along; harmless
        # because their results are never recorded.
        active = jnp.logical_and(t - my_stage >= 0, t - my_stage < m)
        computed = jnp.where(active, computed, jnp.zeros_like(computed))

        # Record finished microbatch t - (stages - 1) on the last stage.
        done_idx = jnp.clip(t - (stages - 1), 0, m - 1)
        record = jnp.logical_and(my_stage == stages - 1,
                                 jnp.logical_and(t >= stages - 1,
                                                 t - (stages - 1) < m))
        outputs = jnp.where(
            record,
            outputs.at[done_idx].set(computed),
            outputs)

        # Rotate activations to the next stage.
        with jax.named_scope("gloo_tpu.pp.stage_shift"):
            nxt = spmd.shift(computed, axis, 1)
        return (nxt, outputs), None

    # pcast: the carry becomes device-varying after the first tick; fresh
    # zeros must be pre-marked to keep scan carry types stable under
    # shard_map's vma checking.
    inflight0 = lax.pcast(jnp.zeros_like(x_microbatches[0]), (axis,),
                          to="varying")
    outputs0 = lax.pcast(jnp.zeros_like(x_microbatches), (axis,),
                         to="varying")
    (_, outputs), _ = lax.scan(tick, (inflight0, outputs0),
                               jnp.arange(ticks))
    return outputs


def _build_1f1b_tables(stages: int, m: int):
    """Event-driven simulation of the non-interleaved 1F1B timetable.

    Returns (fwd, bwd): int32 arrays [T, S]; entry = the microbatch that
    stage s forwards/backwards at tick t, or -1. Policy per stage: run
    min(S-1-s, M) warmup forwards, then alternate forward/backward
    starting with a forward (the "1F1B" steady state), stalling on data
    dependencies (an op's input must have been produced at an EARLIER
    tick — the inter-tick ppermute is the only transport). With M >= S
    this reproduces the classic 2(M + S - 1)-tick timeline.
    """
    warm = [min(stages - 1 - s, m) for s in range(stages)]
    f_done = [[-1] * m for _ in range(stages)]  # tick F(s,i) completed
    b_done = [[-1] * m for _ in range(stages)]
    fc = [0] * stages  # forwards issued per stage
    bc = [0] * stages  # backwards issued per stage
    fwd_rows, bwd_rows = [], []
    t = 0
    limit = 4 * (m + stages) + 8  # any valid schedule is far shorter
    while any(b < m for b in bc):
        assert t < limit, "1F1B table simulation failed to converge"
        row_f, row_b = [-1] * stages, [-1] * stages
        for s in range(stages):
            i_f, i_b = fc[s], bc[s]
            # Completion times are recorded AFTER the per-stage loop, so
            # a recorded tick is always < t: "produced at an earlier
            # tick" is exactly "!= -1" here.
            can_f = i_f < m and (s == 0 or f_done[s - 1][i_f] != -1)
            can_b = i_b < m and f_done[s][i_b] != -1 and (
                s == stages - 1 or b_done[s + 1][i_b] != -1)
            if fc[s] < warm[s]:
                turn = "f"  # warmup
            elif fc[s] < m and (fc[s] - warm[s]) == bc[s]:
                turn = "f"  # steady state: forward's turn
            else:
                turn = "b"
            if turn == "f" and can_f:
                row_f[s] = i_f
            elif turn == "b" and can_b:
                row_b[s] = i_b
            # else: stall this tick (dependency bubble)
        for s in range(stages):
            if row_f[s] >= 0:
                f_done[s][row_f[s]] = t
                fc[s] += 1
            if row_b[s] >= 0:
                b_done[s][row_b[s]] = t
                bc[s] += 1
        fwd_rows.append(row_f)
        bwd_rows.append(row_b)
        t += 1
    return (np.asarray(fwd_rows, np.int32), np.asarray(bwd_rows, np.int32))


def pipeline_train_1f1b(stage_fn: Callable, loss_fn: Callable,
                        stage_params, x_microbatches, y_microbatches,
                        axis: str):
    """One 1F1B training step across the mesh axis. Call inside
    shard_map.

    Per-device arguments:
      stage_params: this device's stage weights (stage s on position s);
      x_microbatches: (M, ...) inputs, meaningful on stage 0;
      y_microbatches: (M, ...) targets, meaningful on the LAST stage.

    stage_fn(params, x) -> y must be shape-preserving across stages;
    loss_fn(y, target) -> scalar is applied by the last stage. Returns
    (grads, loss_sum): grads is this device's stage-parameter gradient
    SUMMED over microbatches (scale by 1/M for the mean); loss_sum is
    the summed loss, nonzero on the last stage (psum it to broadcast).

    Memory: the input stash and both receive rings have static leading
    dimension S — the 1F1B bound of at most S in-flight microbatches
    per stage (a GPipe-style training schedule would stash all M).
    XLA cost note: ticks are SPMD-uniform, so each tick computes a
    masked forward AND a masked backward (selected, not branched);
    schedule wins here are memory and the comm pattern, not flop count.
    """
    stages = spmd.size(axis)
    my_stage = spmd.rank(axis)
    m = x_microbatches.shape[0]
    fwd_np, bwd_np = _build_1f1b_tables(stages, m)
    fwd_tbl = jnp.asarray(fwd_np)
    bwd_tbl = jnp.asarray(bwd_np)
    ticks = fwd_np.shape[0]
    is_last = my_stage == stages - 1

    x0 = jnp.zeros_like(x_microbatches[0])

    def tick(carry, t):
        x_stash, a_recv, g_recv, grad_acc, loss_acc = carry
        f_mb = fwd_tbl[t, my_stage]
        b_mb = bwd_tbl[t, my_stage]
        do_f = f_mb >= 0
        do_b = b_mb >= 0
        f_slot = jnp.clip(f_mb, 0, m - 1) % stages
        b_idx = jnp.clip(b_mb, 0, m - 1)
        b_slot = b_idx % stages

        # ---- forward ----
        x_in = jnp.where(my_stage == 0,
                         x_microbatches[jnp.clip(f_mb, 0, m - 1)],
                         a_recv[f_slot])
        y_out = stage_fn(stage_params, x_in)
        x_stash = jnp.where(do_f, x_stash.at[f_slot].set(x_in), x_stash)

        # ---- backward ----
        # One stage_fn transpose, seeded per identity: the last stage
        # seeds from the loss gradient, others from the received
        # cotangent (SPMD ticks are uniform across devices, so the seed
        # is a select, not a branch).
        xb = x_stash[b_slot]
        yb = y_microbatches[b_idx]
        y_b, vjp_fn = jax.vjp(stage_fn, stage_params, xb)
        loss_val, dldy = jax.value_and_grad(loss_fn)(y_b, yb)
        ct = jnp.where(is_last, dldy, g_recv[b_slot])
        gp, gx = vjp_fn(ct)
        grad_acc = jax.tree.map(
            lambda acc, g: acc + jnp.where(do_b, g, 0), grad_acc, gp)
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(do_b, is_last), loss_val, 0.0)

        # ---- communication (the inter-tick transport) ----
        with jax.named_scope("gloo_tpu.pp.fwd_shift"):
            sent_f = spmd.shift(
                jnp.where(do_f, y_out, jnp.zeros_like(y_out)), axis, 1)
        left_f = fwd_tbl[t, (my_stage - 1) % stages]
        take_f = jnp.logical_and(my_stage > 0, left_f >= 0)
        a_recv = jnp.where(
            take_f,
            a_recv.at[jnp.clip(left_f, 0, m - 1) % stages].set(sent_f),
            a_recv)
        with jax.named_scope("gloo_tpu.pp.bwd_shift"):
            sent_b = spmd.shift(jnp.where(do_b, gx, jnp.zeros_like(gx)),
                                axis, -1)
        right_b = bwd_tbl[t, (my_stage + 1) % stages]
        take_b = jnp.logical_and(my_stage < stages - 1, right_b >= 0)
        g_recv = jnp.where(
            take_b,
            g_recv.at[jnp.clip(right_b, 0, m - 1) % stages].set(sent_b),
            g_recv)

        return (x_stash, a_recv, g_recv, grad_acc, loss_acc), None

    def dev_varying(x):
        # Idempotent: zeros_like of the (already device-varying) stage
        # params is born varying; only fresh replicated zeros need the
        # cast for stable scan carry types under shard_map vma checking.
        if axis in getattr(jax.typeof(x), "vma", ()):
            return x
        return lax.pcast(x, (axis,), to="varying")

    stash0 = dev_varying(jnp.zeros((stages,) + x0.shape, x0.dtype))
    grad0 = jax.tree.map(
        lambda p: dev_varying(jnp.zeros_like(p)), stage_params)
    carry0 = (stash0, stash0, stash0, grad0,
              dev_varying(jnp.zeros((), jnp.float32)))
    (_, _, _, grads, loss_sum), _ = lax.scan(tick, carry0,
                                             jnp.arange(ticks))
    return grads, loss_sum
