"""ctypes binding to the tpucoll C core (csrc/tpucoll/capi.cc).

The native library is the host data plane of gloo_tpu: rendezvous stores, the
epoll TCP transport, and the collective schedules, all in C++ (matching the
reference's C++ core, /root/reference/gloo). This module only declares
prototypes and maps error codes onto Python exceptions.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "_native")
# TPUCOLL_LIB points at an alternate build (e.g. a sanitizer build).
_LIB_PATH = os.environ.get(
    "TPUCOLL_LIB", os.path.join(_NATIVE_DIR, "libtpucoll.so"))


class Error(RuntimeError):
    """Base error from the tpucoll native core."""


class IoError(Error):
    """Transport failure: peer died, connection reset, context poisoned."""


class TimeoutError(IoError):  # noqa: A001 - mirrors the C++ hierarchy
    """A blocking wait exceeded its deadline."""


class Aborted(Exception):
    """A wait was cancelled via abort_wait_send/abort_wait_recv."""


_TC_OK = 0
_TC_ERR = 1
_TC_ERR_TIMEOUT = 2
_TC_ERR_IO = 3
_TC_ERR_ABORTED = 4


def _build_native() -> None:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if not os.path.isdir(os.path.join(repo_root, "csrc")):
        # Installed package (site-packages): there is no source tree to
        # auto-build from. setup.py's build_py hook should have shipped
        # the .so in the wheel — if it's missing the install is broken.
        raise Error(
            f"native library missing at {_LIB_PATH} and no csrc/ beside "
            f"the package to build it from; reinstall (`pip install .` "
            f"from a source checkout) or run `make native` in the repo")
    subprocess.run(["make", "native"], cwd=repo_root, check=True,
                   capture_output=True)


def _load() -> ctypes.CDLL:
    if not os.path.exists(_LIB_PATH):
        _build_native()
    return ctypes.CDLL(_LIB_PATH)


_lib = _load()

_c = ctypes.c_void_p
_sz = ctypes.c_size_t
_i64 = ctypes.c_int64
_u64 = ctypes.c_uint64
_u32 = ctypes.c_uint32
_int = ctypes.c_int

_PROTOTYPES = {
    "tc_last_error": (ctypes.c_char_p, []),
    # stores
    "tc_hash_store_new": (_c, []),
    "tc_file_store_new": (_c, [ctypes.c_char_p]),
    "tc_prefix_store_new": (_c, [_c, ctypes.c_char_p]),
    "tc_tcp_store_server_new": (_c, [ctypes.c_char_p, ctypes.c_uint16]),
    "tc_tcp_store_server_port": (ctypes.c_uint16, [_c]),
    "tc_tcp_store_server_free": (None, [_c]),
    "tc_tcp_store_new": (_c, [ctypes.c_char_p, ctypes.c_uint16]),
    "tc_store_free": (None, [_c]),
    "tc_store_set": (_int, [_c, ctypes.c_char_p,
                            ctypes.POINTER(ctypes.c_uint8), _sz]),
    "tc_store_get": (_int, [_c, ctypes.c_char_p, _i64,
                            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                            ctypes.POINTER(_sz)]),
    "tc_buf_free": (None, [ctypes.POINTER(ctypes.c_uint8)]),
    "tc_store_add": (_int, [_c, ctypes.c_char_p, _i64,
                            ctypes.POINTER(_i64)]),
    "tc_store_delete": (_int, [_c, ctypes.c_char_p,
                               ctypes.POINTER(_int)]),
    "tc_store_list": (_int, [_c, ctypes.c_char_p,
                             ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                             ctypes.POINTER(_sz)]),
    # device / context
    "tc_device_new": (_c, [ctypes.c_char_p, ctypes.c_uint16,
                       ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                       ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p]),
    "tc_derive_keyring": (_int, [ctypes.c_char_p, _int, _int,
                                 ctypes.POINTER(
                                     ctypes.POINTER(ctypes.c_uint8))]),
    "tc_device_free": (None, [_c]),
    "tc_device_engine_stats": (None, [_c, ctypes.POINTER(_u64),
                                      ctypes.POINTER(_u64),
                                      ctypes.POINTER(_u64)]),
    "tc_uring_available": (_int, []),
    "tc_crypto_isa_tier": (_int, []),
    "tc_set_connect_debug_logger": (None, [_c]),
    "tc_context_new": (_c, [_int, _int]),
    "tc_context_set_timeout": (None, [_c, _i64]),
    "tc_context_connect": (_int, [_c, _c, _c]),
    "tc_context_fork": (_int, [_c, _c, _u32]),
    "tc_context_close": (_int, [_c]),
    "tc_context_free": (None, [_c]),
    # process-group subsystem: topology discovery + communicator split
    "tc_context_rank": (_int, [_c]),
    "tc_context_size": (_int, [_c]),
    "tc_context_set_host_id": (_int, [_c, ctypes.c_char_p]),
    "tc_topology_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_context_group_tag": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_split": (_int, [_c, _int, _int, _u32, ctypes.POINTER(_c)]),
    "tc_split_by_host": (_int, [_c, _u32, ctypes.POINTER(_c)]),
    "tc_next_slot": (_u64, [_c, _u32]),
    "tc_debug_dump": (None, [_c]),
    "tc_context_shm_stats": (None, [_c, ctypes.POINTER(_u64),
                             ctypes.POINTER(_u64),
                             ctypes.POINTER(_int)]),
    "tc_trace_start": (None, [_c]),
    "tc_trace_stop": (None, [_c]),
    "tc_trace_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    # metrics + straggler watchdog
    "tc_metrics_enable": (None, [_c, _int]),
    "tc_metrics_enabled": (_int, [_c]),
    "tc_metrics_set_watchdog": (None, [_c, _i64]),
    "tc_metrics_json": (_int, [_c, _int, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    # flight recorder
    "tc_flightrec_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_flightrec_dump": (_int, [_c, ctypes.c_char_p]),
    "tc_flightrec_seq": (_u64, [_c]),
    "tc_flightrec_install_signal_handler": (None, []),
    # phase-level collective profiler
    "tc_profile_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_profile_enable": (None, [_c, _int]),
    "tc_profile_enabled": (_int, [_c]),
    # causal span recorder (cross-rank critical-path tracing)
    "tc_spans_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_spans_enable": (None, [_c, _int]),
    "tc_spans_enabled": (_int, [_c]),
    # in-band fleet observability plane (hierarchical telemetry fold)
    "tc_fleetobs_start": (_int, [_c]),
    "tc_fleetobs_stop": (_int, [_c]),
    "tc_fleetobs_running": (_int, [_c]),
    "tc_fleetobs_set_aux": (_int, [_c, ctypes.c_char_p]),
    "tc_fleet_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    # elastic membership plane (lease liveness + epoch transitions)
    "tc_elastic_new": (_c, [_c, _c, _int, _int, _int, _int,
                            ctypes.c_char_p, _i64]),
    "tc_elastic_rebuild": (_int, [_c, _i64, ctypes.POINTER(_c)]),
    "tc_elastic_note_failure": (_int, [_c, ctypes.c_char_p]),
    "tc_elastic_stop": (_int, [_c]),
    "tc_elastic_free": (None, [_c]),
    "tc_elastic_epoch": (_u64, [_c]),
    "tc_elastic_head_epoch": (_u64, [_c]),
    "tc_elastic_poll": (_int, [_c]),
    "tc_elastic_status_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    # deterministic fault-injection plane
    "tc_fault_install": (_int, [ctypes.c_char_p]),
    "tc_fault_clear": (None, []),
    "tc_fault_report": (_int, [ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    # bootstrap plane (lazy pair broker + leader-relayed rendezvous)
    "tc_boot_rendezvous_bench": (_int, [ctypes.c_char_p, _int, _int, _int,
                                        _int, _int, _i64,
                                        ctypes.POINTER(ctypes.POINTER(
                                            ctypes.c_uint8)),
                                        ctypes.POINTER(_sz)]),
    # collective autotuning plane
    "tc_tune": (_int, [_c, _sz, _sz, _int, _int, _u32, _i64,
                       ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                       ctypes.POINTER(_sz)]),
    "tc_tuning_install": (_int, [_c, ctypes.c_char_p]),
    "tc_tuning_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    # collective schedule plane (algorithms as data)
    "tc_schedule_install": (_int, [_c, ctypes.c_char_p]),
    "tc_schedule_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_schedule_list": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_schedule_describe": (_int, [_c, ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.POINTER(
                                        ctypes.c_uint8)),
                                    ctypes.POINTER(_sz)]),
    "tc_schedule_generate": (_int, [ctypes.c_char_p, _int, ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.POINTER(
                                        ctypes.c_uint8)),
                                    ctypes.POINTER(_sz)]),
    "tc_schedule_families": (_int, [ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_schedule_verify": (_int, [ctypes.c_char_p]),
    # collectives
    "tc_barrier": (_int, [_c, _int, _u32, _i64]),
    "tc_broadcast": (_int, [_c, _c, _sz, _int, _int, _int, _u32, _i64]),
    "tc_allreduce": (_int, [_c, _c, _c, _sz, _int, _int, _int, _u32,
                            _i64]),
    # zero-copy in-place entries (persistent-plan hot path)
    "tc_allreduce_inplace": (_int, [_c, _c, _sz, _int, _int, _int, _u32,
                                    _i64]),
    "tc_reduce_scatter_inplace": (_int, [_c, _c, ctypes.POINTER(_sz),
                                         _int, _int, _int, _u32, _i64]),
    # plan-cache introspection
    "tc_plan_cache_size": (_sz, [_c]),
    "tc_plan_cache_clear": (None, [_c]),
    "tc_allreduce_multi": (_int, [_c, ctypes.POINTER(_c),
                                  ctypes.POINTER(_c), _sz, _sz, _int,
                                  _int, _int, _u32, _i64]),
    "tc_reduce": (_int, [_c, _c, _c, _sz, _int, _int, _int, _int, _u32,
                         _i64]),
    "tc_allreduce_fn": (_int, [_c, _c, _c, _sz, _int, _c, _int, _u32,
                               _i64]),
    "tc_allreduce_multi_fn": (_int, [_c, ctypes.POINTER(_c),
                                     ctypes.POINTER(_c), _sz, _sz, _int,
                                     _c, _int, _u32, _i64]),
    "tc_reduce_fn": (_int, [_c, _c, _c, _sz, _int, _c, _int, _int, _u32,
                            _i64]),
    "tc_reduce_scatter_fn": (_int, [_c, _c, _c, ctypes.POINTER(_sz), _int,
                                    _c, _int, _u32, _i64]),
    "tc_gather": (_int, [_c, _c, _c, _sz, _int, _int, _u32, _i64]),
    "tc_gatherv": (_int, [_c, _c, _c, ctypes.POINTER(_sz), _int, _int,
                          _u32, _i64]),
    "tc_scatter": (_int, [_c, _c, _c, _sz, _int, _int, _u32, _i64]),
    "tc_allgather": (_int, [_c, _c, _c, _sz, _int, _int, _u32, _i64]),
    "tc_allgatherv": (_int, [_c, _c, _c, ctypes.POINTER(_sz), _int, _u32,
                             _i64]),
    "tc_alltoall": (_int, [_c, _c, _c, _sz, _int, _u32, _i64]),
    "tc_alltoallv": (_int, [_c, _c, ctypes.POINTER(_sz), _c,
                            ctypes.POINTER(_sz), _int, _u32, _i64]),
    "tc_reduce_scatter": (_int, [_c, _c, _c, ctypes.POINTER(_sz), _int,
                                 _int, _int, _u32, _i64]),
    # int8 block-quantized wire codec (the kRingQ8Wire per-hop kernels)
    "tc_q8_block": (_sz, []),
    "tc_q8_wire_bytes": (_sz, [_sz]),
    "tc_q8_encode": (_int, [_c, _sz, _c, _sz]),
    "tc_q8_decode": (_int, [_c, _sz, _c, _sz]),
    # int4 packed-nibble wire codec (the kRingQ4Wire per-hop kernels)
    "tc_q4_block": (_sz, []),
    "tc_q4_wire_bytes": (_sz, [_sz]),
    "tc_q4_encode": (_int, [_c, _sz, _c, _sz]),
    "tc_q4_decode": (_int, [_c, _sz, _c, _sz]),
    # sharded codec surface: the pool-sharded kernels the pipelined wire
    # rings run (kind: 0 = bf16, 1 = q8, 2 = q4)
    "tc_codec_threads": (_int, []),
    "tc_codec_pipeline": (_int, []),
    "tc_codec_encode_sharded": (_int, [_int, _c, _sz, _c, _sz, _sz]),
    "tc_codec_accumulate_sharded": (_int, [_int, _c, _c, _sz, _sz, _sz]),
    # async collective engine + work handles
    "tc_async_new": (_c, [_c, _int, _u32]),
    "tc_async_shutdown": (_int, [_c]),
    "tc_async_free": (None, [_c]),
    "tc_async_lanes": (_int, [_c]),
    "tc_async_lane_context": (_c, [_c, _int]),
    "tc_async_stats_json": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_async_allreduce": (_c, [_c, _c, _c, _sz, _int, _int, _int, _i64]),
    "tc_async_allreduce_inplace": (_c, [_c, _c, _sz, _int, _int, _int,
                                        _i64]),
    "tc_async_reduce_scatter": (_c, [_c, _c, _c, ctypes.POINTER(_sz),
                                     _int, _int, _int, _int, _i64]),
    "tc_async_allgather": (_c, [_c, _c, _c, _sz, _int, _int, _i64]),
    "tc_work_wait": (_int, [_c, _i64]),
    "tc_work_status": (_int, [_c]),
    "tc_work_error_message": (_int, [_c, ctypes.POINTER(ctypes.POINTER(
        ctypes.c_uint8)), ctypes.POINTER(_sz)]),
    "tc_work_free": (None, [_c]),
    # p2p
    "tc_buffer_new": (_c, [_c, _c, _sz]),
    "tc_buffer_free": (None, [_c]),
    "tc_buffer_send": (_int, [_c, _int, _u64, _sz, _sz]),
    "tc_buffer_recv": (_int, [_c, _int, _u64, _sz, _sz]),
    "tc_buffer_recv_any": (_int, [_c, ctypes.POINTER(_int), _sz, _u64, _sz,
                                  _sz]),
    "tc_buffer_wait_send": (_int, [_c, _i64]),
    "tc_buffer_wait_recv": (_int, [_c, _i64, ctypes.POINTER(_int)]),
    "tc_buffer_wait_put": (_int, [_c, _i64, ctypes.POINTER(_int)]),
    "tc_remote_key_size": (_sz, []),
    "tc_buffer_remote_key": (_int, [_c, ctypes.c_char_p, _sz]),
    "tc_buffer_put": (_int, [_c, ctypes.c_char_p, _sz, _sz, _sz, _sz,
                             _int]),
    "tc_buffer_get": (_int, [_c, ctypes.c_char_p, _sz, _u64, _sz, _sz,
                             _sz]),
    "tc_buffer_abort_wait_send": (None, [_c]),
    "tc_buffer_abort_wait_recv": (None, [_c]),
}

for _name, (_restype, _argtypes) in _PROTOTYPES.items():
    _fn = getattr(_lib, _name)
    _fn.restype = _restype
    _fn.argtypes = _argtypes


def last_error() -> str:
    msg = _lib.tc_last_error()
    return msg.decode("utf-8", "replace") if msg else ""


def check(code: int) -> None:
    """Raise the Python mapping of a TC_ERR_* code."""
    if code == _TC_OK:
        return
    msg = last_error()
    if code == _TC_ERR_TIMEOUT:
        raise TimeoutError(msg)
    if code == _TC_ERR_IO:
        raise IoError(msg)
    if code == _TC_ERR_ABORTED:
        raise Aborted(msg)
    raise Error(msg)


def check_handle(handle: int | None) -> int:
    if not handle:
        raise Error(last_error())
    return handle


def copy_out(fn, *args) -> bytes:
    """Call a C function whose trailing parameters are (uint8_t** out,
    size_t* out_len), copy the buffer, and free it via tc_buf_free."""
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    check(fn(*args, ctypes.byref(out), ctypes.byref(out_len)))
    try:
        return bytes(bytearray(out[: out_len.value]))
    finally:
        lib.tc_buf_free(out)


lib = _lib
