"""DDP-style gradient-bucket coalescing over the async collective engine.

A training step produces hundreds of small heterogeneous gradient tensors;
allreducing them one by one pays full per-op latency (ctypes round trip,
schedule setup, per-segment rendezvous) serially per tensor. The
:class:`GradientBucketer` flattens them into large per-dtype flat buckets
(``TPUCOLL_BUCKET_BYTES``, default 25 MiB — the PyTorch DDP default
``bucket_cap_mb`` that the reference backs as a ProcessGroup backend) and
issues each bucket's allreduce ASYNC the moment it fills, so bucket k+1's
pack/copy overlaps bucket k's wire time on the engine's lanes
(inter-collective pipelining, docs/async.md). ``finish()`` waits in issue
order and unflattens results back into the original tensors in place.

Usage::

    engine = ctx.async_engine(lanes=2)          # collective, once
    bucketer = GradientBucketer(engine)
    for step in range(steps):
        for g in grads:                          # same order on every rank
            bucketer.add(g)
        bucketer.finish()                        # grads now hold the sums

Ordering contract: every rank must ``add`` the same tensors (shape, dtype)
in the same order and call ``finish()`` at the same point — the buckets
then line up across ranks exactly like a sequence of blocking collectives,
just issued asynchronously (same contract as torch DDP's reducer).

Error contract: bucket failures surface TYPED at the ``finish()`` /
``wait()`` boundary — IoError / TimeoutError / Aborted with the blamed
lane and op named. The collectives run in place, so after an error every
tensor added since the last successful ``finish()`` has UNDEFINED
contents (the undefined window opens at issue time, docs/errors.md
"In-place collectives"); discard the bucketer, rebuild the context
(gloo_tpu.resilience), and restore gradients from application state.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from gloo_tpu import core
from gloo_tpu._lib import Aborted, Error

__all__ = ["GradientBucketer", "DEFAULT_BUCKET_BYTES"]

DEFAULT_BUCKET_BYTES = 25 << 20  # torch DDP's bucket_cap_mb default


def _scale_inplace(arr: np.ndarray, scale: float) -> None:
    # Integer dtypes: arr *= dtype(scale) would multiply by int(0.5)==0;
    # take the truncated mean instead, matching the sequential
    # HostGradSync path (arr / size, then cast back).
    if np.issubdtype(arr.dtype, np.inexact):
        arr *= arr.dtype.type(scale)
    else:
        np.copyto(arr, (arr * scale).astype(arr.dtype))


def _bucket_bytes_from_env() -> int:
    raw = os.environ.get("TPUCOLL_BUCKET_BYTES")
    if not raw:
        return DEFAULT_BUCKET_BYTES
    try:
        value = int(raw)
        if value <= 0:
            raise ValueError(raw)
    except ValueError:
        raise Error(f"TPUCOLL_BUCKET_BYTES: not a positive integer: "
                    f"{raw!r}") from None
    return value


class GradientBucketer:
    """Coalesce many small arrays into flat per-dtype async allreduces.

    One instance is reusable across steps (add... add, finish; repeat).
    Not thread-safe; drive it from one thread per rank.
    """

    def __init__(self, engine: "core.AsyncEngine",
                 bucket_bytes: Optional[int] = None, op="sum",
                 average: bool = False,
                 timeout: Optional[float] = None,
                 wire: Optional[str] = None):
        """engine: the context's AsyncEngine (Context.async_engine()).
        bucket_bytes: flush threshold per dtype bucket (default
        TPUCOLL_BUCKET_BYTES, else 25 MiB). op: reduction (callable
        reductions are unsupported — async contract). average=True
        divides every result by world size after the wait (requires
        op="sum"). timeout: per-bucket collective timeout. wire: opt-in
        wire compression for FLOAT32 buckets — "q8" / "bf16" / "lossy",
        the Context.allreduce shorthand (docs/algorithms.md precision
        contract); other dtypes' buckets always ride the lossless path
        (the codecs are float32-only). Requires op="sum"."""
        if callable(op):
            raise Error("GradientBucketer does not support callable "
                        "reductions (async ops run on lane threads)")
        if average and core.ReduceOp.parse(op) != core.ReduceOp.SUM:
            raise Error("average=True requires op='sum'")
        if wire is not None:
            if wire not in core.Context._WIRE_ALGORITHMS:
                raise Error(f"wire= must be one of "
                            f"{sorted(core.Context._WIRE_ALGORITHMS)}, "
                            f"got {wire!r}")
            if core.ReduceOp.parse(op) != core.ReduceOp.SUM:
                raise Error("wire compression requires op='sum'")
        self._wire = wire
        self._engine = engine
        self._bucket_bytes = (bucket_bytes if bucket_bytes is not None
                              else _bucket_bytes_from_env())
        if self._bucket_bytes <= 0:
            raise Error("bucket_bytes must be positive")
        self._op = op
        self._average = average
        self._timeout = timeout
        # dtype name -> (list of member arrays, running byte total).
        self._pending = {}
        # Issued buckets in issue order: (work, flat, members); flat is
        # None when an oversized array was issued in place.
        self._issued: List = []
        # (dtype name, elements) -> free flat buckets, reused across
        # steps. A training loop adds the same tensors every step, so
        # bucket shapes repeat exactly — reusing the flat buffer keeps
        # its POINTER stable, which is what turns every bucket allreduce
        # into a native plan-cache hit (zero allocations and zero
        # buffer registrations on the lane contexts; docs/design.md).
        # Buffers return to the pool only after their wait completed,
        # so a pooled buffer is never concurrently owned by a lane.
        self._flat_pool = {}

    @property
    def in_flight(self) -> int:
        """Buckets issued and not yet waited (the finish() backlog)."""
        return len(self._issued)

    def add(self, array: np.ndarray) -> None:
        """Queue one tensor. Must be a C-contiguous numpy array; every
        rank must add matching tensors in matching order. The array must
        not be touched again until finish() returns."""
        if not isinstance(array, np.ndarray):
            raise TypeError(f"add() needs a numpy array, "
                            f"got {type(array)}")
        if not array.flags.c_contiguous:
            raise Error("add() needs a C-contiguous array")
        if array.nbytes >= self._bucket_bytes:
            # Already bucket-sized: skip the pack/unpack copy entirely
            # and allreduce it in place as its own bucket. Issue order
            # is preserved relative to the flat buckets.
            self._flush_dtype(array.dtype.name)
            work = self._engine.allreduce_async(
                array, op=self._op, timeout=self._timeout,
                wire=self._wire_for(array.dtype))
            self._issued.append((work, None, None))
            return
        members, nbytes = self._pending.get(array.dtype.name, ([], 0))
        members.append(array)
        nbytes += array.nbytes
        self._pending[array.dtype.name] = (members, nbytes)
        if nbytes >= self._bucket_bytes:
            self._flush_dtype(array.dtype.name)

    def flush(self) -> None:
        """Issue every partially-filled bucket (finish() does this)."""
        for dtype in list(self._pending):
            self._flush_dtype(dtype)

    def _wire_for(self, dtype) -> Optional[str]:
        # The wire codecs are float32-only; every other dtype's bucket
        # stays lossless (the deterministic subset of the add stream
        # that is float32 is identical on every rank, so the per-bucket
        # algorithm choice is too).
        return self._wire if dtype == np.float32 else None

    def _take_flat(self, dtype, total: int) -> np.ndarray:
        stack = self._flat_pool.get((np.dtype(dtype).name, total))
        if stack:
            return stack.pop()
        return np.empty(total, dtype=dtype)

    def _release_flat(self, flat: np.ndarray) -> None:
        key = (flat.dtype.name, int(flat.size))
        stack = self._flat_pool.setdefault(key, [])
        # Bound the pool: at most lanes+1 buckets of one shape are ever
        # in flight, so a small cap covers the steady state.
        if len(stack) < 4:
            stack.append(flat)

    def _flush_dtype(self, dtype: str) -> None:
        entry = self._pending.pop(dtype, None)
        if entry is None or not entry[0]:
            return
        members, _ = entry
        total = sum(int(m.size) for m in members)
        flat = self._take_flat(members[0].dtype, total)
        off = 0
        for m in members:
            flat[off:off + m.size] = m.reshape(-1)
            off += m.size
        work = self._engine.allreduce_async(
            flat, op=self._op, timeout=self._timeout,
            wire=self._wire_for(flat.dtype))
        self._issued.append((work, flat, members))

    def finish(self, timeout: Optional[float] = None) -> None:
        """Flush partial buckets, wait for every issued bucket in issue
        order, and unflatten the reduced values back into the original
        arrays in place (divided by world size when average=True;
        integer dtypes get the truncated mean, matching the sequential
        ``arr / size`` then-cast path).

        On a bucket failure the typed error propagates immediately:
        earlier buckets are already unpacked, the failing and later
        buckets' tensors are undefined, and the bucketer drains its
        backlog (waiting out still-running buckets so no lane thread
        can touch a dropped buffer) — discard it and rebuild the
        context before retrying. `timeout` bounds each individual wait
        (None: rely on the per-bucket collective timeouts)."""
        self.flush()
        scale = (1.0 / self._engine._context.size if self._average
                 else None)
        try:
            while self._issued:
                work, flat, members = self._issued[0]
                work.wait(timeout)
                if flat is None:
                    if scale is not None:
                        _scale_inplace(work.result, scale)
                else:
                    if scale is not None:
                        _scale_inplace(flat, scale)
                    off = 0
                    for m in members:
                        np.copyto(m, flat[off:off + m.size]
                                  .reshape(m.shape))
                        off += m.size
                    # Waited out and unpacked: safe to reuse next step
                    # (same shape -> same pointer -> plan-cache hit).
                    self._release_flat(flat)
                self._issued.pop(0)
        except BaseException:
            self._drain_after_error(timeout)
            raise

    def _drain_after_error(self, timeout: Optional[float]) -> None:
        # Later buckets may still be RUNNING on other lanes; dropping
        # their Work/flat references would free numpy buffers the lane
        # threads are still reducing into (use-after-free). Wait each
        # one out — swallowing its error, the first failure is what
        # propagates — and keep anything still in flight after its wait
        # pinned in the backlog (released once it completes, or when
        # the engine shuts down and joins its lanes).
        remaining, self._issued = self._issued, []
        for entry in remaining:
            work = entry[0]
            try:
                work.wait(timeout)
            except (Error, Aborted):
                if not work.test():
                    self._issued.append(entry)
