"""Checkpoint/resume for elastic training (beyond the reference).

The reference has no checkpoint story (SURVEY.md §5: contexts are
immutable, recovery is "rebuild and start over") — which leaves the
actual production question unanswered: after `resilience.rebuild_after_
failure` shrinks the group, where does the model state come from? This
module closes that loop with an orbax-backed step store:

    ckpt = StepCheckpointer(dir)
    ckpt.save(step, {"params": params, "opt": opt_state})
    ...crash, rebuild_after_failure -> new (rank, size)...
    step, state = ckpt.load_latest(template)   # shardings preserved

Checkpoints are rank-0-writes / everyone-reads (DDP-style replicated
state; sharded state restores onto whatever shardings the template
carries, so a post-failure SMALLER mesh re-lays the arrays out
automatically — orbax resharding on restore).

Note for host-plane-only trainer processes: orbax imports jax, whose
first backend initialization follows the environment's platform pinning;
processes that do not need an accelerator should force the CPU platform
(jax.config.update("jax_platforms", "cpu")) before constructing a
StepCheckpointer to avoid paying accelerator plugin startup per worker.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional, Tuple

_STEP_RE = re.compile(r"^step_(\d+)$")


class StepCheckpointer:
    """Durable (dir-per-step, atomic-rename) pytree checkpoints.

    Built on orbax StandardCheckpointer: jax arrays (with shardings),
    numpy arrays, and python scalars all round-trip. Safe against a crash
    mid-save: orbax commits via rename, and load_latest skips uncommitted
    step dirs.
    """

    def __init__(self, directory: str, keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._keep = keep
        self._ckpt = ocp.StandardCheckpointer()

    def _step_path(self, step: int) -> str:
        return os.path.join(self._dir, f"step_{step}")

    def steps(self):
        """Committed step numbers, ascending."""
        out = []
        for name in os.listdir(self._dir):
            m = _STEP_RE.match(name)
            if m and self._is_committed(os.path.join(self._dir, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    @staticmethod
    def _is_committed(path: str) -> bool:
        # Orbax writes into a tmp dir and renames on commit; a committed
        # checkpoint contains its metadata file. The writer's _gc may
        # delete a step between our isdir and listdir (rank-0-writes /
        # everyone-reads has no reader coordination) — a vanished dir is
        # simply not a candidate.
        try:
            return os.path.isdir(path) and any(
                name.startswith("_CHECKPOINT_METADATA") or name == "d"
                or name.endswith(".zarray") or name == "_METADATA"
                for name in os.listdir(path))
        except (FileNotFoundError, NotADirectoryError):
            return False

    def save(self, step: int, state: Any, *, force: bool = False) -> None:
        """Write `state` under `step` (typically from rank 0 only —
        checkpoints are rank-0-writes / everyone-reads). Blocks until the
        checkpoint is COMMITTED: orbax saves asynchronously by default,
        and an uncommitted step is exactly what a crash-resume contract
        cannot tolerate."""
        self._ckpt.save(self._step_path(step), state, force=force)
        if hasattr(self._ckpt, "wait_until_finished"):
            self._ckpt.wait_until_finished()
        self._gc()

    def load(self, step: int, template: Optional[Any] = None) -> Any:
        """Restore a specific step. With a template (matching pytree of
        arrays or jax.ShapeDtypeStruct, optionally carrying shardings),
        arrays restore onto the template's shardings — a smaller
        post-failure mesh re-lays the state out automatically."""
        if template is None:
            return self._ckpt.restore(self._step_path(step))
        return self._ckpt.restore(self._step_path(step), template)

    def load_latest(self, template: Optional[Any] = None
                    ) -> Tuple[Optional[int], Optional[Any]]:
        """(step, state) of the newest committed checkpoint, or
        (None, None) when the directory has none. Falls back to the
        next-newest step if the writer's retention GC deletes one
        between listing and restore."""
        for step in reversed(self.steps()):
            try:
                return step, self.load(step, template)
            except FileNotFoundError:
                continue
        return None, None

    def _gc(self) -> None:
        import shutil

        steps = self.steps()
        for step in steps[:-self._keep] if self._keep > 0 else []:
            shutil.rmtree(self._step_path(step), ignore_errors=True)
