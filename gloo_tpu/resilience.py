"""Failure recovery: rebuild the process group after a rank dies.

The error contract (docs/errors.md, matching the reference's
docs/errors.md) is that a transport failure poisons the context and the
application re-rendezvouses. This module ships that pattern as code
instead of advice: `rebuild_after_failure` coordinates the survivors of a
failed collective into a fresh, contiguous, smaller group over the same
store.

Protocol (store-side, no working mesh required):
 1. every survivor announces itself under a new generation namespace and
    bumps a membership counter;
 2. survivors wait a settle window for stragglers, then read the final
    count and the announced ranks;
 3. old ranks map to new contiguous ranks by sort order, and a normal
    full-mesh bootstrap runs in the generation's namespace.

Generations make retries safe: a survivor that crashes during rebuild
just triggers another round with generation + 1.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional, Tuple

import gloo_tpu
from gloo_tpu.utils.flightrec import (DesyncError, describe_event,
                                      detect_desync, TAIL_K)


def _flightrec_tail(failed_context) -> Optional[dict]:
    """Compact flight-recorder tail for the store exchange: the last
    TAIL_K COLLECTIVE ops' (cseq, fingerprint, description, state) plus
    the frontier seq — everything the cross-rank desync comparison
    needs, at store-value size. Collectives only: p2p entries carry no
    comparable cseq/fingerprint, and a p2p-heavy workload must not flush
    the collective evidence out of the exchanged window
    (docs/flightrec.md "Desync detection")."""
    try:
        fr = failed_context.flightrec()
    except Exception:  # noqa: BLE001 - a dead context must not block rebuild
        return None
    events = [e for e in fr.get("events", [])
              if e.get("cseq") is not None][-TAIL_K:]
    if not events:
        return None
    return {"next_seq": fr.get("next_seq", 0),
            "tail": [{"seq": e["seq"], "cseq": e["cseq"],
                      "fp": e["fp"], "state": e.get("state"),
                      "desc": describe_event(e)} for e in events]}


def _stall_evidence(failed_context) -> Optional[dict]:
    """Extract the failure verdict from a poisoned context: which peer
    this rank was blocked on (watchdog stall), or — when the watchdog
    never fired because detection was EOF-fast, e.g. a SIGKILL'd peer —
    which peer's link died first (the transport-failure record
    Context.onPairError feeds). Either way the evidence also carries the
    flight recorder's fingerprint tail, so the collected reports can
    distinguish a stalled-but-matching schedule from a desync
    (analyze_stall_reports). Returns None when no source has anything
    to say (or the context is unreadable)."""
    evidence = None
    try:
        snap = failed_context.metrics()
    except Exception:  # noqa: BLE001 - a dead context must not block rebuild
        snap = None
    if snap is not None:
        last = snap.get("watchdog", {}).get("last")
        failure = snap.get("transport_failure")
        if last:
            evidence = {"suspect": last.get("peer", -1),
                        "op": last.get("op"), "slot": last.get("slot"),
                        "waited_ms": last.get("waited_us", 0) // 1000}
            peer = last.get("peer", -1)
            transport = snap.get("transport", {})
            if peer in transport:
                evidence["peer_progress_age_ms"] = (
                    transport[peer].get("last_progress_age_us", -1) // 1000)
        elif failure and failure.get("peer", -1) >= 0:
            evidence = {"suspect": failure["peer"], "op": "transport",
                        "error": str(failure.get("message", ""))[:160],
                        "failures": failure.get("count", 1)}
    tail = _flightrec_tail(failed_context)
    if evidence is None and tail is None:
        return None
    if evidence is None:
        # No single peer to blame (e.g. a timeout caused by a schedule
        # desync) — the fingerprint tail IS the evidence.
        evidence = {"suspect": -1, "op": None}
    if tail is not None:
        evidence["flightrec"] = tail
    return evidence


def stall_reports(store: "gloo_tpu.Store", generation: int,
                  old_size: int) -> Dict[int, dict]:
    """Read every survivor's published stall evidence for `generation`
    (written by rebuild_after_failure when failed_context is passed).
    The modal NON-NEGATIVE `suspect` across reports is the rank to
    blame — since the flight recorder, ranks with nothing to blame also
    publish (suspect -1, fingerprint tail only), so filter those out or
    use `analyze_stall_reports`, which applies the full blame order
    (desync > modal suspect) and names the culprit for you."""
    gen = gloo_tpu.PrefixStore(store, f"rebuild/{generation}")
    reports = {}
    for r in range(old_size):
        try:
            raw = gen.get(f"stall/{r}", timeout=0.001)
        except gloo_tpu.Error:
            continue
        try:
            reports[r] = json.loads(raw.decode())
        except ValueError:
            continue
    return reports


def analyze_stall_reports(reports: Dict[int, dict]) -> dict:
    """Cross-rank verdict over `stall_reports` output.

    Returns {"kind": "desync" | "stall" | "unknown", "blamed_ranks",
    "message", "desync": <detect_desync report or None>,
    "suspects": {rank: votes}}. A fingerprint mismatch at a shared seq
    (two ranks issued DIFFERENT collectives) wins over everything else:
    a desync explains every downstream stall, and no rebuild can fix
    it — the application's schedule itself diverged. Raise it as a
    typed error with `raise_on_desync_reports`."""
    tails = {r: rep.get("flightrec", {}).get("tail", [])
             for r, rep in reports.items()}
    desync = detect_desync(tails)
    suspects: Dict[int, int] = {}
    for rep in reports.values():
        s = rep.get("suspect", -1)
        if isinstance(s, int) and s >= 0:
            suspects[s] = suspects.get(s, 0) + 1
    if desync is not None:
        return {"kind": "desync", "blamed_ranks": desync["blamed_ranks"],
                "message": desync["message"], "desync": desync,
                "suspects": suspects}
    if suspects:
        top = max(suspects.items(), key=lambda kv: kv[1])[0]
        return {"kind": "stall", "blamed_ranks": [top],
                "message": f"survivors blame rank {top}", "desync": None,
                "suspects": suspects}
    return {"kind": "unknown", "blamed_ranks": [],
            "message": "no evidence published", "desync": None,
            "suspects": {}}


def raise_on_desync_reports(reports: Dict[int, dict]) -> dict:
    """`analyze_stall_reports`, raising the typed ``DesyncError`` when
    the reports show a schedule divergence; returns the verdict
    otherwise."""
    verdict = analyze_stall_reports(reports)
    if verdict["kind"] == "desync":
        raise DesyncError(verdict["message"], verdict)
    return verdict


def rebuild_after_failure(store: "gloo_tpu.Store", device: "gloo_tpu.Device",
                          old_rank: int, old_size: int, generation: int,
                          settle: float = 1.0, timeout: float = 30.0,
                          min_size: int = 2, failed_context=None
                          ) -> Tuple[Optional["gloo_tpu.Context"], int, int]:
    """Form a new group from whoever shows up.

    Returns (context, new_rank, new_size); context is None when fewer than
    `min_size` survivors remain (caller decides whether to continue solo).
    `generation` must increase on every rebuild attempt (start at 1).

    Pass the poisoned context as `failed_context` to feed the straggler
    watchdog's evidence into recovery: this rank's last-stall record
    (which peer/slot it was blocked on, per docs/observability.md) is
    published under the generation namespace so survivors — and the
    operator — can cite WHICH rank stalled instead of guessing. Read the
    collected evidence with `stall_reports(store, generation, old_size)`.
    """
    gen = gloo_tpu.PrefixStore(store, f"rebuild/{generation}")
    if failed_context is not None:
        evidence = _stall_evidence(failed_context)
        if evidence is not None:
            gen.set(f"stall/{old_rank}", json.dumps(evidence).encode())
    gen.set(f"alive/{old_rank}", str(time.time()).encode())
    gen.add("count", 1)
    deadline = time.time() + timeout

    # Membership settles when no new survivor has announced for `settle`
    # seconds. Survivors detect the failure at different times — a rank
    # blocked on the dead peer only notices at its operation timeout — so
    # `settle` must exceed the slowest survivor's detection lag (bound it
    # by the per-op timeout your collectives use).
    def roll_call():
        found = []
        for r in range(old_size):
            try:
                gen.get(f"alive/{r}", timeout=0.001)
                found.append(r)
            except gloo_tpu.Error:
                continue
        return found

    last = -1
    last_change = time.time()
    survivors = []
    while True:
        count = gen.add("count", 0)
        now = time.time()
        if count != last:
            last, last_change = count, now
        elif now - last_change >= settle:
            survivors = roll_call()
            # Re-verify: anyone arriving during the roll call restarts the
            # settle window instead of being split-brained out.
            if gen.add("count", 0) == last and len(survivors) == last:
                break
        if now > deadline:
            survivors = roll_call()
            break
        time.sleep(0.05)

    if len(survivors) < min_size or old_rank not in survivors:
        return None, -1, len(survivors)

    new_rank = survivors.index(old_rank)
    new_size = len(survivors)
    ctx = gloo_tpu.Context(new_rank, new_size, timeout=timeout)
    ctx.connect_full_mesh(gloo_tpu.PrefixStore(gen, "mesh"), device)
    if new_rank == 0:
        _reap_generation(gen)
    return ctx, new_rank, new_size


def _reap_generation(gen: "gloo_tpu.Store") -> None:
    """Reap this generation's bootstrap keys once the mesh is up, so
    repeated rebuilds against one long-lived store don't leak a full
    O(n^2) mesh-blob namespace per generation. Safe from new rank 0
    after its connect returns: every survivor batch-reads ALL mesh
    blobs before dialing rank 0, so a fully-accepted rank 0 proves the
    store phase is globally over. Scope discipline: only the bootstrap
    families go — `mesh/tc/` (address blobs + topology fingerprints)
    plus the roll-call keys — because POST-rebuild traffic (splits,
    tuner elections) rides the same store under `mesh/tpucoll/` and a
    wholesale reap would race it. The `stall/<rank>` evidence keys are
    deliberately KEPT — they are the post-mortem record stall_reports /
    analyze_stall_reports read after the fact (docs/faults.md)."""
    try:
        for key in gen.list("mesh/tc/"):
            gen.delete(key)
        for key in gen.list("alive/"):
            gen.delete(key)
        gen.delete("count")
    except gloo_tpu.Error:
        # Hygiene must never turn a successful rebuild into a failure.
        pass
