"""Failure recovery: rebuild the process group after a rank dies.

The error contract (docs/errors.md, matching the reference's
docs/errors.md) is that a transport failure poisons the context and the
application re-rendezvouses. This module ships that pattern as code
instead of advice: `rebuild_after_failure` coordinates the survivors of a
failed collective into a fresh, contiguous, smaller group over the same
store.

Protocol (store-side, no working mesh required):
 1. every survivor announces itself under a new generation namespace and
    bumps a membership counter;
 2. survivors wait a settle window for stragglers, then read the final
    count and the announced ranks;
 3. old ranks map to new contiguous ranks by sort order, and a normal
    full-mesh bootstrap runs in the generation's namespace.

Generations make retries safe: a survivor that crashes during rebuild
just triggers another round with generation + 1.
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional, Tuple

import gloo_tpu


def _stall_evidence(failed_context) -> Optional[dict]:
    """Extract the failure verdict from a poisoned context's metrics
    snapshot: which peer this rank was blocked on (watchdog stall), or —
    when the watchdog never fired because detection was EOF-fast, e.g. a
    SIGKILL'd peer — which peer's link died first (the transport-failure
    record Context.onPairError feeds). Returns None when neither source
    names a peer (or metrics are unavailable)."""
    try:
        snap = failed_context.metrics()
    except Exception:  # noqa: BLE001 - a dead context must not block rebuild
        return None
    last = snap.get("watchdog", {}).get("last")
    if last:
        evidence = {"suspect": last.get("peer", -1), "op": last.get("op"),
                    "slot": last.get("slot"), "waited_ms":
                    last.get("waited_us", 0) // 1000}
        peer = last.get("peer", -1)
        transport = snap.get("transport", {})
        if peer in transport:
            evidence["peer_progress_age_ms"] = (
                transport[peer].get("last_progress_age_us", -1) // 1000)
        return evidence
    failure = snap.get("transport_failure")
    if failure and failure.get("peer", -1) >= 0:
        return {"suspect": failure["peer"], "op": "transport",
                "error": str(failure.get("message", ""))[:160],
                "failures": failure.get("count", 1)}
    return None


def stall_reports(store: "gloo_tpu.Store", generation: int,
                  old_size: int) -> Dict[int, dict]:
    """Read every survivor's published stall evidence for `generation`
    (written by rebuild_after_failure when failed_context is passed).
    The modal `suspect` across reports is the rank to blame — recovery
    tooling can exclude it from re-admission or page its host."""
    gen = gloo_tpu.PrefixStore(store, f"rebuild/{generation}")
    reports = {}
    for r in range(old_size):
        try:
            raw = gen.get(f"stall/{r}", timeout=0.001)
        except gloo_tpu.Error:
            continue
        try:
            reports[r] = json.loads(raw.decode())
        except ValueError:
            continue
    return reports


def rebuild_after_failure(store: "gloo_tpu.Store", device: "gloo_tpu.Device",
                          old_rank: int, old_size: int, generation: int,
                          settle: float = 1.0, timeout: float = 30.0,
                          min_size: int = 2, failed_context=None
                          ) -> Tuple[Optional["gloo_tpu.Context"], int, int]:
    """Form a new group from whoever shows up.

    Returns (context, new_rank, new_size); context is None when fewer than
    `min_size` survivors remain (caller decides whether to continue solo).
    `generation` must increase on every rebuild attempt (start at 1).

    Pass the poisoned context as `failed_context` to feed the straggler
    watchdog's evidence into recovery: this rank's last-stall record
    (which peer/slot it was blocked on, per docs/observability.md) is
    published under the generation namespace so survivors — and the
    operator — can cite WHICH rank stalled instead of guessing. Read the
    collected evidence with `stall_reports(store, generation, old_size)`.
    """
    gen = gloo_tpu.PrefixStore(store, f"rebuild/{generation}")
    if failed_context is not None:
        evidence = _stall_evidence(failed_context)
        if evidence is not None:
            gen.set(f"stall/{old_rank}", json.dumps(evidence).encode())
    gen.set(f"alive/{old_rank}", str(time.time()).encode())
    gen.add("count", 1)
    deadline = time.time() + timeout

    # Membership settles when no new survivor has announced for `settle`
    # seconds. Survivors detect the failure at different times — a rank
    # blocked on the dead peer only notices at its operation timeout — so
    # `settle` must exceed the slowest survivor's detection lag (bound it
    # by the per-op timeout your collectives use).
    def roll_call():
        found = []
        for r in range(old_size):
            try:
                gen.get(f"alive/{r}", timeout=0.001)
                found.append(r)
            except gloo_tpu.Error:
                continue
        return found

    last = -1
    last_change = time.time()
    survivors = []
    while True:
        count = gen.add("count", 0)
        now = time.time()
        if count != last:
            last, last_change = count, now
        elif now - last_change >= settle:
            survivors = roll_call()
            # Re-verify: anyone arriving during the roll call restarts the
            # settle window instead of being split-brained out.
            if gen.add("count", 0) == last and len(survivors) == last:
                break
        if now > deadline:
            survivors = roll_call()
            break
        time.sleep(0.05)

    if len(survivors) < min_size or old_rank not in survivors:
        return None, -1, len(survivors)

    new_rank = survivors.index(old_rank)
    new_size = len(survivors)
    ctx = gloo_tpu.Context(new_rank, new_size, timeout=timeout)
    ctx.connect_full_mesh(gloo_tpu.PrefixStore(gen, "mesh"), device)
    return ctx, new_rank, new_size
