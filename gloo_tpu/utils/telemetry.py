"""Live in-process telemetry endpoint: scrape metrics, probe health,
pull phase profiles and flight-recorder rings over HTTP.

The observability stack (docs/observability.md) is pull-from-Python:
``ctx.metrics()`` / ``ctx.profile()`` / ``ctx.flightrec()`` all require
application-code cooperation. A production fleet wants the opposite —
Prometheus scrapes ``/metrics`` on its own schedule, an orchestrator
health-checks ``/healthz``, and an engineer curls a live rank's
``/profile.json`` mid-incident without touching the training loop.
:func:`serve_telemetry` starts a daemon-thread HTTP server bound to a
context (or any object with the same ``metrics()``/``profile()``/
``flightrec()`` surface, e.g. an ``ElasticContext``):

======================  ================================================
``GET /metrics``        Prometheus text exposition (utils.metrics)
``GET /healthz``        200 when healthy; 503 with a JSON reason list
                        when the watchdog recently recorded a stall, a
                        transport failure was observed, or the elastic
                        plane shows this worker superseded / evicted /
                        below min size
``GET /profile.json``   the phase profiler's per-op breakdown ring
``GET /spans``          the causal span recorder's step-level ring
                        (docs/critpath.md; feed tools/critpath_view.py)
``GET /flightrec``      the always-on flight-recorder ring
``GET /fleet``          the merged fleet observability document (rank 0
                        with ``ctx.fleetobs_start()`` running: coverage,
                        straggler leaderboard, slow links, anomalies;
                        a role stub elsewhere — docs/fleet.md)
``POST /flightrec/dump``  write this rank's ring to the dump directory
                        (guarded: POST-only, plus the ``token`` check
                        below when configured)
======================  ================================================

Security: the server binds ``127.0.0.1`` by default — these endpoints
expose operational detail (peer addresses, error strings) and the dump
route writes files, so exposing them beyond the host is an explicit
opt-in (``host="0.0.0.0"``) that should ride behind ``token=`` /
``TPUCOLL_TELEMETRY_TOKEN``. When a token is configured EVERY route
requires it (``X-TpuColl-Token`` header or ``?token=`` query
parameter); without one, the dump route is still POST-only.

The port comes from ``port=``, else ``TPUCOLL_TELEMETRY_PORT`` (strict
integer parse — a typo'd value raises instead of silently picking an
ephemeral port), else 0 (ephemeral; read ``server.port``).
"""

from __future__ import annotations

import hmac
import json
import os
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from gloo_tpu.utils import metrics as metrics_util

__all__ = ["TelemetryServer", "fetch_route", "serve_telemetry"]


def fetch_route(source: str, route: str, timeout: float = 10.0,
                token: Optional[str] = None):
    """Fetch one telemetry route from a live rank and parse the JSON.

    ``source`` is an ``http(s)://host:port`` base (``route`` — e.g.
    ``"/flightrec"`` or ``"/profile.json"`` — is appended unless the
    source already ends with it). ``token`` (default: the
    ``TPUCOLL_TELEMETRY_TOKEN`` environment variable) rides the
    ``X-TpuColl-Token`` header for token-guarded endpoints. The one
    fetch path shared by ``tools/flightrec_view.py`` and
    ``tools/profile_view.py`` (via ``tools/_telemetry_client.py``) so
    their live-source handling cannot drift."""
    url = source.rstrip("/")
    if not url.endswith(route):
        url += route
    if token is None:
        token = os.environ.get("TPUCOLL_TELEMETRY_TOKEN") or None
    req = urllib.request.Request(
        url, headers={"X-TpuColl-Token": token} if token else {})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def _env_port() -> int:
    raw = os.environ.get("TPUCOLL_TELEMETRY_PORT")
    if raw is None or raw == "":
        return 0
    if not raw.isdigit() or int(raw) > 65535:
        raise ValueError(
            f"TPUCOLL_TELEMETRY_PORT must be a port number in [0, 65535], "
            f"got: {raw!r}")
    return int(raw)


def healthz(snapshot: dict, stall_window_ms: Optional[float] = None,
            ) -> dict:
    """Health verdict over one metrics snapshot: ``{"ok": bool,
    "reasons": [...], ...}``.

    A watchdog stall marks the rank unhealthy while the stall is
    FRESH — within ``stall_window_ms`` (default ``max(3 * watchdog_ms,
    1000)``) of detection — **or still unresolved**: the watchdog
    records a stall at most once per blocked wait, so age alone would
    read a rank wedged in a 60 s collective as healthy after a second;
    as long as the blamed peer has made no transport progress since the
    stall was detected, the rank is still stuck and stays 503. Once the
    peer progressed (the link resumed) the record ages out past the
    window and the verdict flips back to 200 without a manual drain. A
    recorded transport failure is permanent for the context (the mesh
    is poisoned). Elastic status (attached by
    ``ElasticContext.metrics()``) is unhealthy when this worker is
    superseded (bound epoch behind the head), evicted / join-pending,
    or the group sits below min_size."""
    reasons: List[str] = []
    wd = snapshot.get("watchdog", {}) or {}
    last = wd.get("last")
    if last:
        if stall_window_ms is None:
            stall_window_ms = max(
                3 * float(snapshot.get("watchdog_ms", 0) or 0), 1000.0)
        age_ms = float(last.get("age_us", 0)) / 1000.0
        peer = last.get("peer", -1)
        transport = snapshot.get("transport", {}) or {}
        peer_stats = (transport.get(peer) or transport.get(str(peer))
                      or {})
        # Resolved = the blamed peer moved bytes AFTER the stall was
        # detected (timestamps share the rank's steady clock). An
        # unknown peer (-1, recv-from-any) can't be checked and falls
        # back to freshness alone.
        resolved = (peer is None or peer < 0 or
                    peer_stats.get("last_progress_us", 0)
                    > last.get("at_us", 0))
        if age_ms <= stall_window_ms or not resolved:
            detail = ("" if resolved
                      else ", unresolved: peer has not progressed since")
            reasons.append(
                f"watchdog stall {age_ms:.0f}ms ago (peer "
                f"{last.get('peer')}, waited "
                f"{last.get('waited_us', 0) // 1000}ms{detail})")
    failure = snapshot.get("transport_failure")
    if failure:
        reasons.append(
            f"transport failure: peer {failure.get('peer')} "
            f"({failure.get('message', '')[:120]})")
    elastic = snapshot.get("elastic")
    out = {"rank": snapshot.get("rank"), "group": snapshot.get("group")}
    if elastic:
        out["epoch"] = elastic.get("epoch")
        out["head_epoch"] = elastic.get("head_epoch")
        out["members"] = elastic.get("size")
        if elastic.get("join_pending"):
            reasons.append("elastic: not a member of the current epoch "
                           "(evicted or join pending)")
        elif elastic.get("head_epoch", 0) > elastic.get("epoch", 0):
            reasons.append(
                f"elastic: superseded (bound epoch {elastic.get('epoch')}"
                f" behind head {elastic.get('head_epoch')})")
        if (elastic.get("min_size") and
                elastic.get("size", 0) < elastic["min_size"]):
            reasons.append(
                f"elastic: {elastic.get('size')} members below min_size "
                f"{elastic['min_size']}")
    out["ok"] = not reasons
    out["reasons"] = reasons
    return out


class TelemetryServer:
    """Daemon-thread HTTP server bound to one context. Create via
    :func:`serve_telemetry`; stop with :meth:`close` (also a context
    manager). The serving thread never blocks interpreter exit."""

    def __init__(self, ctx, host: str, port: int, token: Optional[str],
                 stall_window_ms: Optional[float]):
        self._ctx = ctx
        self._token = token
        self._stall_window_ms = stall_window_ms
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # The handler must never raise into the socket loop; every
            # route snapshot failure becomes a 500 with the message.
            def log_message(self, *args):  # noqa: D102 - silence stderr
                pass

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code: int, doc) -> None:
                self._reply(code, json.dumps(doc).encode())

            def _authorized(self, parsed) -> bool:
                """With a token configured, EVERY route requires it —
                the GET routes expose the same operational detail
                (peer addresses, error strings) the token exists to
                guard. Constant-time compare: a short-circuiting !=
                would leak the token byte by byte through response
                timing on a deliberately network-exposed server."""
                if not outer._token:
                    return True
                query = parse_qs(parsed.query)
                given = (self.headers.get("X-TpuColl-Token")
                         or (query.get("token") or [None])[0])
                return hmac.compare_digest(given or "", outer._token)

            def do_GET(self):  # noqa: N802 - http.server contract
                try:
                    parsed = urlparse(self.path)
                    path = parsed.path
                    if not self._authorized(parsed):
                        self._reply_json(
                            403, {"error": "bad or missing token"})
                        return
                    if path == "/metrics":
                        text = metrics_util.to_prometheus(
                            outer._ctx.metrics())
                        self._reply(200, text.encode(),
                                    "text/plain; version=0.0.4")
                    elif path == "/healthz":
                        verdict = healthz(outer._ctx.metrics(),
                                          outer._stall_window_ms)
                        self._reply_json(200 if verdict["ok"] else 503,
                                         verdict)
                    elif path == "/profile.json":
                        self._reply_json(200, outer._ctx.profile())
                    elif path == "/spans":
                        spans_fn = getattr(outer._ctx, "spans", None)
                        if callable(spans_fn):
                            self._reply_json(200, spans_fn())
                        else:
                            self._reply_json(404, {
                                "error": "context has no spans() "
                                         "(causal span recorder "
                                         "unavailable)"})
                    elif path == "/flightrec":
                        self._reply_json(200, outer._ctx.flightrec())
                    elif path == "/fleet":
                        fleet_fn = getattr(outer._ctx, "fleet", None)
                        if callable(fleet_fn):
                            self._reply_json(200, fleet_fn())
                        else:
                            self._reply_json(404, {
                                "error": "context has no fleet() "
                                         "(fleet observability plane "
                                         "unavailable)"})
                    elif path == "/":
                        self._reply_json(200, {"routes": [
                            "/metrics", "/healthz", "/profile.json",
                            "/spans", "/flightrec", "/fleet",
                            "POST /flightrec/dump"]})
                    elif path == "/flightrec/dump":
                        self._reply_json(405, {"error":
                                               "use POST (guarded route)"})
                    else:
                        self._reply_json(404, {"error": "unknown route"})
                except Exception as exc:  # noqa: BLE001 - served as 500
                    self._reply_json(500, {"error": repr(exc)})

            def do_POST(self):  # noqa: N802 - http.server contract
                try:
                    parsed = urlparse(self.path)
                    if not self._authorized(parsed):
                        self._reply_json(
                            403, {"error": "bad or missing token"})
                        return
                    if parsed.path != "/flightrec/dump":
                        self._reply_json(404, {"error": "unknown route"})
                        return
                    directory = os.environ.get("TPUCOLL_FLIGHTREC_DIR",
                                               "flightrec-dump")
                    os.makedirs(directory, exist_ok=True)
                    # Mirror the native auto-dump naming: a split /
                    # epoch sub-context's dump carries its group tag
                    # ('/' -> '.', like flightrec.cc) so same-rank
                    # contexts sharing the directory never overwrite
                    # each other and merge_by_tag can partition.
                    tag_fn = getattr(outer._ctx, "group_tag", None)
                    tag = (tag_fn() if callable(tag_fn)
                           else "").replace("/", ".")
                    name = (f"flightrec-rank{outer._ctx.rank}"
                            + (f"-g{tag}" if tag else "") + ".json")
                    path = os.path.join(directory, name)
                    outer._ctx.flightrec_dump(path)
                    self._reply_json(200, {"path": path})
                except Exception as exc:  # noqa: BLE001 - served as 500
                    self._reply_json(500, {"error": repr(exc)})

        # SO_REUSEADDR explicitly: a restarting rank must be able to
        # rebind its fixed TPUCOLL_TELEMETRY_PORT while the previous
        # server's sockets sit in TIME_WAIT. http.server happens to
        # default this on; pinning it here makes the rebind contract
        # ours, not an inherited accident (regression-tested).
        class _Server(ThreadingHTTPServer):
            allow_reuse_address = True

        self._httpd = _Server((host, port), Handler)
        self._httpd.daemon_threads = True
        self._closed = False
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"tpucoll-telemetry-{self._httpd.server_address[1]}",
            daemon=True)
        self._thread.start()

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        """Stop serving, close the listening socket, and JOIN the
        serving thread — after close() returns, the port is free to
        rebind. Idempotent: a second close is a no-op, not an error."""
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def serve_telemetry(ctx, port: Optional[int] = None,
                    host: str = "127.0.0.1",
                    token: Optional[str] = None,
                    stall_window_ms: Optional[float] = None,
                    ) -> TelemetryServer:
    """Start the telemetry endpoint for ``ctx`` (see module docstring).

    ``port=None`` reads TPUCOLL_TELEMETRY_PORT (strict; unset -> 0 =
    ephemeral). ``token=None`` reads TPUCOLL_TELEMETRY_TOKEN; when
    either is set, POST /flightrec/dump requires it. Returns the
    running :class:`TelemetryServer` (``.port`` / ``.url`` / context
    manager)."""
    if port is None:
        port = _env_port()
    if token is None:
        token = os.environ.get("TPUCOLL_TELEMETRY_TOKEN") or None
    return TelemetryServer(ctx, host, port, token, stall_window_ms)
