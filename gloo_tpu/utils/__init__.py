from gloo_tpu.utils.tracing import device_trace, merge_traces

__all__ = ["device_trace", "merge_traces"]
