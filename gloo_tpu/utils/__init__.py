from gloo_tpu.utils import fleet
from gloo_tpu.utils import flightrec
from gloo_tpu.utils import profile
from gloo_tpu.utils.flightrec import DesyncError
from gloo_tpu.utils.metrics import (histogram_quantile, merge_snapshots,
                                    summarize_ops, to_prometheus)
from gloo_tpu.utils.telemetry import TelemetryServer, serve_telemetry
from gloo_tpu.utils.tracing import annotate, device_trace, merge_traces

__all__ = [
    "DesyncError",
    "TelemetryServer",
    "annotate",
    "device_trace",
    "fleet",
    "flightrec",
    "histogram_quantile",
    "merge_snapshots",
    "merge_traces",
    "profile",
    "serve_telemetry",
    "summarize_ops",
    "to_prometheus",
]
