"""Flight-recorder post-mortem tooling: dump, cross-rank merge, desync
analysis, Perfetto conversion.

The native side (csrc/tpucoll/common/flightrec.h, docs/flightrec.md)
keeps an always-on bounded ring of every collective/p2p op per context
and dumps it to JSON on stall, transport failure, fatal signal (opt-in),
or request. This module is the other half of the black box: collect the
per-rank dumps after an incident and turn them into one answer —

- :func:`dump` writes this rank's ring to a dump directory;
- :func:`merge` combines per-rank dumps into a single cross-rank
  timeline, degrading gracefully over empty/corrupt files and noting
  ranks whose dump never appeared (a SIGKILL'd rank writes nothing);
- :func:`analyze` renders the verdict: a **desync** (ranks issued
  different collectives at the same sequence number — fingerprints
  diverge), a **stall** (same schedule, one rank behind or blamed by its
  peers' watchdogs), or a clean record;
- :func:`raise_on_desync` turns a desync verdict into the typed
  :class:`DesyncError`;
- :func:`to_perfetto` emits Chrome trace-event JSON of the merged
  timeline (per-rank rows, in-flight ops rendered to the dump instant).

Timestamps are per-host CLOCK_MONOTONIC: comparable across the
processes of one host (the multiprocess test topology) but NOT across
machines — the analysis therefore reasons in sequence numbers and
states, and only uses timestamps for ordering within a rank and for the
Perfetto rendering.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Union

__all__ = [
    "DesyncError",
    "analyze",
    "describe_event",
    "detect_desync",
    "dump",
    "install_signal_handler",
    "load",
    "merge",
    "merge_by_tag",
    "raise_on_desync",
    "to_perfetto",
]

# How many trailing ops a rank publishes through the rendezvous store
# when recovery exchanges evidence (resilience._stall_evidence): enough
# to find the divergence point across ranks whose frontiers drifted
# apart by a few ops, small enough for a store value.
TAIL_K = 16

_RANK_RE = re.compile(r"flightrec-rank(\d+)\.json$")
# Tagged dump names: flightrec-rank<r>[-g<group>][-lane<k>].json — group
# tags come from split sub-communicators (Context.group_tag, '/' mapped
# to '.'), lane tags from async engines. merge() keeps its historical
# contract (untagged = root-context dumps only); merge_by_tag() is the
# partitioned form.
_TAGGED_RE = re.compile(
    r"flightrec-rank(\d+)(?:-g([\w.]+))?(?:-lane(\d+))?\.json$")


class DesyncError(RuntimeError):
    """Ranks issued DIFFERENT collectives at the same sequence number —
    the unrecoverable schedule divergence. `.report` carries the full
    verdict dict from :func:`analyze` / :func:`detect_desync`."""

    def __init__(self, message: str, report: Optional[dict] = None):
        super().__init__(message)
        self.report = report or {}


def install_signal_handler() -> None:
    """Opt in to fatal-signal dumping: SIGSEGV/SIGABRT/SIGBUS/SIGFPE/
    SIGILL/SIGTERM dump every live context's ring to
    TPUCOLL_FLIGHTREC_DIR before the process dies. Also reachable with
    TPUCOLL_FLIGHTREC_SIGNALS=1 (checked at context connect)."""
    from gloo_tpu import _lib

    _lib.lib.tc_flightrec_install_signal_handler()


def dump(ctx, directory: Optional[str] = None) -> str:
    """Write `ctx`'s flight-recorder ring to
    `directory/flightrec-rank<r>.json` (the same naming automatic dumps
    use, so one merge() reads both). Default directory:
    TPUCOLL_FLIGHTREC_DIR, else ./flightrec-dump. Returns the path."""
    if directory is None:
        directory = os.environ.get("TPUCOLL_FLIGHTREC_DIR",
                                   "flightrec-dump")
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"flightrec-rank{ctx.rank}.json")
    return ctx.flightrec_dump(path)


def load(path: str) -> Optional[dict]:
    """Read one dump file; returns None (never raises) for a missing,
    empty, or corrupt file — a crashing rank may truncate its dump, and
    the merge must survive that."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "events" not in doc:
        return None
    return doc


def describe_event(e: dict) -> str:
    """Human description of one record: "allreduce float32 1.0MB"."""
    parts = [str(e.get("op", "?"))]
    if e.get("algo"):
        parts.append(f"[{e['algo']}]")
    if e.get("dtype"):
        parts.append(str(e["dtype"]))
    nbytes = e.get("bytes", 0)
    if nbytes:
        for unit in ("B", "KB", "MB", "GB"):
            if nbytes < 1024 or unit == "GB":
                parts.append(f"{nbytes:.1f}{unit}"
                             if isinstance(nbytes, float)
                             else f"{nbytes}{unit}")
                break
            nbytes /= 1024
    return " ".join(parts)


def _iter_docs(dumps) -> List[Optional[dict]]:
    """Normalize merge() input — a dump directory, an iterable of file
    paths, or an iterable of already-loaded dicts — into docs."""
    if isinstance(dumps, str):
        paths = [p for p in
                 glob.glob(os.path.join(dumps, "flightrec-rank*.json"))
                 if _RANK_RE.search(p)]
        paths.sort(key=lambda p: int(_RANK_RE.search(p).group(1)))
        return [load(p) for p in paths]
    docs: List[Optional[dict]] = []
    for item in dumps:
        if isinstance(item, str):
            docs.append(load(item))
        else:
            docs.append(item if isinstance(item, dict) else None)
    return docs


def merge(dumps: Union[str, Iterable]) -> dict:
    """Merge per-rank dumps into one cross-rank record.

    `dumps` is a dump directory, an iterable of file paths, or an
    iterable of loaded docs (None entries allowed). Returns::

        {"ranks": {rank: doc},        # successfully loaded dumps
         "size": <group size>,        # max size claimed by any dump
         "missing": [rank, ...],      # ranks with no usable dump
         "timeline": [event + {"rank": r}, ...]}  # ts-sorted

    A missing or unreadable rank is NOTED, never fatal — with a killed
    rank the absence itself is the evidence. Events with absent or
    unsorted timestamps are tolerated (sort key falls back to seq)."""
    ranks: Dict[int, dict] = {}
    size = 0
    for doc in _iter_docs(dumps):
        if doc is None:
            continue
        rank = int(doc.get("rank", -1))
        if rank < 0:
            continue
        ranks[rank] = doc
        size = max(size, int(doc.get("size", 0)), rank + 1)
    timeline = []
    for rank, doc in sorted(ranks.items()):
        for e in doc.get("events", []):
            if not isinstance(e, dict):
                continue
            timeline.append(dict(e, rank=rank))
    timeline.sort(key=lambda e: (e.get("ts_enqueued_us") or 0,
                                 e.get("seq", 0), e.get("rank", 0)))
    missing = [r for r in range(size) if r not in ranks]
    return {"ranks": ranks, "size": size, "missing": missing,
            "timeline": timeline}


def merge_by_tag(directory: str) -> Dict[str, dict]:
    """Partition a dump directory by tag, then merge each partition.

    Returns {tag: merge_result}. The tag is "<group>" for split
    sub-communicator dumps (flightrec-rank<r>-g<group>.json, with the
    "group" field inside the doc as fallback), "<group>/lane<k>" or
    "lane<k>" for async-lane dumps, and "" for plain root-context dumps.

    Partitioning is REQUIRED before analysis when sub-groups share a
    dump directory: disjoint split groups legitimately run different
    schedules, so fingerprint-comparing rank 0 of group A against rank 0
    of group B would report a desync that is not one. Analyze each
    partition independently (see tools/flightrec_view.py)."""
    partitions: Dict[str, list] = {}
    for path in sorted(glob.glob(
            os.path.join(directory, "flightrec-rank*.json"))):
        m = _TAGGED_RE.search(os.path.basename(path))
        if m is None:
            continue
        doc = load(path)
        if doc is None:
            continue
        group = m.group(2) or str(doc.get("group", "") or "")
        lane = m.group(3)
        tag = group
        if lane is not None:
            tag = f"{group}/lane{lane}" if group else f"lane{lane}"
        partitions.setdefault(tag, []).append(doc)
    return {tag: merge(docs) for tag, docs in sorted(partitions.items())}


def detect_desync(tails: Dict[int, List[dict]]) -> Optional[dict]:
    """Compare per-rank op fingerprints at matching COLLECTIVE sequence
    numbers.

    `tails` maps rank -> list of records (full dump events and the
    compact store-exchanged tails both qualify). Only entries with a
    `cseq` participate: the collective sequence advances identically on
    every rank for a matching schedule, whereas p2p ops (send/recv/
    put/get, `cseq` null) are legitimately rank-asymmetric and must not
    shift or poison the comparison. Returns None when every shared cseq
    agrees; otherwise a desync report::

        {"mismatches": [{"seq", "groups": [{"fp", "ranks", "desc"}]}],
         "blamed_ranks": [...],   # minority group at the first mismatch
         "message": "rank 2 is at seq 41 (broadcast ...) while ..."}
    """
    by_seq: Dict[int, Dict[int, dict]] = {}
    for rank, tail in tails.items():
        for e in tail or []:
            if e.get("cseq") is not None and "fp" in e:
                by_seq.setdefault(int(e["cseq"]), {})[rank] = e
    mismatches = []
    for seq in sorted(by_seq):
        groups: Dict[str, List[int]] = {}
        for rank, e in by_seq[seq].items():
            groups.setdefault(str(e["fp"]), []).append(rank)
        if len(groups) < 2:
            continue
        mismatches.append({
            "seq": seq,
            "groups": [{"fp": fp, "ranks": sorted(rs),
                        "desc": by_seq[seq][rs[0]].get("desc")
                        or describe_event(by_seq[seq][rs[0]])}
                       for fp, rs in sorted(groups.items(),
                                            key=lambda kv: kv[1])],
        })
    if not mismatches:
        return None
    first = mismatches[0]
    # Smallest group is the blamed divergent; the message quotes it
    # against the LARGEST OTHER group (size ties — e.g. a 1v1 split when
    # only two ranks' tails overlap — must still name two different
    # sides, not the same group twice).
    by_size = sorted(first["groups"],
                     key=lambda g: (len(g["ranks"]), g["ranks"]))
    minority = by_size[0]
    majority = by_size[-1]
    message = (
        f"collective desync: rank {minority['ranks'][0]} is at seq "
        f"{first['seq']} ({minority['desc']}) while rank "
        f"{majority['ranks'][0]} is at seq {first['seq']} "
        f"({majority['desc']})")
    return {"mismatches": mismatches, "blamed_ranks": minority["ranks"],
            "message": message}


def _frontier(doc: dict) -> Optional[dict]:
    """The record that tells where a rank got to: its first
    non-completed op when one exists (the op it died/hung inside), else
    its last op."""
    events = [e for e in doc.get("events", []) if isinstance(e, dict)]
    if not events:
        return None
    for e in events:
        if e.get("state") != "completed":
            return e
    return events[-1]


def analyze(merged: dict) -> dict:
    """Render the verdict over a :func:`merge` result.

    Returns {"kind": "desync" | "stall" | "ok", "blamed_ranks": [...],
    "message": str, "frontier": {rank: {"seq", "desc", "state"}},
    "desync": <detect_desync report or None>, "missing": [...],
    "suspects": {rank: votes}}.

    Blame order: fingerprint divergence wins (a desync explains every
    downstream stall); then ranks that never dumped (killed before the
    recorder could write) together with the peers their survivors'
    dumps blame; then the watchdog blame votes carried in each dump's
    `blamed_peer`; then the rank whose frontier trails the group."""
    ranks = merged.get("ranks", {})
    frontier = {}
    for rank, doc in ranks.items():
        e = _frontier(doc)
        if e is None:
            continue
        # The displayed frontier is whatever op the rank is stuck in
        # (possibly p2p); the cross-rank COMPARISON axis is the rank's
        # last collective seq — ring seqs count rank-asymmetric p2p
        # traffic and are not comparable between ranks.
        colls = [ev for ev in doc.get("events", [])
                 if isinstance(ev, dict) and ev.get("cseq") is not None]
        frontier[rank] = {"seq": e.get("seq"),
                          "cseq": colls[-1]["cseq"] if colls else None,
                          "desc": describe_event(e),
                          "state": e.get("state")}
    desync = detect_desync(
        {r: doc.get("events", []) for r, doc in ranks.items()})
    suspects: Dict[int, int] = {}
    for doc in ranks.values():
        blamed = doc.get("blamed_peer", -1)
        if isinstance(blamed, int) and blamed >= 0:
            suspects[blamed] = suspects.get(blamed, 0) + 1
    missing = list(merged.get("missing", []))

    if desync is not None:
        return {"kind": "desync", "blamed_ranks": desync["blamed_ranks"],
                "message": desync["message"], "frontier": frontier,
                "desync": desync, "missing": missing,
                "suspects": suspects}

    blamed: List[int] = []
    message = "no desync detected"
    kind = "ok"
    if missing:
        kind = "stall"
        blamed = missing
        message = (f"rank(s) {missing} produced no dump (died before the "
                   f"recorder could write)")
    elif suspects:
        kind = "stall"
        top = max(suspects.items(), key=lambda kv: kv[1])[0]
        blamed = [top]
        message = f"peers blame rank {top}"
    elif frontier:
        # Laggard comparison in COLLECTIVE seq: a rank that never
        # reached a collective sorts as furthest behind.
        def key(f):
            return f["cseq"] if f.get("cseq") is not None else -1

        behind = min(frontier.items(), key=lambda kv: key(kv[1]))
        ahead = max(frontier.items(), key=lambda kv: key(kv[1]))
        if (key(behind[1]) != key(ahead[1])
                or any(f["state"] != "completed"
                       for f in frontier.values())):
            kind = "stall"
            inflight = [r for r, f in frontier.items()
                        if f["state"] != "completed"]
            blamed = [behind[0]] if not inflight else sorted(inflight)
            message = (f"rank {behind[0]} is at seq {key(behind[1])} "
                       f"({behind[1]['desc']}, {behind[1]['state']}); "
                       f"rank {ahead[0]} reached seq {key(ahead[1])}")
    if blamed and frontier:
        extras = [f"rank {r} in-flight: {frontier[r]['desc']} "
                  f"(seq {frontier[r]['seq']}, {frontier[r]['state']})"
                  for r in sorted(frontier)
                  if frontier[r]["state"] != "completed"]
        if extras:
            message += "; " + "; ".join(extras)
    return {"kind": kind, "blamed_ranks": blamed, "message": message,
            "frontier": frontier, "desync": None, "missing": missing,
            "suspects": suspects}


def raise_on_desync(merged_or_verdict: dict) -> dict:
    """Run (or reuse) the analysis; raise :class:`DesyncError` on a
    fingerprint divergence, return the verdict otherwise."""
    verdict = merged_or_verdict
    if "kind" not in verdict:
        verdict = analyze(verdict)
    if verdict.get("kind") == "desync":
        raise DesyncError(verdict["message"], verdict)
    return verdict


def to_perfetto(merged: dict) -> str:
    """Chrome trace-event JSON of the merged timeline: one row per rank
    (pid = rank, labeled like utils.merge_traces), one complete-event
    span per op. In-flight ops extend to the dumping rank's `now_us` so
    the hang is visible as a bar running off the end."""
    events = []
    pids = set()
    for rank, doc in sorted(merged.get("ranks", {}).items()):
        now = doc.get("now_us", 0)
        for e in doc.get("events", []):
            start = e.get("ts_enqueued_us") or 0
            end = e.get("ts_completed_us") or 0
            if end <= 0:
                end = max(now, start)
            args = {"seq": e.get("seq"), "state": e.get("state"),
                    "bytes": e.get("bytes"), "fp": e.get("fp")}
            if e.get("algo"):
                args["algo"] = e["algo"]
            if e.get("peer", -1) is not None and e.get("peer", -1) >= 0:
                args["peer"] = e["peer"]
            events.append({"name": e.get("op", "?"), "ph": "X",
                           "ts": start, "dur": max(end - start, 1),
                           "pid": rank, "tid": 0, "args": args})
            pids.add(rank)
    meta = []
    for pid in sorted(pids):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"rank {pid}"}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"sort_index": pid}})
    return json.dumps(meta + events)
