"""Fleet observability document helpers (docs/fleet.md).

The in-band fleet plane (``Context.fleetobs_start()``) folds every
rank's metrics / profile / health snapshot up the host topology —
members to their host leader, leaders to rank 0 — and rank 0 merges the
stream into one **fleet document** served as ``/fleet`` by
:func:`gloo_tpu.utils.telemetry.serve_telemetry` and returned by
``Context.fleet()``. This module is the consumer side of that document:

- :func:`reports` flattens the embedded per-rank reports out of the
  per-host nesting;
- :func:`coverage` answers "is rank 0 actually seeing the whole
  fleet" (expected / reported / missing / stale);
- :func:`unhealthy` lists the ranks whose own reports flag trouble
  (transport failure, watchdog stalls, op errors);
- :func:`summarize` folds all of the above plus the straggler
  leaderboard, slow links, and recent anomalies into one compact dict
  (what a dashboard or ``tools/profile_view.py --fleet`` renders);
- :func:`render` is the human-readable text form of a summary.

All helpers are pure functions over the parsed JSON document — they
never talk to the network; pair them with
``telemetry.fetch_route(url, "/fleet")`` for live use.
"""

from __future__ import annotations

from typing import Dict, List

__all__ = [
    "coverage",
    "render",
    "reports",
    "summarize",
    "unhealthy",
]


def reports(fleet: dict) -> Dict[int, dict]:
    """Flatten ``{rank: report}`` out of the document's per-host
    nesting. Ranks are ints (wire keys are JSON strings)."""
    out: Dict[int, dict] = {}
    for host in fleet.get("hosts", []) or []:
        for rank, report in (host.get("ranks") or {}).items():
            out[int(rank)] = report
    return out


def coverage(fleet: dict) -> dict:
    """Coverage verdict: ``{"expected", "reported", "missing": [...],
    "complete": bool}``. Prefers the document's own coverage section
    (rank 0 computes it against the live topology) and recomputes from
    the embedded reports when absent (e.g. a truncated document)."""
    cov = fleet.get("coverage")
    if cov is not None:
        expected = cov.get("expected", 0)
        reported = cov.get("reported", 0)
        # Both conditions: a stub document (no aggregation round yet)
        # reports 0 with an empty missing list — that is not coverage.
        return {
            "expected": expected,
            "reported": reported,
            "missing": list(cov.get("missing", [])),
            "complete": (reported >= expected
                         and not cov.get("missing", [])),
        }
    got = reports(fleet)
    expected = fleet.get("size", len(got))
    missing = [r for r in range(expected) if r not in got]
    return {"expected": expected, "reported": len(got),
            "missing": missing, "complete": not missing}


def unhealthy(fleet: dict) -> List[dict]:
    """Ranks whose own report flags trouble, most-errors first:
    ``[{"rank", "reasons": [...]}, ...]``. A missing/unparseable report
    is NOT listed here — that is a coverage problem, not a health
    verdict (see :func:`coverage`)."""
    out: List[dict] = []
    for rank, rep in sorted(reports(fleet).items()):
        reasons: List[str] = []
        if rep.get("ok") is False:
            peer = rep.get("failure_peer", -1)
            reasons.append(f"transport failure (peer {peer})")
        if rep.get("stalls", 0):
            reasons.append(f"{rep['stalls']} watchdog stall(s)")
        if rep.get("errors", 0):
            reasons.append(f"{rep['errors']} op error(s)")
        if reasons:
            out.append({"rank": rank, "reasons": reasons})
    out.sort(key=lambda e: -len(e["reasons"]))
    return out


def summarize(fleet: dict) -> dict:
    """One compact dict over the whole document: coverage, health,
    straggler leaderboard, slow links, anomaly tallies. Safe on stub
    documents (non-rank-0 / plane off): everything degrades to empty."""
    strag = fleet.get("straggler", {}) or {}
    anomalies = fleet.get("anomalies", {}) or {}
    recent = anomalies.get("recent", []) or []
    by_kind: Dict[str, int] = {}
    for ev in recent:
        kind = ev.get("kind", "?")
        by_kind[kind] = by_kind.get(kind, 0) + 1
    return {
        "enabled": bool(fleet.get("enabled")),
        "round": fleet.get("round", 0),
        "size": fleet.get("size", 0),
        "hosts": len(fleet.get("hosts", []) or []),
        "coverage": coverage(fleet),
        "unhealthy": unhealthy(fleet),
        "leaderboard": list(strag.get("leaderboard", [])),
        "slow_links": list(fleet.get("slow_links", []) or []),
        "anomalies_total": anomalies.get("total", 0),
        "recent_anomalies_by_kind": by_kind,
    }


def render(fleet: dict) -> str:
    """Human-readable text form of :func:`summarize` (the
    ``tools/*_view.py --fleet`` output)."""
    s = summarize(fleet)
    lines: List[str] = []
    if not s["enabled"]:
        note = fleet.get("note", "fleet plane not running here")
        lines.append(f"fleet: disabled/stub ({note})")
        return "\n".join(lines) + "\n"
    cov = s["coverage"]
    lines.append(
        f"fleet: round {s['round']}, {s['size']} ranks across "
        f"{s['hosts']} host(s), coverage {cov['reported']}/"
        f"{cov['expected']}"
        + (f" (missing: {cov['missing']})" if cov["missing"] else ""))
    if s["unhealthy"]:
        for e in s["unhealthy"]:
            lines.append(
                f"  unhealthy rank {e['rank']}: "
                + "; ".join(e["reasons"]))
    else:
        lines.append("  all reporting ranks healthy")
    if s["leaderboard"]:
        lines.append("  straggler leaderboard (blamed wait over the "
                     "detection window):")
        for row in s["leaderboard"][:5]:
            lines.append(
                f"    rank {row.get('rank')}: "
                f"{row.get('blamed_us', 0) / 1000.0:.1f}ms over "
                f"{row.get('blamed_ops', 0)} op(s)")
    if s["slow_links"]:
        for link in s["slow_links"]:
            lines.append(
                f"  slow link {link.get('rank')}->{link.get('peer')}: "
                f"{link.get('bw_bps', 0) / 1e6:.1f} MB/s vs median "
                f"{link.get('median_bps', 0) / 1e6:.1f} MB/s")
    total = s["anomalies_total"]
    if total or s["recent_anomalies_by_kind"]:
        kinds = ", ".join(f"{k}×{n}" for k, n
                          in sorted(s["recent_anomalies_by_kind"].items()))
        lines.append(f"  anomalies: {total} total"
                     + (f" (recent: {kinds})" if kinds else ""))
    else:
        lines.append("  no anomalies detected")
    return "\n".join(lines) + "\n"
