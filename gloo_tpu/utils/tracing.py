"""Tracing utilities spanning both data planes.

Host plane: `Context.trace_start()/trace_json()` records collective spans
in the C++ core (Chrome trace-event format). Device plane: `device_trace`
wraps the XLA/jax profiler so compiled collectives over the mesh are
captured in the same investigation (view in TensorBoard / Perfetto).
`merge_traces` combines per-rank host traces into one timeline.
"""

from __future__ import annotations

import contextlib
import json
from typing import Iterable


@contextlib.contextmanager
def device_trace(logdir: str):
    """Profile the device plane (XLA execution, ICI collectives) into
    `logdir`; open with TensorBoard's profile plugin or Perfetto."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def merge_traces(jsons: Iterable[str]) -> str:
    """Merge per-rank Chrome trace JSON arrays into one document."""
    events = []
    for doc in jsons:
        events.extend(json.loads(doc))
    return json.dumps(events)
