"""Tracing utilities spanning both data planes.

Host plane: `Context.trace_start()/trace_json()` records collective spans
in the C++ core (Chrome trace-event format). Device plane: `device_trace`
wraps the XLA/jax profiler so compiled collectives over the mesh are
captured in the same investigation (view in TensorBoard / Perfetto).
`merge_traces` combines per-rank host traces into one timeline.
"""

from __future__ import annotations

import contextlib
import json
from typing import Iterable


@contextlib.contextmanager
def device_trace(logdir: str):
    """Profile the device plane (XLA execution, ICI collectives) into
    `logdir`; open with TensorBoard's profile plugin or Perfetto."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Label a host-side region in the jax profiler timeline (and as a
    named scope during tracing), so gloo_tpu host collectives line up
    with XLA device activity in one Perfetto view. No-ops when jax is
    unavailable — safe to leave in production code paths."""
    try:
        import jax

        with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
            yield
    except ImportError:
        yield


def merge_traces(jsons: Iterable[str]) -> str:
    """Merge per-rank Chrome trace JSON arrays into one document.

    Emits `process_name`/`process_sort_index` metadata ("M") events per
    rank pid so Perfetto shows labeled per-rank rows, and sorts data
    events by timestamp so the merged document reads as one timeline
    (inputs with unsorted timestamps are fine). Pre-existing metadata
    events in the inputs are preserved (except process_name/
    process_sort_index, which are regenerated). Degrades gracefully over
    a crashed rank's leavings: empty or unparseable documents are
    skipped — the merge of the survivors must not throw.
    """
    events = []
    for doc in jsons:
        if not doc:
            continue
        try:
            parsed = json.loads(doc)
        except ValueError:
            continue
        if isinstance(parsed, list):
            events.extend(e for e in parsed if isinstance(e, dict))
    data = [e for e in events
            if e.get("ph") != "M"
            or e.get("name") not in ("process_name",
                                     "process_sort_index")]
    data.sort(key=lambda e: (e.get("ts", 0), e.get("pid", 0)))
    pids = sorted({e.get("pid", 0) for e in data})
    meta = []
    for pid in pids:
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"rank {pid}"}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"sort_index": pid}})
    return json.dumps(meta + data)
