"""Cross-rank phase-profile merging and straggler attribution.

The native phase profiler (csrc/tpucoll/common/profile.h,
docs/profiling.md) decomposes every collective on every rank into
canonical phases (pack / post / wire_wait / reduce / unpack, plus the
hierarchical intra / inter / fanout) and keys each per-op breakdown by
the flight recorder's cross-rank collective sequence number ``cseq``.
This module is the cross-rank half:

- :func:`merge` joins per-rank ``Context.profile()`` snapshots by
  ``cseq`` into one record per collective;
- :func:`attribute` splits each collective's latency into **self time**
  and **straggler wait**: a rank's ``wire_wait`` in excess of the
  cross-rank minimum is time spent waiting for a slower peer, and is
  attributed to the straggler — the rank with the *minimum* wire wait
  (it made everyone else wait while itself never waiting);
- :func:`leaderboard` ranks ranks by total blamed time — "who is
  slowing this job down";
- :func:`to_perfetto` renders per-rank phase tracks (Chrome trace-event
  JSON) with each op's span subdivided into its phases.

Timestamps are per-host CLOCK_MONOTONIC and never compared across
machines; the cross-rank join happens purely on ``cseq``, and
attribution uses per-op durations only.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional

__all__ = [
    "attribute",
    "leaderboard",
    "merge",
    "merge_by_group",
    "to_perfetto",
]

# Phases that count as "waiting on the wire" for attribution. post is
# deliberately excluded: a send delayed at posting time (e.g. the fault
# plane's injected delay) is the STRAGGLER's own time, and folding it
# into the wait would blame the victim.
WAIT_PHASES = ("wire_wait",)


def merge(snapshots: Iterable[dict], group: Optional[str] = None,
          ) -> dict:
    """Join per-rank ``Context.profile()`` snapshots by ``cseq``.

    Returns ``{"group": g, "ranks": [r, ...], "size": n,
    "duplicates": [r, ...], "skipped_groups": [g, ...],
    "ops": {cseq: {rank: op_record}}}``. Ops whose cseq is null (never
    the case for collectives) and ranks without a usable snapshot are
    skipped; an op present on only a subset of ranks (bounded ring
    overwrote it elsewhere) still merges — attribution just sees fewer
    ranks.

    Two safety rails mirror the flight recorder's merge semantics:

    - **one communicator per merge**: the cseq axis only lines up
      within one group tag (split sub-groups renumber ranks AND run
      independent schedules, docs/topology.md), so only snapshots whose
      ``group`` matches — ``group=`` when given, else the first usable
      snapshot's — participate; others are noted under
      ``skipped_groups``. Use :func:`merge_by_group` to handle a mixed
      set.
    - **one snapshot per rank**: several snapshots for one rank (a
      stale dump file beside a live fetch) never mix — the LAST wins
      wholesale and the rank is noted under ``duplicates``."""
    by_rank: Dict[int, dict] = {}
    duplicates: List[int] = []
    skipped_groups: List[str] = []
    size = 0
    for snap in snapshots:
        if not isinstance(snap, dict) or "ops" not in snap:
            continue
        rank = int(snap.get("rank", -1))
        if rank < 0:
            continue
        snap_group = str(snap.get("group", "") or "")
        if group is None:
            group = snap_group
        if snap_group != group:
            if snap_group not in skipped_groups:
                skipped_groups.append(snap_group)
            continue
        if rank in by_rank and rank not in duplicates:
            duplicates.append(rank)
        by_rank[rank] = snap
        size = max(size, int(snap.get("size", 0)), rank + 1)
    ops: Dict[int, Dict[int, dict]] = {}
    for rank, snap in by_rank.items():
        for op in snap.get("ops", []):
            cseq = op.get("cseq")
            if cseq is None:
                continue
            ops.setdefault(int(cseq), {})[rank] = op
    return {"group": group or "", "ranks": sorted(by_rank),
            "size": size, "duplicates": sorted(duplicates),
            "skipped_groups": sorted(skipped_groups), "ops": ops}


def merge_by_group(snapshots: Iterable[dict]) -> Dict[str, dict]:
    """Partition snapshots by their ``group`` tag, then :func:`merge`
    each partition — the safe entry point for a source set spanning
    split sub-groups / epochs (disjoint communicators must never be
    cseq-compared against each other). Returns ``{group: merged}``."""
    partitions: Dict[str, List[dict]] = {}
    for snap in snapshots:
        if not isinstance(snap, dict) or "ops" not in snap:
            continue
        partitions.setdefault(str(snap.get("group", "") or ""),
                              []).append(snap)
    return {g: merge(snaps, group=g)
            for g, snaps in sorted(partitions.items())}


def _wait_us(op: dict) -> int:
    phases = op.get("phases", {})
    return sum(int(phases.get(p, 0)) for p in WAIT_PHASES)


def attribute(merged: dict) -> dict:
    """Attribute each merged collective's latency to self time vs
    straggler wait.

    For collective c with per-rank wire waits w_r, the baseline
    ``min_r w_r`` is the wait everyone pays even in lockstep (wire
    transfer time); rank r's **excess** ``w_r - min w`` is time it
    spent waiting for a slower peer, attributed to the **straggler**
    ``argmin_r w_r``. Self time is ``total - excess``.

    Returns ``{"ops": [{"cseq", "op", "algo", "bytes", "straggler",
    "excess_us", "ranks": {r: {"total_us", "wait_us", "excess_us",
    "self_us", "phases"}}}, ...], "by_rank": {r: {"blamed_us",
    "blamed_ops", "self_us", "excess_us"}}}`` with ops sorted by cseq.
    Single-rank records (ring overwrote the peers) get no straggler."""
    out_ops = []
    by_rank: Dict[int, dict] = {}

    def rank_acc(r: int) -> dict:
        return by_rank.setdefault(r, {"blamed_us": 0, "blamed_ops": 0,
                                      "self_us": 0, "excess_us": 0})

    for cseq in sorted(merged.get("ops", {})):
        per_rank = merged["ops"][cseq]
        waits = {r: _wait_us(op) for r, op in per_rank.items()}
        base = min(waits.values()) if waits else 0
        straggler: Optional[int] = None
        if len(per_rank) > 1:
            straggler = min(waits, key=lambda r: (waits[r], r))
        ranks_out = {}
        total_excess = 0
        first = next(iter(per_rank.values()))
        for r, op in sorted(per_rank.items()):
            total = int(op.get("total_us", 0))
            wait = waits[r]
            excess = max(wait - base, 0)
            total_excess += excess
            ranks_out[r] = {
                "total_us": total,
                "wait_us": wait,
                "excess_us": excess,
                "self_us": max(total - excess, 0),
                "phases": op.get("phases", {}),
            }
            acc = rank_acc(r)
            acc["self_us"] += ranks_out[r]["self_us"]
            acc["excess_us"] += excess
        if straggler is not None and total_excess > 0:
            acc = rank_acc(straggler)
            acc["blamed_us"] += total_excess
            acc["blamed_ops"] += 1
        out_ops.append({
            "cseq": cseq,
            "op": first.get("op"),
            "algo": first.get("algo"),
            "bytes": first.get("bytes", 0),
            "straggler": straggler if total_excess > 0 else None,
            "excess_us": total_excess,
            "ranks": ranks_out,
        })
    return {"ops": out_ops, "by_rank": by_rank}


def leaderboard(attributed: dict) -> List[dict]:
    """Straggler leaderboard from an :func:`attribute` result: one row
    per rank, sorted by total blamed time descending — the rank at the
    top is the one the rest of the job spends the most time waiting
    for."""
    rows = []
    for rank, acc in attributed.get("by_rank", {}).items():
        rows.append({"rank": rank, **acc})
    rows.sort(key=lambda row: (-row["blamed_us"], row["rank"]))
    return rows


_PHASE_ORDER = ("pack", "post", "wire_wait", "reduce", "unpack",
                "intra", "inter", "fanout")


def to_perfetto(snapshots: Iterable[dict]) -> str:
    """Chrome trace-event JSON with per-rank phase tracks.

    One row per rank (pid = rank); each op renders as a span on tid 0
    with its phases as consecutive child spans on tid 1. Phase
    sub-spans are laid out sequentially from the op's start in
    canonical order — an approximation (pipelined schedules interleave
    phases), but the AREA of each phase bar is exact, which is what the
    breakdown reads. Timestamps are per-host CLOCK_MONOTONIC and never
    comparable across machines, so each rank's track is normalized to
    ITS OWN first op (ts 0 = that rank's earliest start) — rows line up
    by relative position, not by a cross-host clock that would offset
    tracks by boot-time differences. Load in ui.perfetto.dev."""
    events = []
    pids = set()
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        rank = int(snap.get("rank", -1))
        if rank < 0:
            continue
        pids.add(rank)
        origin = min((int(op.get("start_us", 0))
                      for op in snap.get("ops", [])), default=0)
        for op in snap.get("ops", []):
            start = int(op.get("start_us", 0)) - origin
            total = max(int(op.get("total_us", 0)), 1)
            name = str(op.get("op", "?"))
            if op.get("algo"):
                name += f"[{op['algo']}]"
            args = {"cseq": op.get("cseq"), "bytes": op.get("bytes")}
            events.append({"name": name, "ph": "X", "ts": start,
                           "dur": total, "pid": rank, "tid": 0,
                           "args": args})
            cursor = start
            for phase in _PHASE_ORDER:
                us = int(op.get("phases", {}).get(phase, 0))
                if us <= 0:
                    continue
                events.append({"name": phase, "ph": "X", "ts": cursor,
                               "dur": us, "pid": rank, "tid": 1,
                               "args": {"cseq": op.get("cseq")}})
                cursor += us
    meta = []
    for pid in sorted(pids):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"rank {pid}"}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"sort_index": pid}})
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": "ops"}})
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": 1, "args": {"name": "phases"}})
    return json.dumps(meta + events)
