"""Metrics post-processing: Prometheus text exposition + histogram math.

`Context.metrics()` returns the native registry's structured snapshot
(see its docstring for the shape). This module turns snapshots into the
two forms a production deployment actually consumes:

- `to_prometheus(snapshot)` renders the Prometheus text exposition format
  (serve it from a /metrics endpoint or push it through a gateway);
- `histogram_quantile(hist, q)` estimates latency quantiles from the
  fixed power-of-two buckets (p50/p95 for dashboards and bench output);
- `merge_snapshots(snaps)` sums per-rank snapshots into a job-level view.

The native histograms store per-bucket (non-cumulative) counts as
[[upper_bound_us, count], ...]; Prometheus buckets are cumulative with a
trailing +Inf, and the conversion happens here so the hot path stays a
couple of relaxed atomic adds.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional


def histogram_quantile(hist: dict, q: float) -> float:
    """Estimate the q-quantile (0 < q <= 1) in microseconds.

    Uses linear interpolation within the containing power-of-two bucket
    ([upper/2, upper]); the true value is within 2x, which is what
    log-bucketed histograms buy. Returns 0.0 for an empty histogram.
    """
    total = hist.get("count", 0)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0
    for upper, n in hist.get("buckets", []):
        if cum + n >= target:
            lower = upper / 2 if upper > 1 else 0
            frac = (target - cum) / n
            return lower + frac * (upper - lower)
        cum += n
    return float(hist.get("max_us", 0))


def summarize_ops(snapshot: dict) -> Dict[str, dict]:
    """Per-op {calls, bytes, errors, p50_us, p95_us, mean_us} digest —
    the compact form bench.py embeds in its JSON line."""
    out = {}
    for name, s in snapshot.get("ops", {}).items():
        hist = s.get("latency_us", {})
        count = hist.get("count", 0)
        out[name] = {
            "calls": s.get("calls", 0),
            "bytes": s.get("bytes", 0),
            "errors": s.get("errors", 0),
            "p50_us": round(histogram_quantile(hist, 0.50), 1),
            "p95_us": round(histogram_quantile(hist, 0.95), 1),
            "mean_us": round(hist.get("sum_us", 0) / count, 1)
            if count else 0.0,
        }
    return out


def _escape_label_value(value: object) -> str:
    """Escape a label value per the Prometheus text exposition format:
    backslash, double-quote, and line-feed must be escaped or the line
    is unparseable — and transport-failure messages (which become label
    values) routinely contain quotes and newlines."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Dict[str, object]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _family(lines: List[str], name: str, kind: str, help_text: str) -> None:
    """Open one metric family: exactly one ``# HELP`` and one ``# TYPE``
    line, in that order, before the family's first sample — the
    exposition-format contract tests/test_prometheus_lint.py enforces.
    HELP text escapes backslash and line-feed (the only escapes the
    format defines for help lines)."""
    escaped = help_text.replace("\\", "\\\\").replace("\n", "\\n")
    lines.append(f"# HELP {name} {escaped}")
    lines.append(f"# TYPE {name} {kind}")


def _emit_histogram(lines: List[str], name: str, hist: dict,
                    labels: Dict[str, object]) -> None:
    cum = 0
    for upper, n in hist.get("buckets", []):
        cum += n
        lines.append(f"{name}_bucket"
                     f"{_fmt_labels({**labels, 'le': upper})} {cum}")
    lines.append(f"{name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} "
                 f"{hist.get('count', 0)}")
    lines.append(f"{name}_sum{_fmt_labels(labels)} "
                 f"{hist.get('sum_us', 0)}")
    lines.append(f"{name}_count{_fmt_labels(labels)} "
                 f"{hist.get('count', 0)}")


def to_prometheus(snapshot: dict,
                  extra_labels: Optional[Dict[str, object]] = None) -> str:
    """Render one rank's snapshot in the Prometheus text exposition
    format (version 0.0.4). Latency units stay microseconds — the metric
    names say so explicitly rather than silently converting."""
    base = dict(extra_labels or {})
    base["rank"] = snapshot.get("rank", 0)
    # Split sub-communicators stamp their group tag into the snapshot
    # (Context.group_tag()); label every family with it so one scrape
    # distinguishes e.g. a DP group's traffic from its TP sibling's.
    # Root contexts ("" group) stay unlabeled — unchanged series names.
    if snapshot.get("group"):
        base["group"] = snapshot["group"]
    lines: List[str] = []

    _family(lines, "gloo_tpu_collective_calls_total", "counter",
            "Collective/p2p calls issued, by op.")
    _family(lines, "gloo_tpu_collective_bytes_total", "counter",
            "Payload bytes moved by collectives, by op.")
    _family(lines, "gloo_tpu_collective_errors_total", "counter",
            "Collective calls that raised, by op.")
    _family(lines, "gloo_tpu_collective_latency_us", "histogram",
            "End-to-end collective latency (microseconds), by op.")
    for op, s in sorted(snapshot.get("ops", {}).items()):
        labels = {**base, "op": op}
        lines.append(f"gloo_tpu_collective_calls_total"
                     f"{_fmt_labels(labels)} {s.get('calls', 0)}")
        lines.append(f"gloo_tpu_collective_bytes_total"
                     f"{_fmt_labels(labels)} {s.get('bytes', 0)}")
        lines.append(f"gloo_tpu_collective_errors_total"
                     f"{_fmt_labels(labels)} {s.get('errors', 0)}")
        _emit_histogram(lines, "gloo_tpu_collective_latency_us",
                        s.get("latency_us", {}), labels)

    # Phase profiler aggregates (docs/profiling.md): one histogram per
    # (collective, algorithm, phase) — the scrape-side decomposition of
    # gloo_tpu_collective_latency_us into pack/post/wire_wait/reduce/
    # unpack (+ hier intra/inter/fanout).
    _family(lines, "gloo_tpu_phase_latency_us", "histogram",
            "Per-phase collective latency (microseconds), by "
            "op/algorithm/phase (docs/profiling.md).")
    for op, algos in sorted(snapshot.get("phases", {}).items()):
        for algo, phases in sorted(algos.items()):
            for phase, hist in sorted(phases.items()):
                labels = {**base, "op": op, "algorithm": algo,
                          "phase": phase}
                _emit_histogram(lines, "gloo_tpu_phase_latency_us",
                                hist, labels)

    _family(lines, "gloo_tpu_transport_sent_msgs_total", "counter",
            "Messages sent to a peer.")
    _family(lines, "gloo_tpu_transport_sent_bytes_total", "counter",
            "Bytes sent to a peer.")
    _family(lines, "gloo_tpu_transport_recv_msgs_total", "counter",
            "Messages received from a peer.")
    _family(lines, "gloo_tpu_transport_recv_bytes_total", "counter",
            "Bytes received from a peer.")
    _family(lines, "gloo_tpu_transport_last_progress_age_us", "gauge",
            "Microseconds since the pair last moved a byte.")
    _family(lines, "gloo_tpu_transport_recv_wait_us", "histogram",
            "Time waitRecv blocked on a peer (microseconds).")
    for peer, s in sorted(snapshot.get("transport", {}).items()):
        labels = {**base, "peer": peer}
        for field, metric in (("sent_msgs", "sent_msgs_total"),
                              ("sent_bytes", "sent_bytes_total"),
                              ("recv_msgs", "recv_msgs_total"),
                              ("recv_bytes", "recv_bytes_total"),
                              ("last_progress_age_us",
                               "last_progress_age_us")):
            lines.append(f"gloo_tpu_transport_{metric}"
                         f"{_fmt_labels(labels)} {s.get(field, 0)}")
        _emit_histogram(lines, "gloo_tpu_transport_recv_wait_us",
                        s.get("recv_wait_us", {}), labels)

    # Link-level wire telemetry (fleet observability plane,
    # docs/fleet.md): per-(peer, channel, direction) bytes, post counts,
    # and the windowed EWMA bandwidth / credit-RTT estimates the
    # slow-link detector consumes.
    _family(lines, "gloo_tpu_pair_bytes_total", "counter",
            "Wire bytes per (peer, channel, direction).")
    _family(lines, "gloo_tpu_pair_posts_total", "counter",
            "Send operations posted toward a peer (enqueue intent; a "
            "growing gap vs sent_msgs is a backed-up link).")
    _family(lines, "gloo_tpu_pair_bw_ewma", "gauge",
            "EWMA link bandwidth toward a peer, bytes/second.")
    _family(lines, "gloo_tpu_pair_rtt_ewma_us", "gauge",
            "EWMA link round-trip estimate toward a peer "
            "(shm credit grants / connect handshake), microseconds.")
    for peer, s in sorted(snapshot.get("transport", {}).items()):
        labels = {**base, "peer": peer}
        for direction, field in (("tx", "chan_tx"), ("rx", "chan_rx")):
            for channel, nbytes in sorted(
                    (s.get(field) or {}).items()):
                lines.append(
                    f"gloo_tpu_pair_bytes_total"
                    f"{_fmt_labels({**labels, 'channel': channel, 'direction': direction})}"
                    f" {nbytes}")
        lines.append(f"gloo_tpu_pair_posts_total{_fmt_labels(labels)} "
                     f"{s.get('tx_posts', 0)}")
        lines.append(f"gloo_tpu_pair_bw_ewma{_fmt_labels(labels)} "
                     f"{s.get('bw_ewma_bps', 0)}")
        lines.append(f"gloo_tpu_pair_rtt_ewma_us{_fmt_labels(labels)} "
                     f"{s.get('rtt_ewma_us', 0)}")

    # Multi-channel transport: wire bytes per data channel (channel "0"
    # is the primary connection; >= "1" carry stripes of large messages
    # when TPUCOLL_CHANNELS > 1) and per-loop-thread progress.
    _family(lines, "gloo_tpu_channel_tx_bytes_total", "counter",
            "Wire bytes transmitted per data channel (all peers).")
    _family(lines, "gloo_tpu_channel_rx_bytes_total", "counter",
            "Wire bytes received per data channel (all peers).")
    for channel, s in sorted(snapshot.get("channels", {}).items()):
        labels = {**base, "channel": channel}
        lines.append(f"gloo_tpu_channel_tx_bytes_total"
                     f"{_fmt_labels(labels)} {s.get('tx_bytes', 0)}")
        lines.append(f"gloo_tpu_channel_rx_bytes_total"
                     f"{_fmt_labels(labels)} {s.get('rx_bytes', 0)}")

    _family(lines, "gloo_tpu_loop_events_total", "counter",
            "Events handled per transport loop thread.")
    _family(lines, "gloo_tpu_loop_last_progress_age_us", "gauge",
            "Microseconds since a loop thread last made progress.")
    for loop, s in sorted(snapshot.get("loops", {}).items()):
        labels = {**base, "loop": loop}
        lines.append(f"gloo_tpu_loop_events_total"
                     f"{_fmt_labels(labels)} {s.get('events', 0)}")
        lines.append(f"gloo_tpu_loop_last_progress_age_us"
                     f"{_fmt_labels(labels)} "
                     f"{s.get('last_progress_age_us', -1)}")

    _family(lines, "gloo_tpu_connect_retries_total", "counter",
            "Bootstrap connect attempts that were retried.")
    lines.append(f"gloo_tpu_connect_retries_total{_fmt_labels(base)} "
                 f"{snapshot.get('retries', 0)}")
    _family(lines, "gloo_tpu_stash_pauses_total", "counter",
            "Times the early-arrival stash paused a sender.")
    lines.append(f"gloo_tpu_stash_pauses_total{_fmt_labels(base)} "
                 f"{snapshot.get('stash_pauses', 0)}")
    _family(lines, "gloo_tpu_trace_events_dropped_total", "counter",
            "Tracer events dropped at the ring bound.")
    lines.append(f"gloo_tpu_trace_events_dropped_total{_fmt_labels(base)} "
                 f"{snapshot.get('trace_events_dropped', 0)}")
    # Persistent collective plans (docs/design.md): cache traffic plus
    # the registration counter the plans flatten — a healthy training
    # loop shows hits climbing with ubuf_creates flat.
    _family(lines, "gloo_tpu_plan_hits_total", "counter",
            "Persistent-plan cache hits.")
    lines.append(f"gloo_tpu_plan_hits_total{_fmt_labels(base)} "
                 f"{snapshot.get('plan_hits', 0)}")
    _family(lines, "gloo_tpu_plan_misses_total", "counter",
            "Persistent-plan cache misses.")
    lines.append(f"gloo_tpu_plan_misses_total{_fmt_labels(base)} "
                 f"{snapshot.get('plan_misses', 0)}")
    _family(lines, "gloo_tpu_plan_evictions_total", "counter",
            "Persistent plans evicted from the LRU.")
    lines.append(f"gloo_tpu_plan_evictions_total{_fmt_labels(base)} "
                 f"{snapshot.get('plan_evictions', 0)}")
    _family(lines, "gloo_tpu_ubuf_creates_total", "counter",
            "UnboundBuffer registrations (flat under plan reuse).")
    lines.append(f"gloo_tpu_ubuf_creates_total{_fmt_labels(base)} "
                 f"{snapshot.get('ubuf_creates', 0)}")
    # Per-action series only; the total is their sum (scrapers derive
    # it), so one metric name never carries two label schemas.
    faults = snapshot.get("faults", {})
    _family(lines, "gloo_tpu_faults_injected_total", "counter",
            "Deterministic fault injections fired, by action.")
    for action, n in sorted(faults.items()):
        if action == "total":
            continue
        lines.append(f"gloo_tpu_faults_injected_total"
                     f"{_fmt_labels({**base, 'action': action})} {n}")

    # Fleet anomaly detectors (docs/fleet.md): same counters the /fleet
    # document reports, so scrape-side alerting and the in-band view
    # can never disagree. The "rank" label is the BLAMED rank (these
    # fire on rank 0, where the aggregation runs).
    anomalies = snapshot.get("anomalies", {})
    _family(lines, "gloo_tpu_anomaly_total", "counter",
            "Fleet anomaly detections, by kind and blamed rank.")
    for kind, by_rank in sorted((anomalies.get("kinds") or {}).items()):
        for blamed, n in sorted(by_rank.items(),
                                key=lambda kv: int(kv[0])):
            labels = {**base, "kind": kind, "rank": blamed}
            lines.append(f"gloo_tpu_anomaly_total"
                         f"{_fmt_labels(labels)} {n}")
    # Async engine gauges (Context.metrics() attaches them when the
    # context has live engines; the per-op detail lives in the lane
    # contexts' own snapshots, AsyncEngine.lane_metrics).
    async_ = snapshot.get("async")
    if async_:
        _family(lines, "gloo_tpu_async_in_flight", "gauge",
                "Async-engine collectives currently in flight.")
        lines.append(f"gloo_tpu_async_in_flight{_fmt_labels(base)} "
                     f"{async_.get('in_flight', 0)}")
        _family(lines, "gloo_tpu_async_lane_submitted_total", "counter",
                "Async ops submitted per engine lane.")
        _family(lines, "gloo_tpu_async_lane_completed_total", "counter",
                "Async ops completed per engine lane.")
        _family(lines, "gloo_tpu_async_lane_errors_total", "counter",
                "Async ops errored per engine lane.")
        for ei, eng in enumerate(async_.get("engines", [])):
            for lane, st in enumerate(eng.get("per_lane", [])):
                labels = {**base, "engine": ei, "lane": lane}
                for key in ("submitted", "completed", "errors"):
                    lines.append(f"gloo_tpu_async_lane_{key}_total"
                                 f"{_fmt_labels(labels)} "
                                 f"{st.get(key, 0)}")
    # Elastic membership plane (docs/elastic.md): ElasticContext.metrics()
    # attaches the agent status under "elastic" — the epoch gauge plus
    # the liveness/transition counters operators alert on.
    elastic = snapshot.get("elastic")
    if elastic:
        _family(lines, "gloo_tpu_elastic_epoch", "gauge",
                "Membership epoch this worker is bound to.")
        lines.append(f"gloo_tpu_elastic_epoch{_fmt_labels(base)} "
                     f"{elastic.get('epoch', 0)}")
        _family(lines, "gloo_tpu_elastic_members", "gauge",
                "Members of the current epoch.")
        lines.append(f"gloo_tpu_elastic_members{_fmt_labels(base)} "
                     f"{elastic.get('size', 0)}")
        _family(lines, "gloo_tpu_elastic_leases_renewed_total", "counter",
                "Liveness lease renewals.")
        lines.append(f"gloo_tpu_elastic_leases_renewed_total"
                     f"{_fmt_labels(base)} "
                     f"{elastic.get('leases_renewed', 0)}")
        _family(lines, "gloo_tpu_elastic_rebuilds_total", "counter",
                "Epoch transitions this worker completed.")
        lines.append(f"gloo_tpu_elastic_rebuilds_total{_fmt_labels(base)} "
                     f"{elastic.get('rebuilds', 0)}")
        _family(lines, "gloo_tpu_elastic_bumps_published_total", "counter",
                "Head-epoch bumps this worker published.")
        lines.append(f"gloo_tpu_elastic_bumps_published_total"
                     f"{_fmt_labels(base)} "
                     f"{elastic.get('bumps_published', 0)}")
    wd = snapshot.get("watchdog", {})
    _family(lines, "gloo_tpu_watchdog_stalls_total", "counter",
            "Straggler-watchdog stalls recorded.")
    lines.append(f"gloo_tpu_watchdog_stalls_total{_fmt_labels(base)} "
                 f"{wd.get('stalls', 0)}")
    last = wd.get("last")
    if last:
        _family(lines, "gloo_tpu_watchdog_last_stall_waited_us", "gauge",
                "Wait time of the most recent recorded stall.")
        labels = {**base, "op": last.get("op", ""),
                  "peer": last.get("peer", -1)}
        lines.append(f"gloo_tpu_watchdog_last_stall_waited_us"
                     f"{_fmt_labels(labels)} {last.get('waited_us', 0)}")
    return "\n".join(lines) + "\n"


def _merge_hist(acc: dict, hist: dict) -> dict:
    if not acc:
        return {k: (list(map(list, v)) if k == "buckets" else v)
                for k, v in hist.items()}
    by_le = {le: n for le, n in acc.get("buckets", [])}
    for le, n in hist.get("buckets", []):
        by_le[le] = by_le.get(le, 0) + n
    acc["buckets"] = sorted([le, n] for le, n in by_le.items())
    acc["count"] = acc.get("count", 0) + hist.get("count", 0)
    acc["sum_us"] = acc.get("sum_us", 0) + hist.get("sum_us", 0)
    acc["max_us"] = max(acc.get("max_us", 0), hist.get("max_us", 0))
    return acc


def merge_snapshots(snapshots: Iterable[dict]) -> dict:
    """Sum per-rank snapshots into one job-level view: op counters and
    histograms add; transport keeps the per-(rank, peer) detail keyed as
    "rank->peer"; watchdog stalls add and the most recent stall wins."""
    merged: dict = {"ranks": [], "ops": {}, "transport": {},
                    "watchdog": {"stalls": 0, "last": None}}
    for snap in snapshots:
        merged["ranks"].append(snap.get("rank"))
        for op, s in snap.get("ops", {}).items():
            acc = merged["ops"].setdefault(
                op, {"calls": 0, "bytes": 0, "errors": 0,
                     "latency_us": {}})
            acc["calls"] += s.get("calls", 0)
            acc["bytes"] += s.get("bytes", 0)
            acc["errors"] += s.get("errors", 0)
            acc["latency_us"] = _merge_hist(acc["latency_us"],
                                            s.get("latency_us", {}))
        for peer, s in snap.get("transport", {}).items():
            merged["transport"][f"{snap.get('rank')}->{peer}"] = s
        wd = snap.get("watchdog", {})
        merged["watchdog"]["stalls"] += wd.get("stalls", 0)
        last = wd.get("last")
        prev = merged["watchdog"]["last"]
        # Recency across ranks compares age_us (relative to each rank's
        # own snapshot instant), NOT at_us: steady-clock epochs are
        # per-host boot times and never comparable across machines.
        if last and (prev is None
                     or last.get("age_us", 0) < prev.get("age_us", 0)):
            merged["watchdog"]["last"] = dict(last,
                                              rank=snap.get("rank"))
    return merged
