"""Cross-rank causal critical-path extraction over span streams.

The native span recorder (csrc/tpucoll/common/span.h,
docs/critpath.md) emits one causal span per phase instance of every
collective — annotated wire sends ("send"), FIFO-attributed arrivals
("recv"), drain waits ("wait"), local work ("local") — keyed by the
flight recorder's cross-rank collective sequence number ``cseq`` and a
per-op emission ordinal ``id``. This module is the cross-rank half:

- :func:`merge` joins per-rank ``Context.spans()`` snapshots by
  ``cseq`` into one span set per collective;
- :func:`analyze` builds each collective's causal graph — intra-rank
  program-order edges plus send->recv wire edges matched by
  ``(sender, receiver)`` FIFO ordinal — extracts the **longest weighted
  path** ending at the op's last-finishing span, attributes every
  segment of the op's latency to the span that gated it, and computes
  per-span **slack** (how far a span's finish could slip before it
  extends the op);
- :func:`to_perfetto` renders per-rank span tracks (Chrome trace-event
  JSON) with the critical path flagged on its own track.

Wire matching needs no timestamps: the k-th "send" span rank a emits
toward b pairs with the k-th "recv" span rank b emits from a (both
streams are in deterministic program order; the slot and byte count
ride along as sanity checks, mismatches are surfaced not guessed
around). Timestamps are per-host CLOCK_MONOTONIC; ``clock="auto"``
compares them raw when the per-rank origins sit within
:data:`CLOCK_SKEW_LIMIT_US` of each other (threads / processes on one
host share the clock) and falls back to aligning each rank's origin —
its earliest span in the first common collective — when they do not
(distinct hosts, distinct boot times). Force ``"raw"`` or ``"align"``
to override.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "CLOCK_SKEW_LIMIT_US",
    "analyze",
    "dump",
    "merge",
    "merge_by_group",
    "to_perfetto",
]

# Per-rank origins further apart than this (10 s) cannot be one host's
# monotonic clock observed through thread scheduling; auto mode aligns.
CLOCK_SKEW_LIMIT_US = 10_000_000


def dump(ctx, directory: str) -> str:
    """Write ``ctx.spans()`` to ``directory/spans-rank<r>.json`` (the
    file layout ``tools/critpath_view.py`` globs) and return the path."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"spans-rank{ctx.rank}.json")
    with open(path, "w") as f:
        json.dump(ctx.spans(), f)
    return path


def merge(snapshots: Iterable[dict], group: Optional[str] = None,
          ) -> dict:
    """Join per-rank ``Context.spans()`` snapshots by ``cseq``.

    Returns ``{"group": g, "ranks": [r, ...], "size": n,
    "duplicates": [r, ...], "skipped_groups": [g, ...],
    "ops": {cseq: {rank: [span, ...]}}}`` with each rank's span list in
    emission (``id``) order. Spans whose cseq is null (p2p ops) are
    skipped. The same two rails as ``utils.profile.merge``: one
    communicator per merge (mismatched ``group`` tags are skipped, use
    :func:`merge_by_group` for mixed sets) and one snapshot per rank
    (the last wins, the rank lands in ``duplicates``)."""
    by_rank: Dict[int, dict] = {}
    duplicates: List[int] = []
    skipped_groups: List[str] = []
    size = 0
    for snap in snapshots:
        if not isinstance(snap, dict) or "spans" not in snap:
            continue
        rank = int(snap.get("rank", -1))
        if rank < 0:
            continue
        snap_group = str(snap.get("group", "") or "")
        if group is None:
            group = snap_group
        if snap_group != group:
            if snap_group not in skipped_groups:
                skipped_groups.append(snap_group)
            continue
        if rank in by_rank and rank not in duplicates:
            duplicates.append(rank)
        by_rank[rank] = snap
        size = max(size, int(snap.get("size", 0)), rank + 1)
    ops: Dict[int, Dict[int, List[dict]]] = {}
    for rank, snap in by_rank.items():
        for span in snap.get("spans", []):
            cseq = span.get("cseq")
            if cseq is None:
                continue
            ops.setdefault(int(cseq), {}).setdefault(rank,
                                                     []).append(span)
    for per_rank in ops.values():
        for spans in per_rank.values():
            spans.sort(key=lambda s: int(s.get("id", 0)))
    return {"group": group or "", "ranks": sorted(by_rank),
            "size": size, "duplicates": sorted(duplicates),
            "skipped_groups": sorted(skipped_groups), "ops": ops}


def merge_by_group(snapshots: Iterable[dict]) -> Dict[str, dict]:
    """Partition snapshots by ``group`` tag, then :func:`merge` each
    partition (disjoint communicators must never be cseq-compared)."""
    partitions: Dict[str, List[dict]] = {}
    for snap in snapshots:
        if not isinstance(snap, dict) or "spans" not in snap:
            continue
        partitions.setdefault(str(snap.get("group", "") or ""),
                              []).append(snap)
    return {g: merge(snaps, group=g)
            for g, snaps in sorted(partitions.items())}


def _origins(merged: dict) -> Dict[int, int]:
    """Per-rank clock origin: the rank's earliest span start in the
    first cseq every merged rank participates in (all ranks enter a
    collective within one schedule of each other, so the origins bound
    the clock offsets), falling back to the rank's earliest span."""
    ranks = set(merged.get("ranks", []))
    common = None
    for cseq in sorted(merged.get("ops", {})):
        if set(merged["ops"][cseq]) == ranks:
            common = cseq
            break
    origins: Dict[int, int] = {}
    for rank in ranks:
        t0s: List[int] = []
        if common is not None and rank in merged["ops"][common]:
            t0s = [int(s.get("t0_us", 0))
                   for s in merged["ops"][common][rank]]
        if not t0s:
            t0s = [int(s.get("t0_us", 0))
                   for per in merged.get("ops", {}).values()
                   for r, spans in per.items() if r == rank
                   for s in spans]
        origins[rank] = min(t0s) if t0s else 0
    return origins


def _resolve_clock(merged: dict, clock: str) -> Tuple[str, Dict[int, int]]:
    origins = _origins(merged)
    if clock == "raw":
        return "raw", {r: 0 for r in origins}
    if clock == "align":
        return "align", origins
    if clock != "auto":
        raise ValueError(f"clock must be auto/raw/align, got {clock!r}")
    if origins and (max(origins.values()) - min(origins.values())
                    > CLOCK_SKEW_LIMIT_US):
        return "align", origins
    return "raw", {r: 0 for r in origins}


class _Node:
    __slots__ = ("rank", "span", "t0", "t1", "preds", "deps", "wire")

    def __init__(self, rank: int, span: dict, shift: int):
        self.rank = rank
        self.span = span
        self.t0 = int(span.get("t0_us", 0)) - shift
        self.t1 = int(span.get("t1_us", 0)) - shift
        self.preds: List["_Node"] = []
        self.deps: List["_Node"] = []
        self.wire: Optional["_Node"] = None

    def row(self) -> dict:
        s = self.span
        return {"rank": self.rank, "id": s.get("id"),
                "kind": s.get("kind"), "phase": s.get("phase"),
                "peer": s.get("peer"), "slot": s.get("slot"),
                "bytes": s.get("bytes", 0), "t0_us": self.t0,
                "t1_us": self.t1}


def _build_graph(per_rank: Dict[int, List[dict]],
                 shifts: Dict[int, int],
                 ) -> Tuple[List[_Node], Dict[str, int]]:
    """One collective's causal DAG: program-order chains per rank plus
    send->recv edges matched by directed-pair FIFO ordinal."""
    nodes: List[_Node] = []
    sends: Dict[Tuple[int, int], List[_Node]] = {}
    recvs: Dict[Tuple[int, int], List[_Node]] = {}
    for rank in sorted(per_rank):
        prev: Optional[_Node] = None
        for span in per_rank[rank]:
            node = _Node(rank, span, shifts.get(rank, 0))
            if prev is not None:
                node.preds.append(prev)
                prev.deps.append(node)
            prev = node
            nodes.append(node)
            peer = span.get("peer")
            if peer is None:
                continue
            if span.get("kind") == "send":
                sends.setdefault((rank, int(peer)), []).append(node)
            elif span.get("kind") == "recv":
                recvs.setdefault((int(peer), rank), []).append(node)
    unmatched = {"sends": 0, "recvs": 0, "mismatched": 0}
    for pair, recv_q in recvs.items():
        send_q = sends.get(pair, [])
        for k, recv in enumerate(recv_q):
            if k >= len(send_q):
                unmatched["recvs"] += 1
                continue
            send = send_q[k]
            if (send.span.get("slot") != recv.span.get("slot") or
                    send.span.get("bytes") != recv.span.get("bytes")):
                unmatched["mismatched"] += 1
            recv.preds.append(send)
            recv.wire = send
            send.deps.append(recv)
        if len(send_q) > len(recv_q):
            unmatched["sends"] += len(send_q) - len(recv_q)
    for pair, send_q in sends.items():
        if pair not in recvs:
            unmatched["sends"] += len(send_q)
    return nodes, unmatched


# A drain wait that merely OBSERVES an arrival finishes this much later
# than the arrival it observed (scheduling latency of the waiting
# thread). Within this window the wire edge is the cause, not the wait.
_OBSERVATION_EPS_US = 1000


def _walk_critical_path(nodes: List[_Node]) -> List[dict]:
    """Backward walk from the last-finishing span: at each span the
    binding predecessor is the latest-finishing one, and the segment
    ``[max(pred.t1, t0), t1]`` of the op's latency is attributed to the
    span that spent it, clipped below the previously attributed
    segment — segments stay disjoint, so the rows' contribs never sum
    past the op's total. Returned origin-first, each row carrying
    ``contrib_us``.

    One asymmetry: at a matched recv that sat blocked on the wire
    beyond scheduling noise while its rank's local chain was already
    done by the arrival, a program-order predecessor finishing within
    observation latency of the arrival is a drain wait that merely
    *noticed* the message — the walk hops the wire to the sender that
    caused the stall instead of stranding the blocked time on the
    waiting rank."""
    if not nodes:
        return []
    # Ties on t1 go to the later-emitted span of the lower rank: at
    # equal finish times the later program-order span is the one that
    # actually closed the op (a drain wait and the recv it observed
    # round to the same microsecond).
    cur = max(nodes, key=lambda n: (n.t1, -n.rank,
                                    int(n.span.get("id", 0))))
    rows: List[dict] = []
    seen = set()
    # Everything at or above `horizon` is already attributed. A span on
    # the chain is credited only below it — a predecessor can outlive
    # the point where it gated (a send's post call returning after the
    # message was consumed), and its overlap with downstream segments
    # was not gating anything.
    horizon = cur.t1
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        pred = None
        if cur.preds:
            pred = max(cur.preds, key=lambda p: p.t1)
        gate = max(pred.t1, cur.t0) if pred is not None else cur.t0
        if pred is not None:
            wire = cur.wire
            # cur.t1 is the arrival. The wire was the binding gate iff
            # this rank sat blocked on it beyond scheduling noise
            # (arrival far after the recv post), its local chain was
            # done by the arrival (a program pred finishing within
            # observation latency of cur.t1 is the drain wait that
            # merely noticed this message), AND the matched send was
            # still in flight at the recv post — arrival stamps are
            # observation-derived, so a message that landed long ago
            # still shows a late arrival on a busy receiver. Only with
            # all three follow the sender; otherwise the local chain
            # is the cause.
            if (wire is not None and pred is not wire
                    and wire.t1 >= cur.t0
                    and cur.t1 - cur.t0 > _OBSERVATION_EPS_US
                    and pred.t1 - cur.t1 <= _OBSERVATION_EPS_US):
                pred = wire
        hi = min(cur.t1, horizon)
        lo = max(gate, cur.t0)
        row = cur.row()
        row["contrib_us"] = max(hi - lo, 0)
        horizon = min(hi, lo)
        rows.append(row)
        cur = pred
    rows.reverse()
    return rows


def _slacks(nodes: List[_Node], end_us: int) -> None:
    """Backward propagation of each span's latest allowable finish:
    sinks may finish at the op's end; elsewhere a span may finish no
    later than every dependent's latest finish minus the dependent's
    own gated busy time. Stored on the node's span row by the caller.
    An approximation (a dependent's busy time is treated as fixed), but
    exact on the critical path, which pins slack 0 where it matters."""
    order: List[_Node] = []
    indeg = {id(n): len(n.deps) for n in nodes}
    stack = [n for n in nodes if not n.deps]
    while stack:
        n = stack.pop()
        order.append(n)
        for p in n.preds:
            indeg[id(p)] -= 1
            if indeg[id(p)] == 0:
                stack.append(p)
    latest = {id(n): end_us for n in nodes}
    for n in order:
        if not n.deps:
            latest[id(n)] = end_us
            continue
        allowed = []
        for d in n.deps:
            gate = max([p.t1 for p in d.preds] + [d.t0])
            busy = max(d.t1 - gate, 0)
            allowed.append(latest[id(d)] - busy)
        latest[id(n)] = min(allowed)
    for n in nodes:
        n.span["_slack_us"] = max(latest[id(n)] - n.t1, 0)


def analyze(merged: dict, clock: str = "auto") -> dict:
    """Causal analysis of every merged collective.

    Returns ``{"clock": "raw"|"align", "ranks", "ops": [{"cseq", "op",
    "bytes", "start_us", "end_us", "total_us", "path": [row, ...],
    "attribution": {rank: {kind: us}}, "slack": [row, ...],
    "unmatched": {...}}, ...]}`` with ops sorted by cseq. ``path`` runs
    origin-first; each row's ``contrib_us`` is the stretch of the op's
    latency that span gated (the rows' contribs sum to ~``total_us``).
    ``attribution`` folds the path's contribs by (rank, kind) — the
    table ``critpath_view --check`` thresholds against. ``slack`` lists
    every span's headroom ascending (the leaderboard's tail is where
    optimization effort is wasted)."""
    mode, shifts = _resolve_clock(merged, clock)
    out_ops = []
    for cseq in sorted(merged.get("ops", {})):
        per_rank = merged["ops"][cseq]
        nodes, unmatched = _build_graph(per_rank, shifts)
        if not nodes:
            continue
        start = min(n.t0 for n in nodes)
        end = max(n.t1 for n in nodes)
        path = _walk_critical_path(nodes)
        _slacks(nodes, end)
        attribution: Dict[int, Dict[str, int]] = {}
        for row in path:
            kinds = attribution.setdefault(int(row["rank"]), {})
            kind = str(row["kind"])
            kinds[kind] = kinds.get(kind, 0) + int(row["contrib_us"])
        slack_rows = []
        for n in nodes:
            row = n.row()
            row["slack_us"] = n.span.pop("_slack_us", 0)
            slack_rows.append(row)
        slack_rows.sort(key=lambda r: (r["slack_us"], r["rank"],
                                       r["id"]))
        first = per_rank[min(per_rank)][0] if per_rank else {}
        out_ops.append({
            "cseq": cseq,
            "op": first.get("op"),
            "bytes": max((int(s.get("bytes", 0))
                          for spans in per_rank.values()
                          for s in spans), default=0),
            "start_us": start,
            "end_us": end,
            "total_us": end - start,
            "path": path,
            "attribution": attribution,
            "slack": slack_rows,
            "unmatched": unmatched,
        })
    return {"clock": mode, "ranks": merged.get("ranks", []),
            "ops": out_ops}


def to_perfetto(merged: dict, analysis: Optional[dict] = None,
                clock: str = "auto") -> str:
    """Chrome trace-event JSON with per-rank step tracks.

    One row per rank (pid = rank): tid 0 carries every span (named by
    kind, with id/peer/slot in args), tid 1 re-renders the spans on the
    critical path (``analysis`` defaults to :func:`analyze` of the same
    merge) so the cross-rank chain reads as a highlighted staircase.
    Timestamps follow the analysis' clock resolution, re-zeroed to the
    earliest span. Load in ui.perfetto.dev."""
    if analysis is None:
        analysis = analyze(merged, clock=clock)
    mode, shifts = _resolve_clock(merged, clock if clock != "auto"
                                  else analysis.get("clock", "auto"))
    events = []
    pids = set()
    origin = None
    for per_rank in merged.get("ops", {}).values():
        for rank, spans in per_rank.items():
            for s in spans:
                t0 = int(s.get("t0_us", 0)) - shifts.get(rank, 0)
                origin = t0 if origin is None else min(origin, t0)
    origin = origin or 0
    for cseq in sorted(merged.get("ops", {})):
        for rank, spans in merged["ops"][cseq].items():
            pids.add(rank)
            for s in spans:
                t0 = int(s.get("t0_us", 0)) - shifts.get(rank, 0)
                t1 = int(s.get("t1_us", 0)) - shifts.get(rank, 0)
                events.append({
                    "name": f"{s.get('kind')}:{s.get('op', '?')}",
                    "ph": "X", "ts": t0 - origin,
                    "dur": max(t1 - t0, 1), "pid": rank, "tid": 0,
                    "args": {"cseq": cseq, "id": s.get("id"),
                             "phase": s.get("phase"),
                             "peer": s.get("peer"),
                             "slot": s.get("slot"),
                             "bytes": s.get("bytes")}})
    for op in analysis.get("ops", []):
        for row in op.get("path", []):
            pids.add(row["rank"])
            events.append({
                "name": f"CRIT {row['kind']}"
                        + (f"->r{row['peer']}"
                           if row.get("peer") is not None else ""),
                "ph": "X", "ts": int(row["t0_us"]) - origin,
                "dur": max(int(row["t1_us"]) - int(row["t0_us"]), 1),
                "pid": row["rank"], "tid": 1,
                "args": {"cseq": op["cseq"],
                         "contrib_us": row["contrib_us"]}})
    meta = []
    for pid in sorted(pids):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"rank {pid}"}})
        meta.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"sort_index": pid}})
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": "spans"}})
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": 1, "args": {"name": "critical path"}})
    return json.dumps(meta + events)
