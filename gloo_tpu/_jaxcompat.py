"""Version compatibility for the jax surface the device plane uses.

The device-plane code targets the current jax API (``jax.shard_map``,
``jax.lax.axis_size``); older jax releases (0.4.x) ship the same
functionality under different names (``jax.experimental.shard_map`` with
``check_rep``, axis sizes via ``jax.core.axis_frame``). Importing this
module installs the MISSING upstream names with their exact upstream
semantics, so every call site stays written against the modern API and
keeps working untouched when the container pins an old jax. On a modern
jax this module is a no-op.
"""

from __future__ import annotations

import jax
from jax import lax

__all__ = ["install"]


def _axis_size_compat(axis_name):
    """lax.axis_size for jax < 0.4.38: static mesh axis size inside
    shard_map/pmap traces. axis_frame returned the bare int in some 0.4.x
    releases and a frame object with .size in others."""
    import jax.core as core

    frame = core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def _shard_map_compat(f=None, **kwargs):
    """jax.shard_map for jax < 0.6: the experimental module's entry with
    the check_vma keyword translated to its old name check_rep."""
    from jax.experimental.shard_map import shard_map as _sm

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return lambda g: _sm(g, **kwargs)
    return _sm(f, **kwargs)


def _pcast_compat(x, axis_name=None, to=None):
    """lax.pcast for jax < 0.7: purely a varying/invariant TYPE cast in
    the new shard_map vma system — identity on values. Old jax has no
    vma tracking (shard_map runs with check_rep=False there), so the
    identity is the exact semantics."""
    del axis_name, to
    return x


def _sds_vma_tolerant():
    """jax.ShapeDtypeStruct accepting (and dropping) the vma= keyword on
    jax releases that predate it."""
    orig = jax.ShapeDtypeStruct

    class ShapeDtypeStruct(orig):  # noqa: N801 - upstream name
        def __init__(self, shape, dtype, *args, vma=None, **kwargs):
            del vma  # no vma tracking on this jax
            super().__init__(shape, dtype, *args, **kwargs)

    return ShapeDtypeStruct


def pallas_interpret_available() -> bool:
    """True when pallas ships the distributed TPU interpreter
    (pltpu.InterpretParams) that emulates remote DMAs + semaphores on a
    CPU mesh. The Pallas ring/overlap kernels need it to run off-TPU;
    callers (e.g. dryrun_multichip) gate those sections on this."""
    try:
        import jax.experimental.pallas.tpu as pltpu
    except ImportError:
        return False
    return hasattr(pltpu, "InterpretParams")


def _install_pallas_compat() -> None:
    try:
        import jax.experimental.pallas.tpu as pltpu
    except ImportError:
        return
    if hasattr(pltpu, "CompilerParams") or not hasattr(pltpu,
                                                       "TPUCompilerParams"):
        return
    import dataclasses

    allowed = {f.name for f in dataclasses.fields(pltpu.TPUCompilerParams)}

    def compiler_params(**kwargs):
        # TPUCompilerParams is the pre-rename spelling; fields that only
        # exist in the modern class (e.g. has_side_effects) are dropped —
        # the kernels passing them also need the distributed interpreter
        # (pallas_interpret_available), so they cannot run on this jax
        # either way.
        return pltpu.TPUCompilerParams(
            **{k: v for k, v in kwargs.items() if k in allowed})

    pltpu.CompilerParams = compiler_params


def install() -> None:
    """Install the missing names (idempotent)."""
    if not hasattr(lax, "axis_size"):
        lax.axis_size = _axis_size_compat
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_compat
    if not hasattr(lax, "pcast"):
        lax.pcast = _pcast_compat
    if not hasattr(jax, "typeof"):
        # jax.typeof(x) is the public aval accessor; get_aval is its
        # pre-0.6 spelling (no vma field there — callers that probe
        # .vma use getattr with a default).
        import jax.core as _core

        jax.typeof = _core.get_aval
    try:
        jax.ShapeDtypeStruct((1,), "float32", vma=frozenset())
    except TypeError:
        jax.ShapeDtypeStruct = _sds_vma_tolerant()
    _install_pallas_compat()


install()
