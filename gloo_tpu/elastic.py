"""Elastic membership plane: lease-based liveness, epoch transitions,
and automatic shrink/grow recovery (docs/elastic.md).

`resilience.rebuild_after_failure` is application-driven: the program
must catch the error, pick a generation, and hand-drive the roll call.
This module inverts the control flow — the SYSTEM detects membership
changes and the application just retries its step:

- every worker runs a native :class:`ElasticAgent`
  (csrc/tpucoll/elastic/): a background heartbeat thread renews a store
  lease every ``TPUCOLL_LEASE_MS``, and a monitor thread watches the
  other members' leases (expiry after ``TPUCOLL_LEASE_GRACE`` ms of no
  renewal = death; a deleted lease = graceful leave) plus the published
  epoch documents;
- the coordinator (lowest live worker id, re-elected by liveness)
  publishes ``{epoch, members}`` documents on lease expiry, on hard
  failure evidence from survivors (watchdog stall verdicts,
  ``transport_failure`` records, flight-recorder tails — published here
  via :meth:`ElasticContext.translate_failure`), and on join requests
  (a respawned or brand-new worker enqueues and is admitted at the next
  boundary, growing the group back to full size);
- an epoch bump CLOSES the bound context, so in-flight collectives
  raise typed errors instead of hanging; :class:`ElasticContext`
  translates them into :class:`EpochChanged`, and :func:`run_elastic`
  drives detect -> agree -> rebuild -> resume automatically (rebuilding
  async engines / gradient bucketers, restoring from a
  :class:`~gloo_tpu.checkpoint.StepCheckpointer` when given).

Minimal usage (every worker runs the same code; no manual rebuild
anywhere)::

    def step_fn(ectx, step, state):
        grad = compute_grad(state)
        ectx.allreduce(grad)          # EpochChanged on membership moves
        return apply(state, grad)

    summary = run_elastic(step_fn, store=store, device=gloo_tpu.Device(),
                          rank=rank, world_size=4, steps=1000,
                          min_size=2, checkpointer=ckpt, template=tmpl)

A replacement worker rejoins with ``join=True`` (rank is then ignored —
it receives a fresh worker id and the next epoch's membership assigns
its rank).
"""

from __future__ import annotations

import ctypes
import json
import time
from typing import Any, Callable, Dict, Optional

from gloo_tpu import _lib, core
from gloo_tpu._lib import Aborted, Error, IoError, check, check_handle

__all__ = [
    "BelowMinSize",
    "ElasticAgent",
    "ElasticContext",
    "EpochChanged",
    "Evicted",
    "Left",
    "run_elastic",
]

_copy_out = _lib.copy_out


class EpochChanged(Error):
    """The membership moved past the epoch this collective ran in: a
    member died (lease expiry), left, was voted out on failure
    evidence, or new members were admitted. The old context is
    poisoned; call :meth:`ElasticContext.rebuild` (or let
    :func:`run_elastic` do it) and retry the step. ``epoch`` is the new
    head epoch."""

    def __init__(self, message: str, epoch: int):
        super().__init__(message)
        self.epoch = epoch


class Evicted(Error):
    """This worker was voted OUT of the membership (its lease expired —
    e.g. a long pause — or it was blamed on failure evidence twice
    running). Rejoin with a fresh join=True agent, or exit."""


class BelowMinSize(Error):
    """The membership shrank under ``min_size``: too few survivors to
    continue. Raised from rebuild on EVERY survivor — the loud,
    typed end the min-size contract promises."""


class Left(Error):
    """This worker gracefully departed via :meth:`ElasticContext.leave`
    (control-flow signal consumed by :func:`run_elastic`)."""


def _failure_evidence(ctx, members) -> dict:
    """This rank's verdict on a broken collective, in wid terms: the
    straggler-watchdog / transport-failure suspect (resilience's
    evidence extractor) mapped through the epoch's member list, plus
    the flight-recorder fingerprint tail."""
    from gloo_tpu.resilience import _stall_evidence

    evidence = _stall_evidence(ctx) or {"suspect": -1}
    suspect = evidence.get("suspect", -1)
    wid = -1
    if isinstance(suspect, int) and 0 <= suspect < len(members):
        wid = members[suspect]
    evidence["suspect_wid"] = wid
    return evidence


def _wrap_context(handle: int, timeout: float, store, device):
    """Wrap a native context handle from tc_elastic_rebuild (ownership
    transfers to the wrapper; the agent must be unbound from it before
    the wrapper is dropped)."""
    obj = core.Context.__new__(core.Context)
    obj.rank = int(_lib.lib.tc_context_rank(handle))
    obj.size = int(_lib.lib.tc_context_size(handle))
    obj._timeout = timeout
    obj._handle = handle
    obj._store = store
    obj._device = device
    obj._engines = []
    obj._free = _lib.lib.tc_context_free
    return obj


class ElasticAgent:
    """Handle to the native membership agent (heartbeat + monitor
    threads). Most applications use :class:`ElasticContext` /
    :func:`run_elastic` instead of driving this directly."""

    # Class-level fallbacks so __del__ is safe when __init__ raised
    # before assignment.
    _handle = None
    _free = staticmethod(lambda handle: None)

    def __init__(self, store: core.Store, device: core.Device, *,
                 rank: int = 0, world_size: int = 1, min_size: int = 1,
                 join: bool = False, host_id: Optional[str] = None,
                 timeout: float = 60.0):
        self._store = store    # keep the handles alive
        self._device = device
        self._handle = check_handle(_lib.lib.tc_elastic_new(
            store._handle, device._handle, rank, world_size, min_size,
            1 if join else 0, host_id.encode() if host_id else None,
            int(timeout * 1000)))
        self._free = _lib.lib.tc_elastic_free
        self.timeout = timeout

    def __del__(self):
        handle, self._handle = self._handle, None
        if handle:
            self._free(handle)

    def rebuild(self, timeout: Optional[float] = None) -> core.Context:
        """Build the communicator for the current head epoch and bind
        it as this agent's monitored context. Typed failures:
        :class:`Evicted`, :class:`BelowMinSize`,
        :class:`~gloo_tpu.TimeoutError`."""
        out = ctypes.c_void_p()
        ms = 0 if timeout is None else max(1, int(timeout * 1000))
        code = _lib.lib.tc_elastic_rebuild(self._handle, ms,
                                           ctypes.byref(out))
        if code != 0:
            msg = _lib.last_error()
            if "evicted" in msg:
                raise Evicted(msg)
            if "below min_size" in msg:
                raise BelowMinSize(msg)
            check(code)
        return _wrap_context(check_handle(out.value), self.timeout,
                             self._store, self._device)

    def note_failure(self, evidence: dict) -> None:
        """Publish hard failure evidence ({"suspect_wid": w|-1, ...})
        for the bound epoch; the coordinator folds it into the next
        membership decision."""
        check(_lib.lib.tc_elastic_note_failure(
            self._handle, json.dumps(evidence).encode()))

    def stop(self) -> None:
        """Graceful leave: stop the threads and delete this worker's
        lease (peers observe the departure immediately). Idempotent."""
        check(_lib.lib.tc_elastic_stop(self._handle))

    def epoch(self) -> int:
        return int(_lib.lib.tc_elastic_epoch(self._handle))

    def head_epoch(self) -> int:
        return int(_lib.lib.tc_elastic_head_epoch(self._handle))

    def poll(self) -> bool:
        """True when the membership moved past the bound epoch (the
        bound collective surface is — or is about to be — poisoned)."""
        return bool(_lib.lib.tc_elastic_poll(self._handle))

    def status(self) -> dict:
        """{"epoch", "head_epoch", "wid", "rank", "size", "members",
        "target_size", "min_size", "coordinator", "join_pending",
        "leases_renewed", "rebuilds", "bumps_published",
        "last_rebuild_ms", "fault_domain", "lease_ms",
        "lease_grace_ms"} — also attached as metrics()["elastic"] by
        ElasticContext (docs/observability.md)."""
        return json.loads(_copy_out(_lib.lib.tc_elastic_status_json,
                                    self._handle))


class ElasticContext:
    """A process-group context that survives membership changes.

    Wraps the current epoch's :class:`~gloo_tpu.Context`; every
    collective that fails because the membership moved raises
    :class:`EpochChanged` instead of a raw IoError (after publishing
    this rank's failure evidence for the coordinator's verdict).
    :meth:`rebuild` swaps in the next epoch's context and re-binds the
    attachments created through this wrapper (async engines, gradient
    bucketers). ``rank`` / ``size`` always describe the CURRENT epoch.
    """

    def __init__(self, store: core.Store, device: core.Device, *,
                 rank: int = 0, world_size: int = 1, min_size: int = 1,
                 join: bool = False, host_id: Optional[str] = None,
                 timeout: float = 60.0):
        self._store = store
        self._device = device
        self._agent = ElasticAgent(
            store, device, rank=rank, world_size=world_size,
            min_size=min_size, join=join, host_id=host_id, timeout=timeout)
        self._grace_s = self._agent.status()["lease_grace_ms"] / 1000.0
        self._ctx: Optional[core.Context] = None
        self._engines: Dict[tuple, core.AsyncEngine] = {}
        self._bucketers: Dict[tuple, Any] = {}
        self.rebuild()

    # ---- identity of the current epoch ----

    @property
    def rank(self) -> int:
        return self._ctx.rank

    @property
    def size(self) -> int:
        return self._ctx.size

    @property
    def agent(self) -> ElasticAgent:
        return self._agent

    @property
    def context(self) -> core.Context:
        """The current epoch's raw Context (poisoned on the next
        membership change — prefer calling collectives through the
        wrapper, which translates failures)."""
        return self._ctx

    def status(self) -> dict:
        return self._agent.status()

    def epoch(self) -> int:
        return self._agent.epoch()

    # ---- failure translation ----

    def translate_failure(self, exc: BaseException):
        """Turn a collective failure into :class:`EpochChanged` when the
        membership moved (or is about to move): publishes this rank's
        failure evidence, then waits up to ~3 lease-grace windows for
        the coordinator's verdict. Re-raises `exc` unchanged when the
        membership holds (a genuine, non-membership failure). Public so
        failures surfacing OUTSIDE the wrapped collectives — e.g. a
        Work.wait() or GradientBucketer.finish() on an engine created
        through this wrapper — can join the same recovery path."""
        try:
            members = self._agent.status().get("members", [])
            self._agent.note_failure(_failure_evidence(self._ctx, members))
        except Exception:  # noqa: BLE001 - evidence is best-effort
            pass
        deadline = time.time() + 3.0 * self._grace_s + 1.0
        while time.time() < deadline:
            if self._agent.poll():
                head = self._agent.head_epoch()
                raise EpochChanged(
                    f"membership moved to epoch {head} "
                    f"(was {self._agent.epoch()}): {exc}", head) from exc
            time.sleep(0.05)
        raise exc

    def rebuild(self, timeout: Optional[float] = None) -> "ElasticContext":
        """Swap in the communicator for the current head epoch:
        shuts down engines bound to the old epoch, rebuilds through the
        agent (typed: Evicted / BelowMinSize / TimeoutError), closes and
        releases the old context. Attachments created through
        :meth:`async_engine` / :meth:`bucketer` are re-created lazily on
        next use — the re-binding `run_elastic` relies on."""
        self._shutdown_attachments()
        old = self._ctx
        self._ctx = self._agent.rebuild(timeout)
        if old is not None:
            try:
                old.close()  # idempotent; the monitor usually closed it
            except Exception:  # noqa: BLE001 - already-poisoned context
                pass
        return self

    def leave(self):
        """Graceful departure: peers observe the deleted lease
        immediately (no grace wait) and shrink at the next epoch.
        Raises :class:`Left` (consumed by :func:`run_elastic`)."""
        self.close()
        raise Left(f"wid {self._agent.status()['wid']} left the group")

    def close(self) -> None:
        """Stop the agent (graceful leave) and close the bound context.
        Idempotent."""
        self._shutdown_attachments()
        try:
            self._agent.stop()
        finally:
            if self._ctx is not None:
                try:
                    self._ctx.close()
                except Exception:  # noqa: BLE001 - poisoned context
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---- per-epoch attachments (re-bound on rebuild) ----

    def async_engine(self, lanes: Optional[int] = None,
                     tag_base: int = 0) -> core.AsyncEngine:
        """The current epoch's async engine for this spec (created on
        first use per epoch — a COLLECTIVE, so every member must reach
        it together, exactly like Context.async_engine). After a
        rebuild the next call creates a fresh engine on the new mesh."""
        key = (lanes, tag_base)
        engine = self._engines.get(key)
        if engine is None or not engine._handle:
            engine = self._ctx.async_engine(lanes=lanes, tag_base=tag_base)
            self._engines[key] = engine
        return engine

    def bucketer(self, bucket_bytes: Optional[int] = None,
                 lanes: Optional[int] = None):
        """The current epoch's GradientBucketer over
        :meth:`async_engine` (re-created per epoch; buffers re-bind to
        the new lanes). Failures from its finish()/wait() should be
        routed through :meth:`translate_failure`."""
        from gloo_tpu.bucketer import GradientBucketer

        key = (bucket_bytes, lanes)
        bucketer = self._bucketers.get(key)
        if bucketer is None:
            kwargs = {}
            if bucket_bytes is not None:
                kwargs["bucket_bytes"] = bucket_bytes
            bucketer = GradientBucketer(self.async_engine(lanes=lanes),
                                        **kwargs)
            self._bucketers[key] = bucketer
        return bucketer

    def _shutdown_attachments(self) -> None:
        self._bucketers.clear()
        engines, self._engines = self._engines, {}
        for engine in engines.values():
            try:
                engine.shutdown()
            except Exception:  # noqa: BLE001 - poisoned lanes
                pass

    # ---- observability ----

    def metrics(self, drain: bool = False) -> dict:
        """Context.metrics() of the current epoch, with the agent's
        membership status attached under "elastic" (epoch gauge, member
        count, leases_renewed / rebuilds counters —
        docs/observability.md)."""
        snap = self._ctx.metrics(drain)
        snap["elastic"] = self._agent.status()
        return snap

    def __getattr__(self, name: str):
        # Everything else (flightrec, group_tag, topology, register,
        # plans, ...) delegates to the current epoch's context. Private
        # names never delegate: during __init__ self._ctx does not exist
        # yet and delegating "_ctx" itself would recurse.
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._ctx, name)


def _wrap_collective(name: str) -> Callable:
    def method(self, *args, **kwargs):
        try:
            return getattr(self._ctx, name)(*args, **kwargs)
        except (IoError, Aborted) as exc:  # TimeoutError subclasses IoError
            self.translate_failure(exc)
            raise AssertionError("unreachable")  # translate always raises

    method.__name__ = name
    method.__qualname__ = f"ElasticContext.{name}"
    method.__doc__ = (
        f"Context.{name} on the current epoch's mesh; raises "
        f":class:`EpochChanged` instead of IoError when the membership "
        f"moved (see :meth:`ElasticContext.translate_failure`).")
    return method


for _name in ("allreduce", "allreduce_multi", "reduce", "reduce_scatter",
              "reduce_scatter_inplace", "broadcast", "barrier", "allgather",
              "allgatherv", "gather", "gatherv", "scatter", "alltoall",
              "alltoallv", "send", "recv"):
    setattr(ElasticContext, _name, _wrap_collective(_name))


def run_elastic(step_fn: Callable, *, store: core.Store,
                device: core.Device, rank: int = 0, world_size: int = 1,
                steps: Optional[int] = None, min_size: int = 1,
                join: bool = False, host_id: Optional[str] = None,
                state: Any = None, checkpointer=None, template=None,
                max_rebuilds: int = 64,
                timeout: float = 60.0) -> dict:
    """Run ``state = step_fn(ectx, step, state)`` for `steps` successful
    steps (None = until `step_fn` raises StopIteration or leaves),
    recovering from membership changes automatically: on
    :class:`EpochChanged` the group is rebuilt (detect -> agree ->
    rebuild -> resume — no application-level rebuild call anywhere),
    engines/bucketers re-bind, and when a `checkpointer`
    (:class:`~gloo_tpu.checkpoint.StepCheckpointer`) is given, `state`
    and the step counter restore from the newest committed checkpoint
    (resuming at its step + 1). Without a checkpointer the failed step
    simply retries — `step_fn` must then tolerate a retried step whose
    in-place buffers hold undefined contents (docs/errors.md).

    :class:`Evicted` / :class:`BelowMinSize` propagate: the caller (or
    its supervisor) decides whether to rejoin (join=True) or die.

    Returns {"steps", "rebuilds", "epochs": [{"epoch", "size", "rank",
    "group"}...], "rebuild_ms": [...], "elastic": final agent status,
    "stopped": bool, "left": bool, "state": final state}.
    """
    ectx = ElasticContext(store, device, rank=rank, world_size=world_size,
                          min_size=min_size, join=join, host_id=host_id,
                          timeout=timeout)
    summary: dict = {"steps": 0, "rebuilds": 0, "epochs": [],
                     "rebuild_ms": [], "stopped": False, "left": False}

    def record_epoch():
        summary["epochs"].append({
            "epoch": ectx.epoch(), "size": ectx.size, "rank": ectx.rank,
            "group": ectx.group_tag()})

    record_epoch()
    step = 0
    try:
        while steps is None or step < steps:
            try:
                state = step_fn(ectx, step, state)
                step += 1
                summary["steps"] += 1
            except StopIteration:
                summary["stopped"] = True
                break
            except Left:
                summary["left"] = True
                break
            except EpochChanged:
                summary["rebuilds"] += 1
                if summary["rebuilds"] > max_rebuilds:
                    raise
                ectx.rebuild()
                summary["rebuild_ms"].append(
                    ectx.status().get("last_rebuild_ms", -1))
                record_epoch()
                if checkpointer is not None:
                    ck_step, ck_state = checkpointer.load_latest(template)
                    if ck_step is not None:
                        step, state = int(ck_step) + 1, ck_state
        summary["elastic"] = ectx.status()
        summary["state"] = state
    finally:
        ectx.close()
    return summary
