"""Launcher-environment bootstrap: Context from mpirun/srun/torchrun.

The reference's mpi::Context (gloo/mpi/context.cc:88-140) serves the
"my cluster already runs MPI" deployment: ranks discover each other
through the communicator the launcher created, no store configuration
in user code. The TPU-native equivalent keys off the same launch
metadata — every mainstream launcher exports rank/world-size into the
environment — and runs the ordinary TcpStore rendezvous over it, with
rank 0 serving the store:

    ctx, server = gloo_tpu.init_from_env()   # inside mpirun/srun/torchrun

Recognized (first match wins):
  rank/size: RANK + WORLD_SIZE (torchrun), OMPI_COMM_WORLD_RANK/_SIZE
    (Open MPI), PMI_RANK/PMI_SIZE (MPICH/Hydra), SLURM_PROCID/
    SLURM_NTASKS (srun).
  store endpoint: MASTER_ADDR[:MASTER_PORT] (torchrun exports these;
    for mpirun/srun export them yourself, e.g.
    `mpirun -x MASTER_ADDR=$(hostname) -x MASTER_PORT=29500 ...` —
    srun analog: `--export=ALL,MASTER_ADDR=...`). Default
    127.0.0.1:29400 suits single-host launches.

Under an MPI launch (OMPI_*/PMI_* present) with mpi4py importable, the
endpoint is instead gathered from rank 0 over the live communicator —
the exact mpi::Context bootstrap, no MASTER_ADDR needed. (This image
ships no MPI, so that branch lands gated and the env path is the
tested contract; the gate is the LAUNCHER environment, never mere
importability, so a torchrun job on a machine that happens to have
mpi4py installed never calls MPI_Init.)
"""

from __future__ import annotations

import os
import socket
from typing import Optional

from gloo_tpu.core import (Context, Device, PrefixStore, TcpStore,
                           TcpStoreServer)

_RANK_VARS = (
    ("RANK", "WORLD_SIZE"),
    ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
    ("PMI_RANK", "PMI_SIZE"),
    ("SLURM_PROCID", "SLURM_NTASKS"),
)

_DEFAULT_PORT = 29400


def detect_launch_env(env=None):
    """(rank, size) from the launcher's environment, or None when no
    recognized launcher variables are present."""
    env = os.environ if env is None else env
    for rank_var, size_var in _RANK_VARS:
        if rank_var in env and size_var in env:
            return int(env[rank_var]), int(env[size_var])
    return None


def _mpi_endpoint(env_rank: int, host: str, port: int):
    """Gather rank 0's store endpoint over the MPI communicator when
    mpi4py is present (the reference mpi::Context bootstrap). Allgather
    rather than bcast-from-root-0: the serving rank is ENV rank 0,
    which need not share the communicator's numbering (e.g. a stray
    RANK export alongside OMPI vars). Returns (host, port) or None
    without mpi4py.

    .. warning:: NEVER EXECUTED IN THIS REPO'S CI. The development and
       CI images ship no MPI runtime and no mpi4py, so the live-
       communicator branch below has never run; only the ImportError
       fallback (env-var endpoint exchange, tested with real processes
       in tests/test_bootstrap.py) is exercised. Treat this branch as
       reviewed-but-unproven when first deploying under a real
       mpirun/srun+PMI launch. Mirrors
       /root/reference/gloo/mpi/context.cc:88-140 behaviorally."""
    try:
        from mpi4py import MPI  # noqa: PLC0415 - optional dependency
    except ImportError:
        return None
    comm = MPI.COMM_WORLD
    vals = comm.allgather((host, port) if env_rank == 0 else None)
    return next((v for v in vals if v is not None), None)


def init_from_env(device: Optional[Device] = None, timeout: float = 30.0,
                  prefix: str = "tc-env", env=None):
    """Connect a full-mesh Context from launcher environment variables.

    Returns (context, store_server): store_server is the rank-0-owned
    TcpStoreServer (None elsewhere) — keep it referenced for the life
    of the job; later contexts can rendezvous through the same server
    with a fresh `prefix`. Raises RuntimeError outside a recognized
    launcher (no silent single-rank fallback: a rank that missed its
    launcher vars would otherwise split the job into broken islands).
    """
    env = os.environ if env is None else env
    detected = detect_launch_env(env)
    if detected is None:
        raise RuntimeError(
            "init_from_env: no launcher environment found (looked for "
            + ", ".join("/".join(v) for v in _RANK_VARS)
            + "); set RANK and WORLD_SIZE or use an explicit store")
    rank, size = detected
    host = env.get("MASTER_ADDR", "127.0.0.1")
    port = int(env.get("MASTER_PORT", _DEFAULT_PORT))

    server = None
    if rank == 0:
        # Serve on the advertised port; bind-all so any MASTER_ADDR
        # interface works.
        server = TcpStoreServer("0.0.0.0", port)
        port = server.port
    # Clients cannot dial "" / 0.0.0.0: normalize bind-all or loopback
    # MASTER_ADDR to something resolvable before anyone connects.
    dial_host = host if host not in ("", "0.0.0.0") else "127.0.0.1"

    # MPI-communicator endpoint exchange: gated on the LAUNCHER env so
    # non-MPI jobs never touch MPI_Init even with mpi4py installed.
    mpi_launch = "OMPI_COMM_WORLD_RANK" in env or "PMI_RANK" in env
    if mpi_launch:
        ep = _mpi_endpoint(rank, _advertised_host(dial_host), port)
        if ep is not None:
            dial_host, port = ep

    store = PrefixStore(TcpStore(dial_host, port), prefix)
    dev = device if device is not None else Device(
        hostname=_bind_host(env, dial_host))
    ctx = Context(rank, size, timeout=timeout)
    ctx.connect_full_mesh(store, dev)
    return ctx, server


def _advertised_host(host: str) -> str:
    """A peer-dialable address: pass real addresses through, replace
    loopback/bind-all with this host's resolvable address."""
    if host not in ("", "0.0.0.0", "127.0.0.1", "localhost"):
        return host
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _bind_host(env, dial_host: str) -> str:
    """The transport bind/advertise address for this rank: loopback for
    single-host launches (the default elsewhere in the package), the
    rank's routable hostname when the launch spans hosts. A non-local
    store endpoint — including one learned over MPI — is itself the
    multi-host signal, which covers MPICH/PMI launches that export no
    node-count variable."""
    if env.get("TPUCOLL_HOSTNAME"):
        return env["TPUCOLL_HOSTNAME"]
    multi = (dial_host not in ("127.0.0.1", "localhost")
             or int(env.get("SLURM_NNODES", "1")) > 1
             or int(env.get("OMPI_COMM_WORLD_LOCAL_SIZE",
                            env.get("OMPI_COMM_WORLD_SIZE", "1")))
             < int(env.get("OMPI_COMM_WORLD_SIZE", "1")))
    if not multi:
        return "127.0.0.1"
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"
