"""Collective schedule plane: algorithms as data.

The native core's collectives historically lived only as hand-written
C++ (ring / halving-doubling / bcube / ...). The schedule plane makes
the communication pattern itself a first-class, inspectable value: a
schedule is a rank-parameterized program of ``send`` / ``recv`` /
``recv_reduce`` / ``reduce_local`` / ``copy`` / ``encode`` / ``decode``
steps over chunk ids with explicit dependency edges
(csrc/tpucoll/schedule/ir.h). A static verifier proves a schedule
computes its declared collective (every chunk reduced exactly once,
delivered everywhere, deadlock-free); an interpreter lowers verified
schedules onto the existing transport through the plan cache, so warm
replays stay zero-allocation exactly like the native algorithms.

Generators (``generate()``) emit the known families — including shapes
the native core has no hardcoded implementation for, like the
chunked-pipelined ring (``ring`` with ``depth`` > 1) and the two-level
hierarchy (``hier`` with ``ranks_per_host``) — and ``sweep()`` measures
a parameter grid on the live fabric, electing the best schedule per
(collective, world, size-bucket) cell wherever one beats the native
algorithms.

Determinism contract
--------------------
Identical to the tuning table (gloo_tpu/tuning.py): every rank must
install byte-identical schedule JSON or groups disagree on the dispatch
and deadlock mid-collective. ``sweep()`` owns that contract (rank 0's
elections are broadcast and installed everywhere); ``install()`` is the
manual path and the caller owns it. Installation verifies and resolves
every schedule for the context's world size BEFORE swapping the plane —
a malformed or invalid table raises and leaves the previous plane (and
the plan cache) untouched.

Workflow
--------
>>> table = schedule.sweep(ctx)                 # all ranks, collectively
>>> if ctx.rank == 0:
...     schedule.save(table, "sched.json")
then in later jobs either ``TPUCOLL_SCHEDULE_FILE=sched.json`` (loaded
and installed at context connect) or::
>>> schedule.install(ctx, schedule.load("sched.json"))

``bench.py --schedule-sweep`` drives the sweep standalone; see
docs/schedules.md for the IR, the JSON format, and the election rules.
"""

from __future__ import annotations

import ctypes
import json
import time
from typing import Optional, Sequence, Union

import numpy as np

from gloo_tpu import _lib
from gloo_tpu._lib import check
from gloo_tpu.core import Context

__all__ = [
    "install",
    "installed",
    "clear",
    "list_schedules",
    "describe",
    "generate",
    "families",
    "verify",
    "merge",
    "sweep",
    "save",
    "load",
]

TableLike = Union[dict, str]


def _read_buf(out, out_len) -> str:
    try:
        return bytes(bytearray(out[: out_len.value])).decode()
    finally:
        _lib.lib.tc_buf_free(out)


def _copy_out(fn, *args) -> str:
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    check(fn(*args, ctypes.byref(out), ctypes.byref(out_len)))
    return _read_buf(out, out_len)


def _to_json_str(table: TableLike) -> str:
    if isinstance(table, str):
        return table
    return json.dumps(table)


def install(context: Context, table: TableLike) -> None:
    """Install a schedule table (dict or JSON string) on THIS rank.

    Every schedule matching the context's world size is statically
    verified and resolved before the swap; failures raise Error and
    leave the previously installed plane untouched. Installing clears
    the plan cache (schedules change what a cached plan would replay),
    exactly like tuning.install_table. The caller owns the every-rank-
    same-bytes contract.
    """
    check(_lib.lib.tc_schedule_install(
        context._handle, _to_json_str(table).encode()))


def installed(context: Context) -> Optional[dict]:
    """The installed schedule table as a dict, or None."""
    raw = _copy_out(_lib.lib.tc_schedule_json, context._handle)
    return json.loads(raw) if raw else None


def clear(context: Context) -> None:
    """Remove the installed plane; dispatch reverts to the native
    algorithms (and clears the plan cache)."""
    check(_lib.lib.tc_schedule_install(context._handle, None))


def list_schedules(context: Context) -> list:
    """Summaries of installed schedules:
    ``[{"name", "collective", "world_size", "steps", "resolved"}]``.
    ``resolved`` is 1 when the schedule matches this context's world
    (its elections can fire)."""
    return json.loads(_copy_out(_lib.lib.tc_schedule_list, context._handle))


def describe(context: Context, name: str) -> dict:
    """One installed schedule in full, as a single-schedule table dict
    (the same shape ``install`` accepts). Raises for unknown names."""
    return json.loads(_copy_out(
        _lib.lib.tc_schedule_describe, context._handle, name.encode()))


def generate(family: str, world_size: int,
             params: Optional[dict] = None) -> dict:
    """Generate + verify one schedule; returns a single-schedule table
    dict. Context-free. ``params`` is a dict of integer generator
    parameters (e.g. ``{"depth": 2}`` for the pipelined ring,
    ``{"ranks_per_host": 2}`` for the two-level hierarchy)."""
    raw = _copy_out(
        _lib.lib.tc_schedule_generate, family.encode(), world_size,
        json.dumps(params).encode() if params else None)
    return json.loads(raw)


def families() -> list:
    """Names of the built-in schedule generator families."""
    return json.loads(_copy_out(_lib.lib.tc_schedule_families))


def verify(table: TableLike) -> None:
    """Statically verify every schedule in a table (all ranks of each
    schedule's declared world). Context-free; raises Error with the
    verifier's typed, step-naming message on the first failure."""
    check(_lib.lib.tc_schedule_verify(_to_json_str(table).encode()))


def merge(*tables: TableLike) -> dict:
    """Union several tables into one (schedule names must not collide;
    later elections win their cells)."""
    out = {"version": 1, "schedules": [], "elections": []}
    seen = set()
    for t in tables:
        d = json.loads(_to_json_str(t))
        for s in d.get("schedules", []):
            if s["name"] in seen:
                raise ValueError(f"duplicate schedule name {s['name']!r}")
            seen.add(s["name"])
            out["schedules"].append(s)
        for e in d.get("elections", []):
            out["elections"] = [
                x for x in out["elections"]
                if (x["collective"], x["world_size"], x.get("dtype", ""),
                    x["bucket"]) != (e["collective"], e["world_size"],
                                     e.get("dtype", ""), e["bucket"])
            ]
            out["elections"].append(e)
    return out


def _default_candidates(world: int) -> list:
    """The default sweep grid: (family, params) pairs that generate for
    ``world``. Pipelined-ring depths scale the chunk pipeline; hier
    shapes try the divisors of the world size."""
    cands = [("ring", {"depth": 1}), ("ring", {"depth": 2}),
             ("ring", {"depth": 4}), ("hd", {}), ("bcube", {})]
    for rph in (2, 4):
        if world % rph == 0 and world // rph >= 2:
            cands.append(("hier", {"ranks_per_host": rph}))
    return cands


def _cand_name(family: str, params: dict, world: int) -> str:
    suffix = "".join(f"_{k[0]}{v}" for k, v in sorted(params.items()))
    return f"{family}{suffix}_p{world}"


def _time_allreduce(context: Context, nbytes: int, iters: int,
                    warmup: int, tag: int) -> float:
    """Median-of-iters wall time for one float32 sum allreduce."""
    arr = np.ones(nbytes // 4, dtype=np.float32)
    for _ in range(warmup):
        context.allreduce(arr, tag=tag)
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        context.allreduce(arr, tag=tag)
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples[len(samples) // 2]


def sweep(context: Context, min_bytes: int = 1 << 10,
          max_bytes: int = 1 << 20, iters: int = 8, warmup: int = 2,
          tag: int = 0,
          candidates: Optional[Sequence] = None) -> dict:
    """Measure the generator grid and elect winning schedules per cell.

    COLLECTIVE: every rank must call concurrently with identical
    arguments. For each log2 size bucket in [min_bytes, max_bytes] the
    sweep times the native kAuto dispatch (schedule plane cleared),
    then each candidate schedule (installed with a single election for
    that exact cell), all on float32 sum allreduce. Rank 0 elects the
    fastest candidate for every cell where it beats native, broadcasts
    the resulting table, and every rank installs those same bytes.

    Returns the installed table as a dict — empty elections mean native
    won everywhere. ``candidates`` overrides the default grid with
    (family, params) pairs.
    """
    world = context.size
    prior = installed(context)
    cands = list(candidates) if candidates is not None \
        else _default_candidates(world)
    # Generate + verify every candidate up front (identical on all
    # ranks: generators are deterministic).
    named = []  # (name, single-schedule table dict)
    for family, params in cands:
        t = generate(family, world, params)
        named.append((_cand_name(family, params, world), t))

    sizes = []
    nbytes = 1 << (min_bytes - 1).bit_length()  # round up to a pow2
    while nbytes <= max_bytes:
        sizes.append(nbytes)
        nbytes *= 2
    results = {}  # (name, nbytes) -> seconds; name None = native
    for size in sizes:
        clear(context)
        context.barrier(tag=tag)
        results[(None, size)] = _time_allreduce(
            context, size, iters, warmup, tag)
        bucket = size.bit_length() - 1
        for name, table in named:
            one = json.loads(json.dumps(table))
            one["schedules"][0]["name"] = name
            one["elections"] = [{
                "collective": "allreduce", "world_size": world,
                "dtype": "", "bucket": bucket, "schedule": name,
            }]
            install(context, one)
            context.barrier(tag=tag)
            results[(name, size)] = _time_allreduce(
                context, size, iters, warmup, tag)
    clear(context)

    # Rank 0 elects; everyone installs rank 0's bytes.
    if context.rank == 0:
        elected = {"version": 1, "schedules": [], "elections": []}
        used = set()
        for size in sizes:
            native = results[(None, size)]
            best, best_t = None, native
            for name, _ in named:
                if results[(name, size)] < best_t:
                    best, best_t = name, results[(name, size)]
            if best is not None:
                used.add(best)
                elected["elections"].append({
                    "collective": "allreduce", "world_size": world,
                    "dtype": "", "bucket": size.bit_length() - 1,
                    "schedule": best,
                })
        for name, table in named:
            if name in used:
                s = json.loads(json.dumps(table))["schedules"][0]
                s["name"] = name
                elected["schedules"].append(s)
        payload = json.dumps(elected).encode()
    else:
        payload = b""
    n = np.array([len(payload)], dtype=np.int64)
    context.broadcast(n, root=0, tag=tag)
    buf = np.zeros(int(n[0]), dtype=np.uint8)
    if context.rank == 0:
        buf[:] = np.frombuffer(payload, dtype=np.uint8)
    context.broadcast(buf, root=0, tag=tag)
    table = json.loads(buf.tobytes().decode())
    install(context, table)
    # The sweep intentionally discards any previously installed plane:
    # its elections were measured under different conditions. Callers
    # wanting to keep them can merge() with the prior table themselves.
    del prior
    return table


def save(table: TableLike, path: str) -> None:
    """Write a table to a JSON file (the TPUCOLL_SCHEDULE_FILE format)."""
    with open(path, "w") as f:
        f.write(_to_json_str(table))
        f.write("\n")


def load(path: str) -> dict:
    """Read a table written by save() / sweep()."""
    with open(path) as f:
        return json.load(f)
