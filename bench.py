"""Headline benchmark: allreduce algorithm bandwidth, host plane.

Config #1 from BASELINE.md: allreduce, float32, 64 MiB payload, 2 ranks,
host transport on localhost — the reference's own benchmark methodology
(p50 of timed iterations after warmup, verified first iteration). "Host"
because the transport routes bulk payloads over its same-host shm plane
with TCP as the control stream (docs/transport.md) — the same stack a
user gets from Device() with no configuration, measured against the
reference's own localhost TCP number.

vs_baseline compares against pytorch/gloo's `benchmark --transport tcp
allreduce_ring_chunked` at the same config: measured live when the
reference build exists at build-ref/ (run `cmake -S /root/reference -B
build-ref -G Ninja -DBUILD_BENCHMARK=ON -DUSE_REDIS=OFF && cmake --build
build-ref`), otherwise against the value recorded on this host
(0.620 GB/s, see BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"spread", "runs"} — value is the median of five full measurements taken
after one discarded warm-up run (run-to-run spread was ~9.4% at
median-of-3, BENCH_r05), spread is (max-min)/median of those runs (this
host's remaining noise floor next to the number), runs lists all five.

--channel-sweep measures allreduce algbw across the multi-channel
transport grid (TPUCOLL_LOOP_THREADS x TPUCOLL_CHANNELS x
TPUCOLL_STRIPE_BYTES), one JSON line per point, feeding the tuning
plane's transport hints; add --quick for a small smoke grid.

--wire-sweep measures allreduce algbw across the wire-codec family
(plain ring vs ring_bf16_wire vs ring_q8_wire vs ring_q4_wire) x
payload size under TPUCOLL_SHM=0 (the TCP plane, where wire bytes are
the bottleneck the codecs exist to cut), one JSON line per
(algorithm, size) point — the crossover data the tuner's lossy arms
and future rounds consume. It also runs the pipelined-engine A/B
(serial depth-1 hop vs depth-4 + codec pool, interleaved passes), the
TPUCOLL_CODEC_THREADS width axis, and a profiled 64 MiB phase
breakdown quantifying the op-thread pack+unpack cut; add --quick for
a small smoke grid.
"""

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

ELEMENTS = 16 * 1024 * 1024  # 64 MiB float32
WARMUP = 3
ITERS = 15
RECORDED_REFERENCE_GBPS = 0.620

# --pin: sched_setaffinity each rank's thread/process (and so, by
# inheritance, its loop and lane threads) to core (rank % cpu_count),
# cutting scheduler-migration noise out of the ~9% headline spread on
# this 2-core host. Recorded in every JSON line it affects.
PIN_RANKS = False


def _maybe_pin(rank):
    if not PIN_RANKS:
        return
    ncpu = os.cpu_count() or 1
    # pid 0 = the calling thread on Linux; threads spawned afterwards
    # (event loops, async lanes) inherit the mask.
    os.sched_setaffinity(0, {rank % ncpu})


def bench_ours(metrics_out=None):
    import numpy as np

    import gloo_tpu

    store = gloo_tpu.HashStore()
    samples = [None, None]

    def worker(rank):
        _maybe_pin(rank)
        device = gloo_tpu.Device()
        ctx = gloo_tpu.Context(rank, 2, timeout=120)
        ctx.connect_full_mesh(store, device)
        x = np.full(ELEMENTS, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        assert x[0] == 3.0, "allreduce verification failed"
        x[:] = 1.0
        for _ in range(WARMUP):
            ctx.allreduce(x)
        if metrics_out is not None and rank == 0:
            ctx.metrics(drain=True)  # measure the timed loop only
        times = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            ctx.allreduce(x)
            times.append(time.perf_counter() - t0)
        samples[rank] = times
        if metrics_out is not None and rank == 0:
            metrics_out.append(ctx.metrics())
        ctx.barrier()
        ctx.close()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    import numpy as np

    p50 = float(np.median(samples[0]))
    p99 = float(np.percentile(samples[0], 99))
    algbw = ELEMENTS * 4 / p50 / 1e9
    print(f"[bench] ours: p50 {p50 * 1e6:.0f}us p99 {p99 * 1e6:.0f}us "
          f"algbw {algbw:.3f} GB/s", file=sys.stderr)
    return algbw


def bench_reference():
    """Run the reference gloo benchmark at the identical config, if built."""
    binary = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "build-ref", "gloo", "benchmark", "benchmark")
    if not os.path.exists(binary):
        return None
    store = tempfile.mkdtemp()
    procs = []
    for rank in range(2):
        procs.append(subprocess.Popen(
            [binary, "--size", "2", "--rank", str(rank),
             "--shared-path", store, "--transport", "tcp",
             "--elements", str(ELEMENTS), "--iteration-time", "2s",
             "--no-verify", "allreduce_ring_chunked"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True))
    outs = [p.communicate(timeout=300)[0] for p in procs]
    for out in outs:
        m = re.search(r"^\s*\d+\s+\d+\s+\d+\s+(\d+)\s+\d+\s+\d+\s+"
                      r"([\d.]+)\s+\d+\s*$", out, re.M)
        if m:
            gbps = float(m.group(2))
            p50_us = int(m.group(1))
            print(f"[bench] reference gloo: p50 {p50_us}us algbw "
                  f"{gbps:.3f} GB/s", file=sys.stderr)
            return gbps
    return None


def bench_autotune(quick=False, out_path=None):
    """--autotune: run the tuner sweep on a 2-rank group, persist the
    elected table, and measure what the table buys: for every swept
    allreduce size, p50 with the tuned table installed vs the default
    (untuned) kAuto thresholds vs each fixed arm (ring, halving-
    doubling). Prints ONE JSON line:

      {"metric": "allreduce_autotune_2rank_host",
       "value": <geomean over sizes of default_us / tuned_us>,
       "unit": "x_speedup_vs_default_auto",
       "ranks_agree": <all ranks installed byte-identical tables>,
       "table": <path the table was saved to>,
       "cells": [{"bytes", "tuned_us", "default_us", "ring_us", "hd_us",
                  "tuned_vs_best_fixed"}, ...]}

    tuned_vs_best_fixed is the acceptance signal: ~<= 1 plus noise means
    the tuned dispatch never loses to the better fixed arm at any swept
    size (the hardcoded threshold CAN lose — that is the point).
    """
    import math

    import numpy as np

    import gloo_tpu
    from gloo_tpu import tuning

    if out_path is None:
        out_path = "/tmp/tuning_table.json"
    # Quick mode (CI smoke): tiny sizes, few iterations.
    min_bytes = 4 << 10
    max_bytes = (64 << 10) if quick else (4 << 20)
    tune_iters, tune_warmup = (3, 1) if quick else (8, 2)
    time_iters = 10 if quick else 30

    store = gloo_tpu.HashStore()
    rank_tables = [None, None]
    cells_out = [None]

    def time_allreduce(ctx, x, iters, **kw):
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ctx.allreduce(x, **kw)
            times.append(time.perf_counter() - t0)
        return float(np.median(times)) * 1e6

    def worker(rank):
        device = gloo_tpu.Device()
        ctx = gloo_tpu.Context(rank, 2, timeout=120)
        ctx.connect_full_mesh(store, device)
        table = tuning.tune(ctx, min_bytes=min_bytes, max_bytes=max_bytes,
                            iters=tune_iters, warmup=tune_warmup)
        rank_tables[rank] = json.dumps(table, sort_keys=True)
        if rank == 0:
            tuning.save_table(table, out_path)

        # Measured-vs-default sweep. Both ranks run the identical
        # sequence (install/clear are dispatch-relevant state and must
        # flip at the same sequence points on every rank); rank 0's
        # timings are reported.
        cells = []
        nbytes = min_bytes
        while nbytes <= max_bytes:
            x = np.zeros(nbytes // 4, dtype=np.float32)
            tuned = time_allreduce(ctx, x, time_iters)  # table installed
            ring = time_allreduce(ctx, x, time_iters, algorithm="ring")
            hd = time_allreduce(ctx, x, time_iters,
                                algorithm="halving_doubling")
            tuning.clear_table(ctx)
            default = time_allreduce(ctx, x, time_iters)  # stock kAuto
            tuning.install_table(ctx, table)
            cells.append({
                "bytes": nbytes,
                "tuned_us": round(tuned, 1),
                "default_us": round(default, 1),
                "ring_us": round(ring, 1),
                "hd_us": round(hd, 1),
                "tuned_vs_best_fixed": round(tuned / min(ring, hd), 3),
            })
            nbytes *= 2
        if rank == 0:
            cells_out[0] = cells
        ctx.barrier()
        ctx.close()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(1200)
    assert all(t is not None for t in rank_tables), "a rank failed to tune"
    cells = cells_out[0]
    assert cells, "no measurement cells"
    speedup = math.exp(
        sum(math.log(c["default_us"] / c["tuned_us"]) for c in cells)
        / len(cells))
    for c in cells:
        print(f"[autotune] {c['bytes'] >> 10}KiB tuned {c['tuned_us']:.0f}us"
              f" default {c['default_us']:.0f}us ring {c['ring_us']:.0f}us"
              f" hd {c['hd_us']:.0f}us", file=sys.stderr)
    line = {
        "metric": "allreduce_autotune_2rank_host",
        "value": round(speedup, 3),
        "unit": "x_speedup_vs_default_auto",
        "ranks_agree": rank_tables[0] == rank_tables[1],
        "table": out_path,
        "cells": cells,
    }
    print(json.dumps(line))


def bench_schedule_sweep(quick=False, out_path=None):
    """--schedule-sweep [--quick]: sweep the schedule generator grid
    against the native arms on a 4-rank group (docs/schedules.md).

    For every swept allreduce size: p50 of the native kAuto dispatch
    (schedule plane cleared) and of the fixed native ring and hd arms,
    then each generated candidate schedule installed with a single
    election for exactly that (collective, world, bucket) cell — the
    grid includes the two families the native enum cannot express (the
    chunked-pipelined ring, depth 2/4, and the 2-level hierarchy).
    Elects the fastest candidate wherever it beats the BEST native arm,
    saves the elected table (the TPUCOLL_SCHEDULE_FILE format), and
    prints ONE JSON line:

      {"metric": "allreduce_schedule_sweep_4rank_host",
       "value": <cells where a generated schedule beat best-native>,
       "unit": "cells_won", "ranks_agree": ..., "table": <path>,
       "cells": [{"bytes", "native_auto_us", "native_ring_us",
                  "native_hd_us", "arms": {name: us}, "winner",
                  "winner_vs_best_native"}, ...]}

    SCHED_r17.json in the repo root is a committed full run: the
    acceptance evidence that schedule search finds real wins (a
    pipelined ring or hierarchy cell under 1.0).
    """
    import numpy as np

    import gloo_tpu
    from gloo_tpu import schedule

    if out_path is None:
        out_path = "/tmp/schedule_table.json"
    world = 4
    min_bytes = (16 << 10) if quick else (64 << 10)
    max_bytes = (64 << 10) if quick else (4 << 20)
    iters, warmup = (6, 1) if quick else (20, 3)
    candidates = [("ring", {"depth": 1}), ("ring", {"depth": 2}),
                  ("ring", {"depth": 4}), ("hd", {}), ("bcube", {}),
                  ("hier", {"ranks_per_host": 2})]
    # The generated-only families: the acceptance signal counts wins
    # from shapes the native enum cannot dispatch.
    generated_only = {"ring_p4_k2", "ring_p4_k4", "hier_p4_h2"}

    store = gloo_tpu.HashStore()
    rank_tables = [None] * world
    cells_out = [None]

    def time_allreduce(ctx, x, **kw):
        for _ in range(warmup):
            ctx.allreduce(x, **kw)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ctx.allreduce(x, **kw)
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2] * 1e6

    def worker(rank):
        _maybe_pin(rank)
        device = gloo_tpu.Device()
        ctx = gloo_tpu.Context(rank, world, timeout=120)
        ctx.connect_full_mesh(store, device)
        named = []
        for family, params in candidates:
            t = schedule.generate(family, world, params)
            named.append((t["schedules"][0]["name"], t))

        # Every rank runs the identical install/clear sequence (the
        # plane is dispatch-relevant state and must flip at the same
        # sequence points everywhere); rank 0's timings are reported.
        cells = []
        nbytes = min_bytes
        while nbytes <= max_bytes:
            x = np.zeros(nbytes // 4, dtype=np.float32)
            schedule.clear(ctx)
            ctx.barrier()
            native_auto = time_allreduce(ctx, x)
            native_ring = time_allreduce(ctx, x, algorithm="ring")
            native_hd = time_allreduce(ctx, x,
                                       algorithm="halving_doubling")
            arms = {}
            for name, table in named:
                one = json.loads(json.dumps(table))
                one["elections"] = [{
                    "collective": "allreduce", "world_size": world,
                    "dtype": "",
                    "bucket": nbytes.bit_length() - 1,
                    "schedule": name,
                }]
                schedule.install(ctx, one)
                ctx.barrier()
                arms[name] = time_allreduce(ctx, x)
            best_native = min(native_auto, native_ring, native_hd)
            winner = min(arms, key=arms.get)
            cells.append({
                "bytes": nbytes,
                "native_auto_us": round(native_auto, 1),
                "native_ring_us": round(native_ring, 1),
                "native_hd_us": round(native_hd, 1),
                "arms": {k: round(v, 1) for k, v in arms.items()},
                "winner": winner,
                "winner_vs_best_native": round(arms[winner] / best_native,
                                               3),
            })
            nbytes *= 2
        schedule.clear(ctx)

        # Rank 0's timings decide (each rank measured its own clock);
        # its elected table is broadcast so every rank reports the same
        # bytes — the same agreement protocol schedule.sweep() uses.
        if rank == 0:
            elected = {"version": 1, "schedules": [], "elections": []}
            used = set()
            for c in cells:
                best_native = min(c["native_auto_us"],
                                  c["native_ring_us"], c["native_hd_us"])
                if c["arms"][c["winner"]] < best_native:
                    used.add(c["winner"])
                    elected["elections"].append({
                        "collective": "allreduce", "world_size": world,
                        "dtype": "",
                        "bucket": c["bytes"].bit_length() - 1,
                        "schedule": c["winner"],
                    })
            for name, table in named:
                if name in used:
                    elected["schedules"].append(
                        json.loads(json.dumps(table))["schedules"][0])
            payload = json.dumps(elected, sort_keys=True).encode()
            cells_out[0] = cells
        else:
            payload = b""
        n = np.array([len(payload)], dtype=np.int64)
        ctx.broadcast(n, root=0)
        buf = np.zeros(int(n[0]), dtype=np.uint8)
        if rank == 0:
            buf[:] = np.frombuffer(payload, dtype=np.uint8)
        ctx.broadcast(buf, root=0)
        rank_tables[rank] = buf.tobytes().decode()
        if rank == 0:
            schedule.verify(rank_tables[rank])
            schedule.save(rank_tables[rank], out_path)
        ctx.barrier()
        ctx.close()

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(1800)
    assert all(t is not None for t in rank_tables), "a rank failed"
    cells = cells_out[0]
    assert cells, "no measurement cells"
    for c in cells:
        print(f"[sched] {c['bytes'] >> 10}KiB native "
              f"{c['native_auto_us']:.0f}us winner {c['winner']} "
              f"{c['arms'][c['winner']]:.0f}us "
              f"(x{c['winner_vs_best_native']})", file=sys.stderr)
    generated_wins = sum(
        1 for c in cells
        if c["winner"] in generated_only and c["winner_vs_best_native"] < 1)
    line = {
        "metric": "allreduce_schedule_sweep_4rank_host",
        "value": generated_wins,
        "unit": "cells_won",
        "ranks_agree": len(set(rank_tables)) == 1,
        "table": out_path,
        "cells": cells,
    }
    print(json.dumps(line))


def bench_latency(quick=False):
    """Small-message latency A/B: persistent collective plans on vs off.

    The plan cache's headline is LATENCY, not bandwidth: per-op setup
    (UnboundBuffer create+destroy, scratch acquisition, schedule
    recompute) is a fixed cost that dominates small messages. This sweep
    measures allreduce and reduce_scatter p50/p99 at 64 B..256 KiB under
    TPUCOLL_SHM=0 (pure TCP loopback, the acceptance configuration),
    with the two arms interleaved in time per size (A/B/A/B passes) so
    host drift hits both equally. One JSON line per (op, size, arm).

    Arms differ ONLY by TPUCOLL_PLAN_CACHE at context construction: the
    off-arm context runs the transient path (pre-plan behavior), the
    on-arm replays cached plans. The on-arm line also records the
    steady-state ubuf_creates delta across the timed loop — the
    zero-registration proof.
    """
    import numpy as np

    import gloo_tpu

    os.environ["TPUCOLL_SHM"] = "0"
    sizes = [64, 256, 1024, 4096, 16384, 65536, 262144]
    if quick:
        sizes = [64, 1024, 16384, 65536]
    warmup = 10 if quick else 30
    passes = 2 if quick else 4
    iters = 30 if quick else 100

    store_on = gloo_tpu.HashStore()
    store_off = gloo_tpu.HashStore()
    gate = threading.Barrier(2)
    results = []
    lock = threading.Lock()

    def worker(rank):
        _maybe_pin(rank)
        # Coordinated construction: TPUCOLL_PLAN_CACHE is read at
        # Context creation, and the env is process-global, so both
        # ranks build each arm's context under the same setting.
        gate.wait()
        if rank == 0:
            os.environ["TPUCOLL_PLAN_CACHE"] = "0"
        gate.wait()
        dev = gloo_tpu.Device()
        ctx_off = gloo_tpu.Context(rank, 2, timeout=120)
        ctx_off.connect_full_mesh(store_off, dev)
        gate.wait()
        if rank == 0:
            os.environ["TPUCOLL_PLAN_CACHE"] = "1"
        gate.wait()
        ctx_on = gloo_tpu.Context(rank, 2, timeout=120)
        ctx_on.connect_full_mesh(store_on, dev)

        for nbytes in sizes:
            count = max(1, nbytes // 4)
            for op in ("allreduce", "reduce_scatter"):
                # Stable buffers per (size, arm): the plan cache keys on
                # the pointer, and a training loop's buffers are stable —
                # this measures that steady state.
                # On-arm: the full persistent path — a CollectivePlan
                # handle (one foreign call per step, marshalled once)
                # over the warm native plan. Off-arm: the pre-plan
                # per-call path (classic API, cache disabled).
                x_on = np.full(count, float(rank + 1), dtype=np.float32)
                out_on = np.empty(count // 2, dtype=np.float32)
                if op == "allreduce":
                    plan = ctx_on.allreduce_plan(x_on, tag=7)
                else:
                    # count is a multiple of 2 at every swept size
                    # (>= 16 f32 elements), so the default even split
                    # applies.
                    plan = ctx_on.reduce_scatter_plan(x_on, tag=9,
                                                      output=out_on)
                x_off = np.full(count, float(rank + 1), dtype=np.float32)
                out_off = np.empty(count // 2, dtype=np.float32)
                cells = {"on": [], "off": []}
                ub_delta = {}

                def run_op(ctx, arm, n, record):
                    for _ in range(n):
                        t0 = time.perf_counter()
                        if arm == "on":
                            plan()
                        elif op == "allreduce":
                            ctx.allreduce(x_off, tag=7)
                        else:
                            ctx.reduce_scatter(x_off, tag=9,
                                               output=out_off)
                        if record is not None:
                            record.append(time.perf_counter() - t0)

                # Warm both arms (plan build happens here, outside the
                # timed loops), then interleave A/B passes.
                run_op(ctx_on, "on", warmup, None)
                run_op(ctx_off, "off", warmup, None)
                ub0 = ctx_on.metrics()["ubuf_creates"]
                for _ in range(passes):
                    run_op(ctx_on, "on", iters, cells["on"])
                    run_op(ctx_off, "off", iters, cells["off"])
                ub_delta["on"] = ctx_on.metrics()["ubuf_creates"] - ub0
                if rank == 0:
                    snap = ctx_on.metrics()
                    for arm in ("on", "off"):
                        times = cells[arm]
                        line = {
                            "bench": "latency",
                            "op": op,
                            "bytes": nbytes,
                            "plans": arm == "on",
                            "iters": len(times),
                            "p50_us": round(
                                float(np.median(times)) * 1e6, 2),
                            "p99_us": round(
                                float(np.percentile(times, 99)) * 1e6, 2),
                            "pinned": PIN_RANKS,
                        }
                        if arm == "on":
                            line["ubuf_creates_steady_delta"] = int(
                                ub_delta["on"])
                            line["plan_hits"] = snap["plan_hits"]
                            line["plan_misses"] = snap["plan_misses"]
                        with lock:
                            results.append(line)
        ctx_on.barrier(tag=99)
        ctx_off.barrier(tag=99)
        ctx_on.close()
        ctx_off.close()

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(1200)

    for line in results:
        print(json.dumps(line))
    # Summary: geomean p50 speedup (plans on vs off) over the <= 64 KiB
    # cells — the acceptance criterion's number.
    import math
    ratios = []
    by_key = {(l["op"], l["bytes"], l["plans"]): l for l in results}
    for (op_name, nbytes, plans), l in by_key.items():
        if plans or nbytes > 65536:
            continue
        on = by_key.get((op_name, nbytes, True))
        if on and on["p50_us"] > 0:
            ratios.append(l["p50_us"] / on["p50_us"])
    summary = {
        "bench": "latency_summary",
        "cells": len(results),
        "geomean_p50_speedup_le_64KiB": round(
            math.exp(sum(math.log(r) for r in ratios) / len(ratios)), 3)
        if ratios else None,
        "pinned": PIN_RANKS,
    }
    print(json.dumps(summary))
    return results + [summary]


def bench_elastic_soak(seconds, quick=False):
    """--elastic-soak N [--quick]: soak the elastic membership plane
    (docs/elastic.md) for ~N seconds: three workers run a VERIFIED
    mixed workload (allreduce at three sizes + allgather, every result
    checked against its closed form for the CURRENT size) under
    run_elastic while this driver periodically SIGKILLs a live worker
    and respawns a replacement with join=True. No worker ever calls a
    rebuild — every transition is lease-detected, epoch-agreed, and
    auto-recovered. Prints ONE JSON line:

      {"metric": "elastic_soak_3rank_host", "value": <epochs reached>,
       "unit": "epochs", "seconds": N, "kills": k, "rejoins": k,
       "steps": <verified steps across final workers>,
       "rebuild_ms_p50": ..., "rebuild_ms_p99": ...,
       "lease_ms": 200, "lease_grace_ms": 1200, "ok": true}

    rebuild latency = EpochChanged caught -> successor mesh bound, per
    transition per worker (the detect half is bounded separately by the
    lease grace). --quick: one kill/rejoin cycle sized for CI smoke.
    """
    repo = os.path.dirname(os.path.abspath(__file__))
    store_dir = tempfile.mkdtemp()
    world = 3
    env = dict(os.environ, TPUCOLL_LEASE_MS="200",
               TPUCOLL_LEASE_GRACE="1200")

    body = textwrap.dedent("""
        import json, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu
        from gloo_tpu import elastic

        rank = int(sys.argv[1])
        join = sys.argv[2] == "join"
        store = gloo_tpu.FileStore({store!r})
        SIZES = (1 << 12, 1 << 14, 1 << 16)

        def step_fn(ectx, step, state):
            # flag[1] carries this rank's step counter so the size
            # index below comes from the allreduced (group-agreed) sum:
            # a joiner enters with a fresh i=0 while survivors are at
            # i=k, and rank-local SIZES[i % 3] would post mismatched
            # allreduce lengths that wedge the mesh.
            flag = np.zeros(2, dtype=np.float32)
            flag[1] = float(state["i"] % 3)
            if ectx.rank == 0:
                try:
                    store.get("soak_stop", timeout=0.001)
                    flag[0] = 1.0
                except gloo_tpu.Error:
                    pass
            ectx.allreduce(flag, tag=0)
            if flag[0] > 0:
                raise StopIteration
            n = ectx.size
            x = np.full(SIZES[int(flag[1]) % 3], float(ectx.rank + 1),
                        dtype=np.float32)
            ectx.allreduce(x, tag=1)
            assert x[0] == n * (n + 1) / 2, (state["i"], x[0], n)
            g = np.full(256, float(ectx.rank), dtype=np.float32)
            out = ectx.allgather(g, tag=2)
            assert [int(out[r][0]) for r in range(n)] == list(range(n))
            state["i"] += 1
            return state

        res = elastic.run_elastic(
            step_fn, store=store, device=gloo_tpu.Device(), rank=rank,
            world_size={world}, min_size=2, join=join,
            state={{"i": 0}}, timeout=120.0)
        res.pop("state")
        print("OK", json.dumps(res))
    """).format(repo=repo, store=store_dir, world=world)

    def spawn(rank, join=False):
        return subprocess.Popen(
            [sys.executable, "-c", body, str(rank),
             "join" if join else "found"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)

    procs = [spawn(r) for r in range(world)]
    kills = 1 if quick else max(1, int(seconds // 10))
    period = max(5.0, seconds / (kills + 1))
    deadline = time.monotonic() + seconds
    done_kills = 0
    rng = __import__("random").Random(14)
    try:
        while time.monotonic() < deadline and done_kills < kills:
            time.sleep(min(period, max(0.0, deadline - time.monotonic())))
            live = [p for p in procs if p.poll() is None]
            if done_kills >= kills or len(live) < world:
                continue
            victim = rng.choice(live)
            victim.send_signal(signal.SIGKILL)
            victim.wait(timeout=30)
            done_kills += 1
            time.sleep(1.0)
            procs.append(spawn(100 + done_kills, join=True))
            print(f"[elastic-soak] kill #{done_kills} -> respawned joiner",
                  file=sys.stderr)
        while time.monotonic() < deadline:
            time.sleep(0.25)
    finally:
        # Consensus stop: the current rank 0 folds the key into the
        # next step's flag allreduce, so every worker exits at the
        # same step boundary.
        import gloo_tpu

        gloo_tpu.FileStore(store_dir).set("soak_stop", b"1")

    summaries = []
    ok = True
    for p in procs:
        try:
            out, err = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            ok = False
            print(f"[elastic-soak] worker hung: {err[-400:]!r}",
                  file=sys.stderr)
            continue
        if p.returncode == -signal.SIGKILL:
            continue  # a driver-killed victim
        if p.returncode != 0:
            ok = False
            print(f"[elastic-soak] worker rc={p.returncode}: "
                  f"{err[-400:]!r}", file=sys.stderr)
            continue
        line = [ln for ln in out.splitlines() if ln.startswith("OK ")]
        if not line:
            ok = False
            continue
        summaries.append(json.loads(line[0][3:]))

    ok = ok and len(summaries) == world  # full size at the end
    rebuild_ms = sorted(ms for s in summaries for ms in s["rebuild_ms"])
    epochs = max((e["epoch"] for s in summaries for e in s["epochs"]),
                 default=0)
    sizes_ok = all(e["size"] >= 2 for s in summaries
                   for e in s["epochs"])

    def pct(q):
        if not rebuild_ms:
            return None
        return rebuild_ms[min(len(rebuild_ms) - 1,
                              int(q * (len(rebuild_ms) - 1) + 0.5))]

    line = {
        "metric": "elastic_soak_3rank_host",
        "value": epochs,
        "unit": "epochs",
        "seconds": seconds,
        "kills": done_kills,
        "rejoins": done_kills,
        "steps": sum(s["steps"] for s in summaries),
        "rebuilds": sum(s["rebuilds"] for s in summaries),
        "rebuild_ms_p50": pct(0.50),
        "rebuild_ms_p99": pct(0.99),
        "lease_ms": 200,
        "lease_grace_ms": 1200,
        "ok": bool(ok and sizes_ok and epochs >= 1 + 2 * done_kills),
    }
    print(json.dumps(line))
    if not line["ok"]:
        sys.exit(1)


def bench_chaos_soak(seconds):
    """--chaos-soak N: run a mixed collective/p2p workload for N seconds
    with a low-rate delay/dup fault schedule installed (the soak-mode
    face of the fault plane, docs/faults.md), verifying every result
    against its closed form. Prints ONE JSON line:

      {"metric": "chaos_soak_2rank_host", "value": <ops completed>,
       "unit": "ops", "seconds": N, "faults": <faults injected>,
       "faults_by_action": {...}, "ok": true}

    A wrong value or a hang is a failure; the point is that a transport
    under continuous low-rate fault pressure stays correct, not fast.
    """
    import numpy as np

    import gloo_tpu
    from gloo_tpu import fault

    fault.install({"seed": 0xC405, "faults": [
        {"when": {"opcode": "data", "min_bytes": 1},
         "action": "delay", "ms": 1, "prob": 0.02},
        {"when": {"opcode": "data", "min_bytes": 1},
         "action": "dup", "prob": 0.01},
    ]})
    store = gloo_tpu.HashStore()
    ops_out = [0]
    errors = []
    deadline = time.monotonic() + seconds

    def guarded(rank):
        try:
            worker(rank)
        except BaseException as exc:  # noqa: BLE001 — soak must report it
            errors.append((rank, repr(exc)))

    def worker(rank):
        import numpy as np

        device = gloo_tpu.Device()
        ctx = gloo_tpu.Context(rank, 2, timeout=60)
        ctx.connect_full_mesh(store, device)
        ops = 0
        i = 0
        while True:
            # Rank 0 owns the clock; the decision rides an allreduce so
            # both ranks always agree on the iteration count. Tags and
            # slots are unique per iteration — the dup-tolerance rule
            # (docs/faults.md) — so a stale duplicate can never match a
            # later operation.
            flag = np.array(
                [1.0 if rank != 0 or time.monotonic() < deadline
                 else 0.0], dtype=np.float32)
            ctx.allreduce(flag, op="min", tag=4 * i)
            if flag[0] < 1.0:
                break
            n = 256 + (i * 97) % 4096
            x = np.full(n, float(rank + 1 + i), dtype=np.float32)
            ctx.allreduce(x, tag=4 * i + 1)
            assert x[0] == 2 * i + 3, (i, x[0])
            g = ctx.allgather(np.full(64, float(rank + i), np.float64),
                              tag=4 * i + 2)
            assert g[0][0] == float(i) and g[1][0] == float(1 + i), g
            y = np.arange(n, dtype=np.float64) * (rank + 1)
            out = np.zeros(n, dtype=np.float64)
            ctx.send(y, dst=1 - rank, slot=10_000 + 2 * i + rank)
            ctx.recv(out, src=1 - rank, slot=10_000 + 2 * i + (1 - rank))
            assert out[1] == float(2 - rank), (i, out[1])
            ops += 4
            i += 1
        ctx.barrier(tag=1)
        if rank == 0:
            ops_out[0] = ops
        ctx.close()

    # Daemon threads: the "soak hung" branch must actually exit 1 —
    # interpreter shutdown would otherwise block forever joining the
    # still-alive worker.
    threads = [threading.Thread(target=guarded, args=(r,), daemon=True)
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(max(seconds * 10, 120))
        if t.is_alive():
            print(json.dumps({"metric": "chaos_soak_2rank_host",
                              "ok": False, "error": "soak hung"}))
            sys.exit(1)
    if errors:
        # A wrong value under fault pressure is the bug this soak
        # exists to catch — it must never report ok.
        print(json.dumps({"metric": "chaos_soak_2rank_host",
                          "ok": False,
                          "error": [f"rank {r}: {e}" for r, e in errors]}))
        sys.exit(1)
    fired = fault.report()
    fault.clear()
    by_action = {}
    for e in fired:
        by_action[e["action"]] = by_action.get(e["action"], 0) + 1
    print(json.dumps({
        "metric": "chaos_soak_2rank_host",
        "value": ops_out[0],
        "unit": "ops",
        "seconds": seconds,
        "faults": len(fired),
        "faults_by_action": by_action,
        "ok": True,
    }))


def bench_flightrec_soak(seconds):
    """--flightrec N: the post-mortem soak. Three real processes run a
    mixed collective workload for N seconds with the always-on flight
    recorder pointed at a dump directory; then one rank is SIGKILLed
    mid-collective. The survivors' transport-failure auto-dumps plus the
    victim's ABSENT dump must merge into a verdict that blames the dead
    rank. Prints ONE JSON line:

      {"metric": "flightrec_soak_3rank_host", "value": <ops recorded>,
       "unit": "ops", "seconds": N, "blamed_ranks": [2],
       "verdict": "stall", "dumps": 2, "ok": true}

    A wrong blame (or no dumps) is a failure — the chain under test is
    chaos -> recorder -> merge -> blame, end to end.
    """
    import signal as _signal
    import textwrap

    from gloo_tpu.utils import flightrec

    store = tempfile.mkdtemp()
    fr_dir = os.path.join(store, "flightrec")
    victim = 2
    body = textwrap.dedent("""
        import os, signal, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1]); size = 3
        deadline = time.monotonic() + {seconds}
        ctx = gloo_tpu.Context(rank, size, timeout=30.0)
        ctx.connect_full_mesh(gloo_tpu.FileStore({store!r}),
                              gloo_tpu.Device())
        i = 0
        try:
            while True:
                flag = np.array(
                    [1.0 if rank != 0 or time.monotonic() < deadline
                     else 0.0], dtype=np.float32)
                ctx.allreduce(flag, op="min", tag=3 * i)
                if flag[0] < 1.0:
                    break
                n = 256 + (i * 131) % 2048
                x = np.full(n, float(rank + 1), dtype=np.float32)
                ctx.allreduce(x, tag=3 * i + 1)
                assert x[0] == 6.0, (i, x[0])
                ctx.barrier(tag=3 * i + 2)
                i += 1
            # Soak done: the victim dies INSIDE the next collective so
            # survivors observe a mid-op link death, not a goodbye.
            y = np.full(1 << 16, float(rank + 1), dtype=np.float32)
            if rank == {victim}:
                os.kill(os.getpid(), signal.SIGKILL)
            ctx.allreduce(y, tag=1000000, timeout=5.0)
            print("UNEXPECTED-SUCCESS"); sys.exit(3)
        except gloo_tpu.IoError:
            pass
        print("SOAK-OK", ctx.flightrec_seq())
    """).format(repo=os.path.dirname(os.path.abspath(__file__)),
                seconds=seconds, store=store, victim=victim)
    env = dict(os.environ, TPUCOLL_FLIGHTREC_DIR=fr_dir)
    procs = [subprocess.Popen([sys.executable, "-c", body, str(r)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for r in range(3)]
    outs = [p.communicate(timeout=max(seconds * 10, 120)) for p in procs]

    ok = True
    errors = []
    ops = 0
    if procs[victim].returncode != -_signal.SIGKILL:
        ok = False
        errors.append(f"victim exited {procs[victim].returncode}, "
                      f"expected SIGKILL")
    for r in (0, 1):
        if procs[r].returncode != 0 or "SOAK-OK" not in outs[r][0]:
            ok = False
            errors.append(f"rank {r}: rc={procs[r].returncode} "
                          f"out={outs[r][0][-200:]!r} "
                          f"err={outs[r][1][-200:]!r}")
        else:
            ops = max(ops, int(outs[r][0].split("SOAK-OK", 1)[1]))

    merged = flightrec.merge(fr_dir)
    verdict = flightrec.analyze(merged)
    if verdict["blamed_ranks"] != [victim]:
        ok = False
        errors.append(f"blame miss: {verdict}")
    line = {
        "metric": "flightrec_soak_3rank_host",
        "value": ops,
        "unit": "ops",
        "seconds": seconds,
        "blamed_ranks": verdict["blamed_ranks"],
        "verdict": verdict["kind"],
        "dumps": len(merged["ranks"]),
        "ok": ok,
    }
    if errors:
        line["error"] = errors
    print(json.dumps(line))
    if not ok:
        sys.exit(1)


def bench_channel_sweep(quick=False):
    """--channel-sweep: measure 2-rank allreduce algbw across the
    multi-channel transport grid (loop threads x data channels x stripe
    threshold), one JSON line per point — the measurement source for the
    tuning plane's transport hints (tuning.set_transport_hints). Each
    point runs in fresh subprocesses because the knobs are env-resolved
    at context construction; TPUCOLL_SHM=0 pins the payloads to the TCP
    plane the knobs actually govern (same-host shm bypasses striping).
    """
    import tempfile
    import textwrap

    if quick:
        elements = 1 << 22  # 16 MiB f32
        iters, warmup = 4, 1
        grid = [(1, 1, 1 << 20), (2, 2, 1 << 20)]
    else:
        elements = ELEMENTS  # the headline 64 MiB config
        iters, warmup = 8, 2
        grid = [(loops, ch, stripe)
                for loops in (1, 2, 4)
                for ch in (1, 2, 4)
                for stripe in (256 << 10, 1 << 20, 4 << 20)
                # stripe threshold is meaningless without channels;
                # keep exactly one single-channel baseline per loop count
                if ch > 1 or stripe == 1 << 20]

    body = textwrap.dedent("""
        import sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1])
        ctx = gloo_tpu.Context(rank, 2, timeout=120)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[2]),
                              gloo_tpu.Device())
        n = int(sys.argv[3]); iters = int(sys.argv[4]); warm = int(sys.argv[5])
        x = np.full(n, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x)
        assert x[0] == 3.0, x[0]
        x[:] = 1.0
        for _ in range(warm):
            ctx.allreduce(x)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ctx.allreduce(x)
            times.append(time.perf_counter() - t0)
        if rank == 0:
            print("P50US", int(np.median(times) * 1e6))
        ctx.barrier(); ctx.close()
    """).format(repo=os.path.dirname(os.path.abspath(__file__)))

    ok_all = True
    for loops, channels, stripe in grid:
        store = tempfile.mkdtemp()
        env = dict(os.environ,
                   TPUCOLL_SHM="0",
                   TPUCOLL_LOOP_THREADS=str(loops),
                   TPUCOLL_CHANNELS=str(channels),
                   TPUCOLL_STRIPE_BYTES=str(stripe))
        procs = [subprocess.Popen(
            [sys.executable, "-c", body, str(r), store, str(elements),
             str(iters), str(warmup)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for r in range(2)]
        outs = [p.communicate(timeout=600) for p in procs]
        line = {"metric": "channel_sweep", "loops": loops,
                "channels": channels, "stripe_bytes": stripe,
                "elements": elements, "iters": iters, "unit": "GB/s"}
        if any(p.returncode != 0 for p in procs) or                 "P50US" not in outs[0][0]:
            ok_all = False
            line["ok"] = False
            line["error"] = [f"rank {r}: rc={p.returncode} "
                             f"err={outs[r][1][-200:]!r}"
                             for r, p in enumerate(procs)]
        else:
            p50_us = int(outs[0][0].split("P50US", 1)[1].split()[0])
            line["value"] = round(elements * 4 / (p50_us * 1e-6) / 1e9, 3)
            line["p50_us"] = p50_us
            line["ok"] = True
        print(json.dumps(line))
    if not ok_all:
        sys.exit(1)


def bench_wire_sweep(quick=False):
    """--wire-sweep: 2-rank allreduce algbw per (wire codec x size)
    point under TPUCOLL_SHM=0 — the host plane's wire-compression
    crossover data (ISSUE 11 grid, grown by ISSUE 20: the q4 arm, the
    pipelined-vs-serial engine A/B in interleaved passes, the
    codec-threads axis, and a profiled 64 MiB phase breakdown proving
    the pack+unpack cut). One JSON line per point; fresh subprocesses
    per point so transport state never leaks between cells. Every run
    verifies the reduced values first: exact for the lossless ring,
    within the per-hop error bound for the codecs."""
    import tempfile
    import textwrap

    if quick:
        sizes = [1 << 20]  # 4 MiB f32
        iters, warmup = 3, 1
        ab_passes = 2
    else:
        sizes = [1 << 20, 1 << 22, ELEMENTS]  # 4 MiB, 16 MiB, 64 MiB
        iters, warmup = 8, 2
        ab_passes = 3
    algorithms = ["ring", "ring_bf16_wire", "ring_q8_wire",
                  "ring_q4_wire"]

    body = textwrap.dedent("""
        import sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1])
        ctx = gloo_tpu.Context(rank, 2, timeout=120)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[2]),
                              gloo_tpu.Device())
        n = int(sys.argv[3]); iters = int(sys.argv[4])
        warm = int(sys.argv[5]); algo = sys.argv[6]
        x = np.full(n, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, algorithm=algo)
        # 1+2=3 is exactly representable through the codecs' per-hop
        # quantization only to within one step; bound the error instead
        # of asserting exactness for the lossy arms (q4's step is
        # max|block|/7, the coarsest in the set).
        tol = (0.0 if algo == "ring"
               else 3.0 / 7.0 if algo == "ring_q4_wire"
               else 3.0 / 127.0)
        assert abs(x[0] - 3.0) <= tol, x[0]
        x[:] = 1.0
        for _ in range(warm):
            ctx.allreduce(x, algorithm=algo)
        x[:] = 1.0
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ctx.allreduce(x, algorithm=algo)
            times.append(time.perf_counter() - t0)
            x[:] = 1.0  # repeated lossy sums must not drift the scale
        if rank == 0:
            print("P50US", int(np.median(times) * 1e6))
        ctx.barrier(); ctx.close()
    """).format(repo=os.path.dirname(os.path.abspath(__file__)))

    prof_body = textwrap.dedent("""
        import json, sys
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1])
        ctx = gloo_tpu.Context(rank, 2, timeout=120)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[2]),
                              gloo_tpu.Device())
        n = int(sys.argv[3]); iters = int(sys.argv[4])
        warm = int(sys.argv[5]); algo = sys.argv[6]
        x = np.full(n, 1.0, dtype=np.float32)
        for _ in range(warm + 1):
            ctx.allreduce(x, algorithm=algo)
            x[:] = 1.0
        seq0 = ctx.profile()["next_seq"]
        for _ in range(iters):
            ctx.allreduce(x, algorithm=algo)
            x[:] = 1.0
        if rank == 0:
            ops = [o for o in ctx.profile()["ops"] if o["seq"] >= seq0]
            tot = {{}}
            for o in ops:
                for k, v in o.get("phases", {{}}).items():
                    tot[k] = tot.get(k, 0) + v
            print("PHASES", json.dumps(
                {{k: v // max(len(ops), 1) for k, v in tot.items()}}))
        ctx.barrier(); ctx.close()
    """).format(repo=os.path.dirname(os.path.abspath(__file__)))

    # The engine A/B arms. "serial" pins depth 1 on one lane with the
    # fused transport fold off — byte- and schedule-identical to the
    # pre-pipeline hop (the r11/r15 engine). "pipelined" is the new
    # default shape: depth-4 sub-blocks, a 2-wide codec pool, fused
    # dequant-accumulate on arrival.
    serial_env = {"TPUCOLL_CODEC_PIPELINE": "1",
                  "TPUCOLL_CODEC_THREADS": "1",
                  "TPUCOLL_RECV_REDUCE": "0"}
    piped_env = {"TPUCOLL_CODEC_PIPELINE": "4",
                 "TPUCOLL_CODEC_THREADS": "2",
                 "TPUCOLL_RECV_REDUCE": "1"}

    ok_all = True

    def run_point(src, elements, algo, extra_env=None, marker="P50US"):
        """One fresh 2-rank subprocess pair; returns (payload, errs)."""
        store = tempfile.mkdtemp()
        env = dict(os.environ, TPUCOLL_SHM="0", **(extra_env or {}))
        procs = [subprocess.Popen(
            [sys.executable, "-c", src, str(r), store, str(elements),
             str(iters), str(warmup), algo],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for r in range(2)]
        outs = [p.communicate(timeout=600) for p in procs]
        if any(p.returncode != 0 for p in procs) or \
                marker not in outs[0][0]:
            return None, [f"rank {r}: rc={p.returncode} "
                          f"err={outs[r][1][-200:]!r}"
                          for r, p in enumerate(procs)]
        return outs[0][0].split(marker, 1)[1], None

    def emit(line, payload, errs, elements):
        nonlocal ok_all
        if errs is not None:
            ok_all = False
            line["ok"] = False
            line["error"] = errs
        else:
            p50_us = int(payload.split()[0])
            line["value"] = round(elements * 4 / (p50_us * 1e-6) / 1e9, 3)
            line["p50_us"] = p50_us
            line["ok"] = True
        print(json.dumps(line))

    # 1) The codec-family grid (the r11 shape, plus the q4 arm).
    for elements in sizes:
        for algo in algorithms:
            payload, errs = run_point(body, elements, algo)
            emit({"metric": "wire_sweep", "algorithm": algo,
                  "elements": elements, "bytes": elements * 4,
                  "iters": iters, "unit": "GB/s"}, payload, errs,
                 elements)

    # 2) Pipelined-vs-serial engine A/B, interleaved passes (arm order
    # alternates within each pass so drift lands on both arms equally).
    for elements in sizes:
        for algo in ("ring_q8_wire", "ring_q4_wire"):
            runs = {"serial": [], "pipelined": []}
            for p in range(ab_passes):
                order = [("serial", serial_env), ("pipelined", piped_env)]
                if p % 2:
                    order.reverse()
                for arm, arm_env in order:
                    payload, errs = run_point(body, elements, algo,
                                              arm_env)
                    if errs is not None:
                        ok_all = False
                        print(json.dumps(
                            {"metric": "wire_pipeline_ab", "ok": False,
                             "algorithm": algo, "arm": arm,
                             "elements": elements, "error": errs}))
                    else:
                        runs[arm].append(int(payload.split()[0]))
            for arm in ("serial", "pipelined"):
                if not runs[arm]:
                    continue
                p50 = int(sorted(runs[arm])[len(runs[arm]) // 2])
                print(json.dumps(
                    {"metric": "wire_pipeline_ab", "algorithm": algo,
                     "arm": arm, "elements": elements,
                     "bytes": elements * 4, "iters": iters,
                     "unit": "GB/s", "runs_us": runs[arm],
                     "p50_us": p50,
                     "value": round(elements * 4 / (p50 * 1e-6) / 1e9, 3),
                     "ok": True}))

    # 3) Codec-pool width axis at the largest size (depth pinned to the
    # pipelined arm's 4 so only the pool width moves).
    for threads in (1, 2, 4):
        payload, errs = run_point(
            body, sizes[-1], "ring_q8_wire",
            {"TPUCOLL_CODEC_PIPELINE": "4",
             "TPUCOLL_CODEC_THREADS": str(threads)})
        emit({"metric": "wire_codec_threads", "algorithm": "ring_q8_wire",
              "codec_threads": threads, "elements": sizes[-1],
              "bytes": sizes[-1] * 4, "iters": iters, "unit": "GB/s"},
             payload, errs, sizes[-1])

    # 4) Profiled phase breakdown at the headline size: where did the
    # pack/unpack time go. The serial arm reproduces the pre-pipeline
    # attribution (encode + staged decode on the op thread); the
    # pipelined arm's codec work runs on the pool and in the transport
    # fold, so op-thread pack+unpack must collapse.
    phases = {}
    for arm, arm_env in (("serial", serial_env), ("pipelined", piped_env)):
        payload, errs = run_point(prof_body, sizes[-1], "ring_q8_wire",
                                  dict(arm_env, TPUCOLL_PROFILE="1"),
                                  marker="PHASES")
        line = {"metric": "wire_phase_ab", "algorithm": "ring_q8_wire",
                "arm": arm, "elements": sizes[-1],
                "bytes": sizes[-1] * 4, "iters": iters}
        if errs is not None:
            ok_all = False
            line["ok"] = False
            line["error"] = errs
        else:
            line["mean_phase_us"] = json.loads(payload)
            line["ok"] = True
            phases[arm] = line["mean_phase_us"]
        print(json.dumps(line))
    if len(phases) == 2:
        codec_us = {a: p.get("pack", 0) + p.get("unpack", 0)
                    for a, p in phases.items()}
        print(json.dumps(
            {"metric": "wire_phase_cut", "elements": sizes[-1],
             "pack_unpack_us": codec_us,
             "cut": round(codec_us["serial"] /
                          max(codec_us["pipelined"], 1), 2),
             "ok": True}))

    if not ok_all:
        sys.exit(1)


def bench_profile(quick=False):
    """--profile: per-phase breakdown per (size x algorithm) cell plus
    the profiler overhead A/B (ISSUE 15; docs/profiling.md).

    Each cell runs a fresh 2-rank subprocess pair under TPUCOLL_SHM=0,
    times `iters` allreduces, and reports the mean per-phase breakdown
    from Context.profile() restricted to the timed ops. The A/B block
    re-times the largest cell's ring allreduce with TPUCOLL_PROFILE=1
    vs =0 in interleaved passes — the committed evidence (PROF_r15.json)
    that the profiler stays inside host noise."""
    import tempfile
    import textwrap

    if quick:
        sizes = [1 << 18]  # 1 MiB f32
        iters, warmup, ab_passes = 3, 1, 2
    else:
        sizes = [1 << 18, 1 << 22, ELEMENTS]  # 1 MiB, 16 MiB, 64 MiB
        iters, warmup, ab_passes = 8, 2, 5
    algorithms = ["ring", "hd", "ring_q8_wire"]

    body = textwrap.dedent("""
        import json, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1])
        ctx = gloo_tpu.Context(rank, 2, timeout=120)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[2]),
                              gloo_tpu.Device())
        n = int(sys.argv[3]); iters = int(sys.argv[4])
        warm = int(sys.argv[5]); algo = sys.argv[6]
        x = np.full(n, 1.0, dtype=np.float32)
        for _ in range(warm):
            ctx.allreduce(x, algorithm=algo)
            x[:] = 1.0
        first_seq = len(ctx.profile()["ops"])  # == ring seq after warm-up
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ctx.allreduce(x, algorithm=algo)
            times.append(time.perf_counter() - t0)
            x[:] = 1.0
        if rank == 0:
            snap = ctx.profile()
            timed = [o for o in snap["ops"]
                     if o["op"] == "allreduce" and o["seq"] >= first_seq]
            phases = {{}}
            total = 0
            for o in timed:
                total += o["total_us"]
                for k, v in o["phases"].items():
                    phases[k] = phases.get(k, 0) + v
            out = {{"p50_us": int(np.median(times) * 1e6),
                    "profiled_ops": len(timed),
                    "enabled": snap["enabled"],
                    "mean_total_us": total // max(len(timed), 1),
                    "mean_phase_us": {{k: v // max(len(timed), 1)
                                       for k, v in sorted(phases.items())}}}}
            print("RESULT " + json.dumps(out))
        ctx.barrier(); ctx.close()
    """).format(repo=os.path.dirname(os.path.abspath(__file__)))

    def run_cell(elements, algo, profile_on):
        store = tempfile.mkdtemp()
        env = dict(os.environ, TPUCOLL_SHM="0",
                   TPUCOLL_PROFILE="1" if profile_on else "0")
        procs = [subprocess.Popen(
            [sys.executable, "-c", body, str(r), store, str(elements),
             str(iters), str(warmup), algo],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for r in range(2)]
        outs = [p.communicate(timeout=600) for p in procs]
        if any(p.returncode != 0 for p in procs) or \
                "RESULT " not in outs[0][0]:
            return None, [f"rank {r}: rc={p.returncode} "
                          f"err={outs[r][1][-200:]!r}"
                          for r, p in enumerate(procs)]
        return json.loads(outs[0][0].split("RESULT ", 1)[1]), None

    ok_all = True
    for elements in sizes:
        for algo in algorithms:
            res, err = run_cell(elements, algo, profile_on=True)
            line = {"metric": "profile_phases", "algorithm": algo,
                    "elements": elements, "bytes": elements * 4,
                    "iters": iters}
            if res is None:
                ok_all = False
                line.update(ok=False, error=err)
            else:
                line.update(ok=True, **res)
            print(json.dumps(line))

    # Overhead A/B on the largest ring cell: interleaved passes so host
    # drift hits both arms equally; the JSON records both p50 series.
    ab_elements = sizes[-1]
    on_us, off_us = [], []
    ab_errors = []
    for _ in range(ab_passes):
        for arm, acc in (("on", on_us), ("off", off_us)):
            res, err = run_cell(ab_elements, "ring", arm == "on")
            if res is None:
                ab_errors.extend(err)
            else:
                acc.append(res["p50_us"])
    line = {"metric": "profile_overhead_ab", "algorithm": "ring",
            "elements": ab_elements, "bytes": ab_elements * 4,
            "passes": ab_passes}
    # A pass failure anywhere invalidates the A/B as committed evidence
    # (a median over fewer samples than `passes` claims would quietly
    # understate its own noise): every collected error is emitted and
    # flips ok=False, even when both arms still have survivors.
    if not on_us or not off_us or ab_errors:
        ok_all = False
        line.update(ok=False, error=ab_errors,
                    runs_on_us=on_us, runs_off_us=off_us)
    else:
        med_on = sorted(on_us)[len(on_us) // 2]
        med_off = sorted(off_us)[len(off_us) // 2]
        line.update(ok=True, p50_us_profile_on=med_on,
                    p50_us_profile_off=med_off,
                    runs_on_us=on_us, runs_off_us=off_us,
                    overhead=round(med_on / med_off - 1.0, 4))
    print(json.dumps(line))
    if not ok_all:
        sys.exit(1)


def bench_critpath(quick=False):
    """--critpath: overhead A/B of the causal span recorder (ISSUE 19;
    docs/critpath.md) plus a critical-path attribution sanity cell.

    The A/B times 2-rank ring allreduces with TPUCOLL_SPANS=1 vs =0 in
    interleaved passes (host drift hits both arms equally) — the
    committed evidence (CRIT_r19.json) that span recording stays inside
    host noise. The attribution cell runs one spans-on pair, merges
    both ranks' Context.spans() through utils.critpath.analyze(), and
    reports how much of the op latency the extracted critical path
    explains and that every wire edge matched."""
    import tempfile
    import textwrap

    if quick:
        elements, iters, warmup, ab_passes = 1 << 18, 3, 1, 2
    else:
        elements, iters, warmup, ab_passes = 1 << 22, 8, 2, 5

    body = textwrap.dedent("""
        import json, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1])
        ctx = gloo_tpu.Context(rank, 2, timeout=120)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[2]),
                              gloo_tpu.Device())
        n = int(sys.argv[3]); iters = int(sys.argv[4])
        warm = int(sys.argv[5]); store = sys.argv[2]
        x = np.full(n, 1.0, dtype=np.float32)
        for _ in range(warm):
            ctx.allreduce(x, algorithm="ring")
            x[:] = 1.0
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ctx.allreduce(x, algorithm="ring")
            times.append(time.perf_counter() - t0)
            x[:] = 1.0
        # Every rank parks its span snapshot in the store dir before the
        # barrier so rank 0 can fold a cross-rank analysis after it.
        import os
        with open(os.path.join(store, f"spans-rank{{rank}}.json"),
                  "w") as f:
            json.dump(ctx.spans(), f)
        ctx.barrier()
        if rank == 0:
            from gloo_tpu.utils import critpath
            snaps = []
            for r in range(2):
                with open(os.path.join(store,
                                       f"spans-rank{{r}}.json")) as f:
                    snaps.append(json.load(f))
            out = {{"p50_us": int(np.median(times) * 1e6),
                    "spans_enabled": snaps[0]["enabled"]}}
            if snaps[0]["enabled"]:
                a = critpath.analyze(critpath.merge(snaps))
                covs, unmatched = [], 0
                for op in a["ops"]:
                    if op["total_us"] <= 0:
                        continue
                    covered = sum(r["contrib_us"] for r in op["path"])
                    covs.append(covered / op["total_us"])
                    unmatched += sum(op["unmatched"].values())
                covs.sort()
                out.update(analyzed_ops=len(covs), unmatched=unmatched,
                           path_coverage_p50=round(
                               covs[len(covs) // 2], 4) if covs else 0.0)
            print("RESULT " + json.dumps(out))
        ctx.barrier(); ctx.close()
    """).format(repo=os.path.dirname(os.path.abspath(__file__)))

    def run_cell(spans_on):
        store = tempfile.mkdtemp()
        env = dict(os.environ, TPUCOLL_SHM="0",
                   TPUCOLL_SPANS="1" if spans_on else "0")
        procs = [subprocess.Popen(
            [sys.executable, "-c", body, str(r), store, str(elements),
             str(iters), str(warmup)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for r in range(2)]
        outs = [p.communicate(timeout=600) for p in procs]
        if any(p.returncode != 0 for p in procs) or \
                "RESULT " not in outs[0][0]:
            return None, [f"rank {r}: rc={p.returncode} "
                          f"err={outs[r][1][-200:]!r}"
                          for r, p in enumerate(procs)]
        return json.loads(outs[0][0].split("RESULT ", 1)[1]), None

    ok_all = True

    # Attribution sanity cell (spans on, one pair).
    res, err = run_cell(spans_on=True)
    line = {"metric": "critpath_attribution", "algorithm": "ring",
            "elements": elements, "bytes": elements * 4, "iters": iters}
    if res is None:
        ok_all = False
        line.update(ok=False, error=err)
    else:
        line.update(ok=True, **res)
    print(json.dumps(line))

    # Overhead A/B: interleaved passes so host drift hits both arms
    # equally; the JSON records both p50 series.
    on_us, off_us = [], []
    ab_errors = []
    for i in range(ab_passes):
        # Alternate which arm goes first so per-pass warm-up transients
        # (page cache, cpufreq) don't land on one arm systematically.
        arms = (("on", on_us), ("off", off_us))
        for arm, acc in arms if i % 2 == 0 else arms[::-1]:
            res, err = run_cell(spans_on=arm == "on")
            if res is None:
                ab_errors.extend(err)
            else:
                acc.append(res["p50_us"])
    line = {"metric": "critpath_overhead_ab", "algorithm": "ring",
            "elements": elements, "bytes": elements * 4,
            "passes": ab_passes}
    # A pass failure anywhere invalidates the A/B as committed evidence
    # (same rule as profile_overhead_ab): every collected error is
    # emitted and flips ok=False, even when both arms have survivors.
    if not on_us or not off_us or ab_errors:
        ok_all = False
        line.update(ok=False, error=ab_errors,
                    runs_on_us=on_us, runs_off_us=off_us)
    else:
        med_on = sorted(on_us)[len(on_us) // 2]
        med_off = sorted(off_us)[len(off_us) // 2]
        line.update(ok=True, p50_us_spans_on=med_on,
                    p50_us_spans_off=med_off,
                    runs_on_us=on_us, runs_off_us=off_us,
                    overhead=round(med_on / med_off - 1.0, 4))
    print(json.dumps(line))
    if not ok_all:
        sys.exit(1)


def bench_fleetobs(quick=False):
    """--fleetobs: overhead A/B of the in-band fleet observability
    plane (ISSUE 16; docs/fleet.md).

    Each arm runs a fresh 4-rank subprocess grid over a FileStore with
    two simulated hosts (TPUCOLL_HOST_ID per process) so the full
    member -> leader -> rank 0 relay is live, and times `iters` ring
    allreduces with the plane aggregating at a 100 ms interval (on) vs
    TPUCOLL_FLEETOBS=0 (off). Arms are interleaved so host drift hits
    both equally. The on-arm also reports the fleet document's
    coverage — the committed evidence (OBS_r16.json) that the plane
    covers every rank while staying inside host noise."""
    import tempfile
    import textwrap

    if quick:
        elements, iters, warmup, ab_passes = 1 << 18, 3, 1, 2
    else:
        elements, iters, warmup, ab_passes = 1 << 20, 8, 2, 5
    size, rph = 4, 2

    body = textwrap.dedent("""
        import json, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu
        from gloo_tpu.utils import fleet as fleet_util

        rank = int(sys.argv[1])
        ctx = gloo_tpu.Context(rank, {size}, timeout=120)
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[2]),
                              gloo_tpu.Device())
        n = int(sys.argv[3]); iters = int(sys.argv[4])
        warm = int(sys.argv[5]); fleet_on = sys.argv[6] == "on"
        if fleet_on:
            ctx.fleetobs_start()
        x = np.full(n, 1.0, dtype=np.float32)
        for _ in range(warm):
            ctx.allreduce(x, algorithm="ring")
            x[:] = 1.0
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            ctx.allreduce(x, algorithm="ring")
            times.append(time.perf_counter() - t0)
            x[:] = 1.0
        coverage = None
        if fleet_on and rank == 0:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                coverage = fleet_util.coverage(ctx.fleet())
                if coverage["complete"]:
                    break
                time.sleep(0.1)
        if rank == 0:
            out = {{"p50_us": int(np.median(times) * 1e6),
                    "running": ctx.fleetobs_running(),
                    "coverage": coverage}}
            print("RESULT " + json.dumps(out))
        ctx.barrier()
        if fleet_on:
            ctx.fleetobs_stop()
        ctx.close()
    """).format(repo=os.path.dirname(os.path.abspath(__file__)),
                size=size)

    def run_arm(arm):
        store = tempfile.mkdtemp()
        procs = []
        for r in range(size):
            env = dict(os.environ, TPUCOLL_SHM="0",
                       TPUCOLL_HOST_ID=f"obshost{r // rph}",
                       TPUCOLL_FLEETOBS="1" if arm == "on" else "0",
                       TPUCOLL_FLEETOBS_INTERVAL_MS="100")
            procs.append(subprocess.Popen(
                [sys.executable, "-c", body, str(r), store,
                 str(elements), str(iters), str(warmup), arm],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env))
        outs = [p.communicate(timeout=600) for p in procs]
        if any(p.returncode != 0 for p in procs) or \
                "RESULT " not in outs[0][0]:
            return None, [f"rank {r}: rc={p.returncode} "
                          f"err={outs[r][1][-200:]!r}"
                          for r, p in enumerate(procs)]
        return json.loads(outs[0][0].split("RESULT ", 1)[1]), None

    on_us, off_us, ab_errors = [], [], []
    coverages = []
    for _ in range(ab_passes):
        for arm, acc in (("on", on_us), ("off", off_us)):
            res, err = run_arm(arm)
            if res is None:
                ab_errors.extend(err)
                continue
            acc.append(res["p50_us"])
            if arm == "on":
                coverages.append(res["coverage"])
    line = {"metric": "fleetobs_overhead_ab", "algorithm": "ring",
            "ranks": size, "hosts": size // rph, "elements": elements,
            "bytes": elements * 4, "iters": iters, "passes": ab_passes}
    covered = bool(coverages) and all(
        c and c["complete"] for c in coverages)
    # Same evidence discipline as the profiler A/B: any pass failure or
    # coverage hole flips ok=False — a partial median would quietly
    # overstate its own confidence.
    if not on_us or not off_us or ab_errors or not covered:
        line.update(ok=False, error=ab_errors, coverage=coverages,
                    runs_on_us=on_us, runs_off_us=off_us)
        print(json.dumps(line))
        sys.exit(1)
    med_on = sorted(on_us)[len(on_us) // 2]
    med_off = sorted(off_us)[len(off_us) // 2]
    line.update(ok=True, p50_us_fleetobs_on=med_on,
                p50_us_fleetobs_off=med_off,
                runs_on_us=on_us, runs_off_us=off_us,
                coverage=coverages[-1],
                overhead=round(med_on / med_off - 1.0, 4))
    print(json.dumps(line))


def bench_hier_sweep(quick=False):
    """--hier-sweep: flat (ring) vs hierarchical allreduce per
    (size x simulated hosts x ranks-per-host) cell, one JSON line per
    cell (ISSUE 13; docs/topology.md).

    Each cell spawns hosts*rph real processes over a FileStore, with
    TPUCOLL_HOST_ID grouping them into simulated hosts — so intra-"host"
    pairs negotiate the shm plane while cross-"host" pairs stay on TCP
    (the topology mask pins them there), exactly the mixed fabric the
    hierarchical schedule is built for. Both arms run in the same
    process set (same mesh, interleaved) and verify the reduced value
    first; `hier_vs_flat` is the bandwidth ratio (>1 = hier faster)."""
    import tempfile
    import textwrap

    if quick:
        cells = [(2, 2, 1 << 18)]  # 2 hosts x 2 rph, 1 MiB f32
        iters, warmup = 3, 1
    else:
        cells = [(hosts, rph, elements)
                 for hosts, rph in ((2, 2), (2, 3))
                 for elements in (1 << 16, 1 << 18, 1 << 20, 1 << 22)]
        iters, warmup = 8, 2

    body = textwrap.dedent("""
        import sys, time, json
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1]); size = int(sys.argv[2])
        rph = int(sys.argv[3]); n = int(sys.argv[4])
        iters = int(sys.argv[5]); warm = int(sys.argv[6])
        ctx = gloo_tpu.Context(rank, size, timeout=120)
        ctx.set_host_id("simhost%d" % (rank // rph))
        ctx.connect_full_mesh(gloo_tpu.FileStore(sys.argv[7]),
                              gloo_tpu.Device())
        topo = ctx.topology()
        assert topo["n_hosts"] == size // rph and topo["non_flat"], topo
        expect = float(sum(range(1, size + 1)))
        # Correctness first, then INTERLEAVED timed passes: alternating
        # the arms inside each iteration exposes both to the same host
        # drift (this box's run-to-run spread dwarfs the arm delta).
        times = {{"ring": [], "hier": []}}
        x = np.full(n, float(rank + 1), dtype=np.float32)
        for algo in ("ring", "hier"):
            ctx.allreduce(x, algorithm=algo)
        x = np.full(n, float(rank + 1), dtype=np.float32)
        ctx.allreduce(x, algorithm="hier")
        assert x[0] == expect and x[-1] == expect, x[0]
        x[:] = 1.0
        for _ in range(warm):
            for algo in ("ring", "hier"):
                ctx.allreduce(x, algorithm=algo)
        for _ in range(iters):
            for algo in ("ring", "hier"):
                t0 = time.perf_counter()
                ctx.allreduce(x, algorithm=algo)
                times[algo].append(time.perf_counter() - t0)
                x[:] = 1.0
        results = {{a: int(np.median(t) * 1e6)
                    for a, t in times.items()}}
        # Mixed-fabric evidence: intra-host pairs negotiated shm.
        assert ctx.shm_stats()["active_pairs"] == rph - 1
        if rank == 0:
            print("P50US", json.dumps(results))
        ctx.barrier(); ctx.close()
    """).format(repo=os.path.dirname(os.path.abspath(__file__)))

    ok_all = True
    for hosts, rph, elements in cells:
        size = hosts * rph
        store = tempfile.mkdtemp()
        procs = [subprocess.Popen(
            [sys.executable, "-c", body, str(r), str(size), str(rph),
             str(elements), str(iters), str(warmup), store],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for r in range(size)]
        outs = [p.communicate(timeout=600) for p in procs]
        line = {"metric": "hier_sweep", "hosts": hosts,
                "ranks_per_host": rph, "ranks": size,
                "elements": elements, "bytes": elements * 4,
                "iters": iters, "unit": "GB/s"}
        if any(p.returncode != 0 for p in procs) or \
                "P50US" not in outs[0][0]:
            ok_all = False
            line["ok"] = False
            line["error"] = [f"rank {r}: rc={p.returncode} "
                             f"err={outs[r][1][-200:]!r}"
                             for r, p in enumerate(procs)]
        else:
            p50 = json.loads(
                outs[0][0].split("P50US", 1)[1].strip().splitlines()[0])
            line["flat_p50_us"] = p50["ring"]
            line["hier_p50_us"] = p50["hier"]
            line["flat_gbps"] = round(
                elements * 4 / (p50["ring"] * 1e-6) / 1e9, 3)
            line["hier_gbps"] = round(
                elements * 4 / (p50["hier"] * 1e-6) / 1e9, 3)
            line["hier_vs_flat"] = round(p50["ring"] / p50["hier"], 3)
            line["ok"] = True
        print(json.dumps(line))
    if not ok_all:
        sys.exit(1)


def bench_grad_bucket(n_tensors, lanes=2, pin=False):
    """--grad-bucket N: the training-shaped workload — N heterogeneous
    gradient tensors with log-normally distributed sizes, allreduced
    per step either sequentially (one blocking allreduce per tensor,
    the pre-async baseline) or through the async engine + gradient
    bucketer (docs/async.md: per-dtype ~TPUCOLL_BUCKET_BYTES flat
    buckets, issued async so bucket k+1's pack overlaps bucket k's wire
    time). Two real rank processes over a FileStore; per mode the step
    time is the median of 5 timed steps after a warm-up step; three
    size-distribution seeds; ONE JSON line:

      {"metric": "grad_bucket_allreduce_2rank_host",
       "value": <geomean over seeds of seq_ms / bucketed_ms>,
       "unit": "x_speedup_vs_sequential", "tensors": N, "lanes": L,
       "bucket_bytes": B, "pinned": bool,
       "cells": [{"seed", "total_mb", "seq_ms", "bucketed_ms",
                  "speedup"}, ...]}

    Every step's results are verified against the closed form on both
    ranks before anything is timed.
    """
    import math
    import textwrap

    from gloo_tpu.bucketer import DEFAULT_BUCKET_BYTES

    bucket_bytes = int(os.environ.get("TPUCOLL_BUCKET_BYTES",
                                      DEFAULT_BUCKET_BYTES))
    body = textwrap.dedent("""
        import os, sys, time
        sys.path.insert(0, {repo!r})
        import numpy as np
        import gloo_tpu

        rank = int(sys.argv[1]); store_path = sys.argv[2]
        n = int(sys.argv[3]); seed = int(sys.argv[4])
        lanes = int(sys.argv[5]); pin = int(sys.argv[6])
        if pin:
            os.sched_setaffinity(0, {{rank % (os.cpu_count() or 1)}})
        ctx = gloo_tpu.Context(rank, 2, timeout=120)
        ctx.connect_full_mesh(gloo_tpu.FileStore(store_path),
                              gloo_tpu.Device())

        # Log-normal tensor sizes (the shape of a real model's gradient
        # list: many small, a few large), identical on both ranks.
        rng = np.random.default_rng(seed)
        nbytes = np.exp(rng.normal(np.log(64 * 1024), 1.25, size=n))
        nbytes = np.clip(nbytes, 1024, 8 << 20).astype(np.int64)
        tensors = [np.empty(max(1, int(b) // 4), dtype=np.float32)
                   for b in nbytes]

        def refill():
            for t in tensors:
                t[:] = rank + 1.0

        def verify():
            for t in tensors:
                assert t[0] == 3.0, t[0]

        STEPS = 5

        # Sequential baseline: one blocking allreduce per tensor.
        refill(); ctx.barrier(tag=1)
        for t in tensors:
            ctx.allreduce(t)
        verify()
        seq_times = []
        for _ in range(STEPS):
            refill(); ctx.barrier(tag=2)
            t0 = time.perf_counter()
            for t in tensors:
                ctx.allreduce(t)
            seq_times.append(time.perf_counter() - t0)
        verify()

        # Bucketed-async: per-dtype flat buckets on the engine lanes.
        engine = ctx.async_engine(lanes=lanes)
        bucketer = gloo_tpu.GradientBucketer(engine)
        refill(); ctx.barrier(tag=3)
        for t in tensors:
            bucketer.add(t)
        bucketer.finish()
        verify()
        bkt_times = []
        for _ in range(STEPS):
            refill(); ctx.barrier(tag=4)
            t0 = time.perf_counter()
            for t in tensors:
                bucketer.add(t)
            bucketer.finish()
            bkt_times.append(time.perf_counter() - t0)
        verify()
        if rank == 0:
            print("SEQ_MS", round(float(np.median(seq_times)) * 1e3, 2),
                  "BKT_MS", round(float(np.median(bkt_times)) * 1e3, 2),
                  "TOTAL_MB",
                  round(float(sum(t.nbytes for t in tensors)) / 2**20, 1))
        ctx.barrier(tag=5); ctx.close()
    """).format(repo=os.path.dirname(os.path.abspath(__file__)))

    cells = []
    ok_all = True
    for seed in (11, 23, 47):
        store = tempfile.mkdtemp()
        procs = [subprocess.Popen(
            [sys.executable, "-c", body, str(r), store, str(n_tensors),
             str(seed), str(lanes), "1" if pin else "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for r in range(2)]
        outs = [p.communicate(timeout=900) for p in procs]
        if any(p.returncode != 0 for p in procs) or \
                "SEQ_MS" not in outs[0][0]:
            ok_all = False
            cells.append({"seed": seed, "ok": False,
                          "error": [f"rank {r}: rc={p.returncode} "
                                    f"err={outs[r][1][-300:]!r}"
                                    for r, p in enumerate(procs)]})
            continue
        fields = outs[0][0].split()
        seq_ms = float(fields[fields.index("SEQ_MS") + 1])
        bkt_ms = float(fields[fields.index("BKT_MS") + 1])
        total_mb = float(fields[fields.index("TOTAL_MB") + 1])
        cells.append({"seed": seed, "total_mb": total_mb,
                      "seq_ms": seq_ms, "bucketed_ms": bkt_ms,
                      "speedup": round(seq_ms / bkt_ms, 3)})
        print(f"[grad-bucket] seed {seed}: {n_tensors} tensors "
              f"({total_mb:.1f} MiB) seq {seq_ms:.1f}ms bucketed "
              f"{bkt_ms:.1f}ms ({seq_ms / bkt_ms:.2f}x)",
              file=sys.stderr)
    line = {
        "metric": "grad_bucket_allreduce_2rank_host",
        "unit": "x_speedup_vs_sequential",
        "tensors": n_tensors,
        "lanes": lanes,
        "bucket_bytes": bucket_bytes,
        "pinned": pin,
        "cells": cells,
        "ok": ok_all,
    }
    good = [c["speedup"] for c in cells if "speedup" in c]
    if good:
        line["value"] = round(
            math.exp(sum(math.log(s) for s in good) / len(good)), 3)
    print(json.dumps(line))
    if not ok_all:
        sys.exit(1)


def bench_bootstrap_sweep(quick=False, out_path=None):
    """--bootstrap-sweep [--quick]: measure the bootstrap plane
    (docs/bootstrap.md) along its three acceptance axes and write ONE
    JSON document (default BOOT_r18.json next to this script):

    1. Store choreography: tc_boot_rendezvous_bench runs an in-process
       N-thread rendezvous over a shared FileStore for N in {8, 32,
       128, 512} ({8, 32} with --quick), once with the leader-relayed
       lazy protocol and once with the full-mesh simulation the seed's
       connectFullMesh performs. The lazy arm's store traffic is
       O(hosts^2 + N) vs O(N^2); by N=512 the wall-clock gap must be
       superlinear in N (the committed evidence for P>=512 scaling).
    2. Real bring-up at small N: 8 thread-ranks across 2 simulated
       hosts connect with TPUCOLL_BOOT_MODE=lazy vs the default eager
       full mesh, verifying the reduced value both ways, then soak the
       lazy mesh with a mixed alltoall/allreduce/p2p workload under
       TPUCOLL_MAX_PAIRS=2 and assert the broker held the steady-state
       broker-dialed pair count at or under the cap (with evictions
       actually exercised).
    3. Elastic rebuild with per-host lease aggregation: re-runs the
       --elastic-soak quick cell with TPUCOLL_LEASE_AGG=1 and checks
       rebuild_ms_p50 against the committed ELASTIC_r14.json p50 —
       aggregation must not slow the small-N rebuild it exists to
       protect at large N.
    """
    import numpy as np

    import gloo_tpu
    from gloo_tpu import _lib

    repo = os.path.dirname(os.path.abspath(__file__))
    if out_path is None:
        out_path = os.path.join(repo, "BOOT_r18.json")
    rph, shards, payload = 8, 8, 64
    ns = (8, 32) if quick else (8, 32, 128, 512)
    ok_all = True

    # -- 1. store choreography curves (native N-thread rendezvous sim) --
    choreography = []
    for n in ns:
        cell = {"nranks": n, "hosts": max(1, n // rph)}
        for arm in ("lazy", "full"):
            d = tempfile.mkdtemp()
            raw = _lib.copy_out(
                _lib.lib.tc_boot_rendezvous_bench, d.encode(), n, rph,
                shards, 1 if arm == "lazy" else 0, payload, 300000)
            cell[arm] = {k: v for k, v in json.loads(raw).items()
                         if k in ("wall_ms", "publish_ms", "topo_ms",
                                  "exchange_ms", "store_ops",
                                  "store_bytes")}
        cell["wall_ratio"] = round(
            cell["full"]["wall_ms"] / max(cell["lazy"]["wall_ms"], 1e-9), 2)
        cell["ops_ratio"] = round(
            cell["full"]["store_ops"] / max(cell["lazy"]["store_ops"], 1), 2)
        # Crossover: the relay round-trips cost more than they save at
        # tiny N; from 128 up the O(N^2) store scan must lose.
        if n >= 128 and cell["wall_ratio"] <= 1.0:
            ok_all = False
        choreography.append(cell)
        print(f"[bootstrap-sweep] N={n}: lazy "
              f"{cell['lazy']['wall_ms']:.0f}ms/"
              f"{cell['lazy']['store_ops']} ops, full "
              f"{cell['full']['wall_ms']:.0f}ms/"
              f"{cell['full']['store_ops']} ops "
              f"({cell['wall_ratio']}x wall)", file=sys.stderr)
    # Superlinear gap: the full/lazy wall ratio must itself grow with N.
    ratios = [c["wall_ratio"] for c in choreography]
    if not quick and not ratios[-1] > ratios[-2]:
        ok_all = False

    # -- 2. real bring-up + capped-broker soak at 8 ranks / 2 hosts --
    size, cap = 8, 2

    def bringup(lazy, soak):
        errs = []
        connect_ms = [0.0] * size
        stats = [None] * size
        store_dir = tempfile.mkdtemp()
        barrier = threading.Barrier(size)

        def worker(rank):
            try:
                ctx = gloo_tpu.Context(rank, size, timeout=60)
                ctx.set_host_id("bootbench%d" % (rank // 4))
                barrier.wait()
                t0 = time.perf_counter()
                ctx.connect_full_mesh(gloo_tpu.FileStore(store_dir),
                                      gloo_tpu.Device())
                connect_ms[rank] = (time.perf_counter() - t0) * 1e3
                eager = ctx.metrics()["boot"]["pairs_connected"]
                x = np.full(64, float(rank + 1), dtype=np.float32)
                ctx.allreduce(x)
                assert x[0] == size * (size + 1) / 2, x[0]
                if soak:
                    for i in range(12):
                        a2a = np.full((size, 8), float(rank),
                                      dtype=np.float32)
                        out = ctx.alltoall(a2a, tag=1)
                        assert out[rank][0] == float(rank), out[rank][0]
                        y = np.ones(256, dtype=np.float32)
                        ctx.allreduce(y)
                        assert y[0] == size, y[0]
                    # Quiesced single fresh dial per rank: the cap is
                    # enforced at dial time (in-flight pairs are pinned
                    # and may transiently exceed it), so the steady-
                    # state claim is "after a dial with the mesh idle,
                    # broker pairs <= cap".
                    ctx.barrier(tag=2)
                    z = np.full(16, float(rank), dtype=np.float32)
                    ctx.send(z, (rank + 3) % size, slot=7)
                    w = np.empty(16, dtype=np.float32)
                    ctx.recv(w, (rank - 3) % size, slot=7)
                    assert w[0] == float((rank - 3) % size), w[0]
                    boot = ctx.metrics()["boot"]
                    broker = boot["pairs_connected"] - eager
                    assert broker <= cap, (rank, broker, boot)
                    stats[rank] = {"eager": eager,
                                   "broker_end": broker,
                                   "evicted": boot["pairs_evicted"],
                                   "dials": boot["lazy_dials"]}
                ctx.barrier(tag=3)
                ctx.close()
            except BaseException as e:  # noqa: B036 - report & join
                errs.append(f"rank {rank}: {type(e).__name__}: {e}")

        env_keys = ("TPUCOLL_BOOT_MODE", "TPUCOLL_MAX_PAIRS")
        saved = {k: os.environ.get(k) for k in env_keys}
        try:
            if lazy:
                os.environ["TPUCOLL_BOOT_MODE"] = "lazy"
                os.environ["TPUCOLL_MAX_PAIRS"] = str(cap)
            else:
                os.environ.pop("TPUCOLL_BOOT_MODE", None)
                os.environ.pop("TPUCOLL_MAX_PAIRS", None)
            threads = [threading.Thread(target=worker, args=(r,))
                       for r in range(size)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if errs:
            raise RuntimeError("; ".join(errs))
        return max(connect_ms), stats

    e2e = {"nranks": size, "hosts": 2, "cap": cap}
    try:
        lazy_ms, soak_stats = bringup(lazy=True, soak=True)
        full_ms, _ = bringup(lazy=False, soak=False)
        e2e["connect_ms_lazy"] = round(lazy_ms, 1)
        e2e["connect_ms_full"] = round(full_ms, 1)
        e2e["soak"] = {
            "iters": 12,
            "eager_pairs": [s["eager"] for s in soak_stats],
            "broker_pairs_end": [s["broker_end"] for s in soak_stats],
            "evictions": sum(s["evicted"] for s in soak_stats),
            "dials": sum(s["dials"] for s in soak_stats),
        }
        e2e["ok"] = (max(s["broker_end"] for s in soak_stats) <= cap
                     and e2e["soak"]["evictions"] > 0)
    except RuntimeError as e:
        e2e["ok"] = False
        e2e["error"] = str(e)[-500:]
    ok_all = ok_all and e2e["ok"]
    print(f"[bootstrap-sweep] e2e 8-rank: {e2e}", file=sys.stderr)

    # -- 3. elastic rebuild with aggregated leases vs ELASTIC_r14 --
    base_p50 = 11
    try:
        with open(os.path.join(repo, "ELASTIC_r14.json")) as f:
            base_p50 = json.load(f)["rebuild_ms_p50"]
    except (OSError, KeyError, ValueError):
        pass
    soak_s = "8" if quick else "20"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--elastic-soak", soak_s, "--quick"],
        capture_output=True, text=True, timeout=600,
        env=dict(os.environ, TPUCOLL_LEASE_AGG="1"))
    elastic = {"baseline_r14_p50_ms": base_p50, "lease_agg": True}
    lines = [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")]
    if proc.returncode == 0 and lines:
        soak_line = json.loads(lines[-1])
        elastic["rebuild_ms_p50"] = soak_line["rebuild_ms_p50"]
        elastic["rebuild_ms_p99"] = soak_line["rebuild_ms_p99"]
        elastic["epochs"] = soak_line["value"]
        elastic["kills"] = soak_line["kills"]
        # Same-machine jitter allowance: the claim is "aggregation does
        # not slow the small-N rebuild", not a microbenchmark tie.
        elastic["ok"] = (soak_line["ok"]
                         and soak_line["rebuild_ms_p50"] <= base_p50 * 2)
    else:
        elastic["ok"] = False
        elastic["error"] = (proc.stderr or proc.stdout)[-500:]
    ok_all = ok_all and elastic["ok"]
    print(f"[bootstrap-sweep] elastic agg rebuild: {elastic}",
          file=sys.stderr)

    doc = {
        "metric": "bootstrap_scale_sweep",
        "unit": "x_full_over_lazy_wall",
        "value": ratios[-1],
        "quick": quick,
        "ranks_per_host": rph,
        "shards": shards,
        "choreography": choreography,
        "e2e_8rank": e2e,
        "elastic_rebuild": elastic,
        "ok": ok_all,
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.write("\n")
    print(json.dumps({k: doc[k] for k in ("metric", "value", "ok")}))
    if not ok_all:
        sys.exit(1)


def main():
    global PIN_RANKS
    if "--pin" in sys.argv[1:]:
        PIN_RANKS = True
    if "--grad-bucket" in sys.argv[1:]:
        i = sys.argv.index("--grad-bucket") + 1
        if i >= len(sys.argv) or sys.argv[i].startswith("--"):
            sys.exit("--grad-bucket requires a tensor count")
        lanes = 2
        if "--lanes" in sys.argv[1:]:
            j = sys.argv.index("--lanes") + 1
            if j >= len(sys.argv) or sys.argv[j].startswith("--"):
                sys.exit("--lanes requires a count")
            lanes = int(sys.argv[j])
        bench_grad_bucket(int(sys.argv[i]), lanes=lanes, pin=PIN_RANKS)
        return
    if "--flightrec" in sys.argv[1:]:
        i = sys.argv.index("--flightrec") + 1
        if i >= len(sys.argv) or sys.argv[i].startswith("--"):
            sys.exit("--flightrec requires a duration (seconds)")
        bench_flightrec_soak(float(sys.argv[i]))
        return
    if "--latency" in sys.argv[1:]:
        bench_latency(quick="--quick" in sys.argv[1:])
        return
    if "--channel-sweep" in sys.argv[1:]:
        bench_channel_sweep(quick="--quick" in sys.argv[1:])
        return
    if "--wire-sweep" in sys.argv[1:]:
        bench_wire_sweep(quick="--quick" in sys.argv[1:])
        return
    if "--hier-sweep" in sys.argv[1:]:
        bench_hier_sweep(quick="--quick" in sys.argv[1:])
        return
    if "--bootstrap-sweep" in sys.argv[1:]:
        out = None
        if "--bootstrap-out" in sys.argv[1:]:
            i = sys.argv.index("--bootstrap-out") + 1
            if i >= len(sys.argv) or sys.argv[i].startswith("--"):
                sys.exit("--bootstrap-out requires a path argument")
            out = sys.argv[i]
        bench_bootstrap_sweep(quick="--quick" in sys.argv[1:],
                              out_path=out)
        return
    if "--profile" in sys.argv[1:]:
        bench_profile(quick="--quick" in sys.argv[1:])
        return
    if "--fleetobs" in sys.argv[1:]:
        bench_fleetobs(quick="--quick" in sys.argv[1:])
        return
    if "--critpath" in sys.argv[1:]:
        bench_critpath(quick="--quick" in sys.argv[1:])
        return
    if "--elastic-soak" in sys.argv[1:]:
        i = sys.argv.index("--elastic-soak") + 1
        if i >= len(sys.argv) or sys.argv[i].startswith("--"):
            sys.exit("--elastic-soak requires a duration (seconds)")
        bench_elastic_soak(float(sys.argv[i]),
                           quick="--quick" in sys.argv[1:])
        return
    if "--chaos-soak" in sys.argv[1:]:
        i = sys.argv.index("--chaos-soak") + 1
        if i >= len(sys.argv) or sys.argv[i].startswith("--"):
            sys.exit("--chaos-soak requires a duration (seconds)")
        bench_chaos_soak(float(sys.argv[i]))
        return
    if "--schedule-sweep" in sys.argv[1:]:
        out = None
        if "--schedule-out" in sys.argv[1:]:
            i = sys.argv.index("--schedule-out") + 1
            if i >= len(sys.argv) or sys.argv[i].startswith("--"):
                sys.exit("--schedule-out requires a path argument")
            out = sys.argv[i]
        bench_schedule_sweep(quick="--quick" in sys.argv[1:],
                             out_path=out)
        return
    if "--autotune" in sys.argv[1:]:
        out = None
        if "--autotune-out" in sys.argv[1:]:
            i = sys.argv.index("--autotune-out") + 1
            if i >= len(sys.argv) or sys.argv[i].startswith("--"):
                sys.exit("--autotune-out requires a path argument")
            out = sys.argv[i]
        bench_autotune(quick="--autotune-quick" in sys.argv[1:],
                       out_path=out)
        return
    # Median-of-5 full measurements after one discarded warm-up run:
    # this host's run-to-run spread was measured at ~9.4% over
    # median-of-3 (BENCH_r05), which is noise the channel sweep's
    # comparisons cannot afford. The warm-up run pays the first-touch /
    # page-cache / cpufreq transients once, outside the sample; five
    # samples tighten the median's own variance. `spread` =
    # (max - min) / median — readers (and the round-over-round diff)
    # see the remaining noise floor next to the number.
    # --metrics: include a per-op metrics digest (calls, bytes, p50/p95
    # latency from the native registry's histograms) from the last run's
    # rank-0 context in the JSON line. Opt-in so the headline number's
    # methodology is untouched by default.
    with_metrics = "--metrics" in sys.argv[1:]
    metrics_out = [] if with_metrics else None
    warmup = bench_ours()
    print(f"[bench] warm-up run: {warmup:.3f} GB/s (discarded)",
          file=sys.stderr)
    runs = []
    for i in range(5):
        # Only the final run collects metrics (digest matches the last
        # measurement rather than mixing contexts).
        collect = metrics_out if with_metrics and i == 4 else None
        runs.append(bench_ours(collect))
    runs = sorted(runs)
    ours = runs[2]
    spread = (runs[-1] - runs[0]) / ours if ours > 0 else 0.0
    print(f"[bench] five runs: {[round(r, 3) for r in runs]} GB/s "
          f"(spread {spread:.1%})", file=sys.stderr)
    ref = bench_reference()
    if ref is None:
        ref = RECORDED_REFERENCE_GBPS
        print(f"[bench] reference build absent; using recorded baseline "
              f"{ref} GB/s", file=sys.stderr)
    line = {
        "metric": "allreduce_algbw_2rank_64MiB_host",
        "value": round(ours, 3),
        "unit": "GB/s",
        "vs_baseline": round(ours / ref, 3),
        "spread": round(spread, 3),
        "runs": [round(r, 3) for r in runs],
        "pinned": PIN_RANKS,
    }
    if with_metrics and metrics_out:
        from gloo_tpu.utils.metrics import summarize_ops

        line["metrics"] = summarize_ops(metrics_out[0])
    print(json.dumps(line))


if __name__ == "__main__":
    main()
